"""Inference tests (reference: tests/unit/inference/test_inference.py —
parity with vanilla HF pipeline outputs across models × dtype × TP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import comm


def _tiny_gpt2():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    cfg = GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    return GPT2LMHeadModel(cfg).eval()


def _tiny_llama():
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    return LlamaForCausalLM(cfg).eval()


class TestHFConversion:
    @pytest.mark.parametrize("maker", [_tiny_gpt2, _tiny_llama], ids=["gpt2", "llama"])
    def test_logits_parity_with_hf(self, maker):
        import torch

        hf = maker()
        from deepspeed_tpu.module_inject.policies import convert_hf_model
        from deepspeed_tpu.models.transformer import TransformerModel

        cfg, params = convert_hf_model(hf)
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_policy_dispatch_unknown(self):
        from deepspeed_tpu.module_inject.policies import policy_for

        class FakeCfg:
            architectures = ["T5ForConditionalGeneration"]
            model_type = "t5"

        with pytest.raises(ValueError, match="no injection policy"):
            policy_for(FakeCfg())


class TestKVCache:
    def test_cached_forward_matches_full(self):
        from deepspeed_tpu.models.transformer import (
            TransformerConfig, TransformerModel, forward_with_cache, init_cache,
        )

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                num_kv_heads=2, max_seq_len=32, pos_embedding="rope",
                                norm_type="rmsnorm", activation="silu_glu", use_bias=False,
                                tie_embeddings=False)
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 12)), jnp.int32)

        full = model.apply(params, tokens)

        cache = init_cache(cfg, 2, 32)
        logits_p, cache = forward_with_cache(params, cfg, tokens[:, :8], cache, 0)
        # decode the remaining 4 tokens one by one
        outs = [logits_p]
        for i in range(8, 12):
            step, cache = forward_with_cache(params, cfg, tokens[:, i:i + 1], cache, i)
            outs.append(step)
        cached = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(cached), np.asarray(full), rtol=2e-4, atol=2e-4)


class TestInferenceEngine:
    def test_generate_greedy_matches_hf(self):
        import torch

        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        hf = _tiny_gpt2()
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.module_inject.policies import convert_hf_model
        from deepspeed_tpu.models.transformer import TransformerModel

        cfg, params = convert_hf_model(hf)
        engine = init_inference(TransformerModel(cfg), config={"dtype": "float32"},
                                params=jax.tree.map(jnp.asarray, params))
        prompt = np.random.RandomState(1).randint(0, 128, (1, 8)).astype(np.int64)
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
                              pad_token_id=0).numpy()
        ours = np.asarray(engine.generate(prompt, max_new_tokens=8, temperature=0.0))
        np.testing.assert_array_equal(ours, ref)

    def test_tensor_parallel_generate(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": 2, "tensor": 4}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32")
        engine = init_inference(TransformerModel(cfg), config={"dtype": "float32",
                                                               "tensor_parallel": {"tp_size": 4}})
        # qkv weights sharded over tensor axis
        assert "tensor" in str(engine.params["layers"]["attn"]["wq"].sharding.spec)
        prompt = np.random.RandomState(0).randint(0, 64, (2, 8))
        out = engine.generate(prompt, max_new_tokens=4)
        assert out.shape == (2, 12)

    def test_int8_weight_quant_path(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32")
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        fp = init_inference(model, config={"dtype": "float32"}, params=params)
        q8 = init_inference(model, config={"dtype": "int8"}, params=params)
        prompt = np.random.RandomState(0).randint(0, 64, (1, 8))
        lf = np.asarray(fp.forward(prompt))
        lq = np.asarray(q8.forward(prompt)).astype(np.float32)
        # int8 weight quantization should stay close to fp32 logits
        assert np.mean(np.abs(lf - lq)) < 0.35

    def test_config_compat_mp_size(self):
        from deepspeed_tpu.inference.config import InferenceConfig

        c = InferenceConfig.parse({"mp_size": 4, "dtype": "float16"})
        assert c.tensor_parallel.tp_size == 4
        assert c.dtype == "float16"


class TestTopLevelAPI:
    def test_package_init_inference(self):
        """deepspeed_tpu.init_inference must forward params/mesh and accept
        reference-style kwargs (regression: a broken duplicate once shadowed
        the working definition)."""
        import deepspeed_tpu
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel
        import jax

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
                                max_seq_len=16, dtype="float32")
        model = TransformerModel(cfg)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        engine = deepspeed_tpu.init_inference(model, dtype="float32", params=params)
        out = engine(jnp.zeros((1, 4), jnp.int32))
        assert out.shape == (1, 4, 64)
        # params= must actually reach the engine (not be swallowed into config)
        imported = engine.params["embed"]["tok"]
        np.testing.assert_allclose(np.asarray(imported), np.asarray(params["embed"]["tok"]))

    def test_eos_truncation(self):
        """generate(eos_token_id=...) must not crash on the read-only host
        buffer (regression) and must pad past-eos positions."""
        from deepspeed_tpu.inference.engine import InferenceEngine

        tokens = jnp.array([[5, 6, 7, 2, 9, 9], [5, 6, 7, 8, 9, 9]], jnp.int32)
        out = InferenceEngine._truncate_eos(tokens, prompt_len=3, eos_id=2)
        assert list(np.asarray(out[0])) == [5, 6, 7, 2, 2, 2]
        assert list(np.asarray(out[1])) == [5, 6, 7, 8, 9, 9]
