"""Inference tests (reference: tests/unit/inference/test_inference.py —
parity with vanilla HF pipeline outputs across models × dtype × TP)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import comm


def _tiny_gpt2():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    cfg = GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    return GPT2LMHeadModel(cfg).eval()


def _tiny_llama():
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False,
    )
    return LlamaForCausalLM(cfg).eval()


def _tiny_mistral():
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(0)
    cfg = MistralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        sliding_window=8,  # < the test prompt length, so the window matters
        attn_implementation="eager",
    )
    return MistralForCausalLM(cfg).eval()


def _tiny_opt(post_ln=False):
    import torch
    from transformers import OPTConfig, OPTForCausalLM

    torch.manual_seed(0)
    cfg = OPTConfig(
        vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64, word_embed_proj_dim=32,
        do_layer_norm_before=not post_ln, dropout=0.0, attention_dropout=0.0,
        activation_function="relu",
    )
    return OPTForCausalLM(cfg).eval()


def _tiny_opt_postln():
    return _tiny_opt(post_ln=True)


def _tiny_bloom():
    import torch
    from transformers import BloomConfig, BloomForCausalLM

    torch.manual_seed(0)
    cfg = BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    return BloomForCausalLM(cfg).eval()


def _tiny_neox():
    import torch
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    torch.manual_seed(0)
    cfg = GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64, rotary_pct=0.5,
        use_parallel_residual=True, hidden_dropout=0.0, attention_dropout=0.0,
    )
    return GPTNeoXForCausalLM(cfg).eval()


def _tiny_gptj():
    import torch
    from transformers import GPTJConfig, GPTJForCausalLM

    torch.manual_seed(0)
    cfg = GPTJConfig(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4, rotary_dim=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    return GPTJForCausalLM(cfg).eval()


class TestHFConversion:
    @pytest.mark.parametrize(
        "maker",
        [_tiny_gpt2, _tiny_llama, _tiny_mistral, _tiny_opt, _tiny_opt_postln, _tiny_bloom, _tiny_neox, _tiny_gptj],
        ids=["gpt2", "llama", "mistral", "opt", "opt-350m-postln", "bloom", "gptneox", "gptj"],
    )
    def test_logits_parity_with_hf(self, maker):
        import torch

        hf = maker()
        from deepspeed_tpu.module_inject.policies import convert_hf_model
        from deepspeed_tpu.models.transformer import TransformerModel

        cfg, params = convert_hf_model(hf)
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_bert_hidden_state_parity(self):
        """BERT policy: encoder last_hidden_state parity (the reference
        injects encoder layers; heads stay outside, replace_policy.py:20)."""
        import torch
        from transformers import BertConfig, BertModel

        torch.manual_seed(0)
        hf = BertModel(
            BertConfig(
                vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                num_attention_heads=4, max_position_embeddings=64, type_vocab_size=2,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            ),
            add_pooling_layer=False,
        ).eval()
        from deepspeed_tpu.models.transformer import encode
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        rs = np.random.RandomState(0)
        tokens = rs.randint(0, 128, (2, 16)).astype(np.int64)
        types = rs.randint(0, 2, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens), token_type_ids=torch.from_numpy(types)).last_hidden_state.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(
            encode(params, cfg, jnp.asarray(tokens, jnp.int32), token_types=jnp.asarray(types, jnp.int32))
        )
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_sharded_checkpoint_loading(self, tmp_path):
        """Sharded HF checkpoint converts shard-by-shard with bounded cache
        (reference: module_inject/load_checkpoint.py:255) and matches the
        in-memory conversion exactly."""
        hf = _tiny_gpt2()
        ckpt = str(tmp_path / "ckpt")
        hf.save_pretrained(ckpt, max_shard_size="30kB", safe_serialization=True)

        from deepspeed_tpu.module_inject.load_checkpoint import ShardedStateDict, convert_hf_checkpoint
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        state = ShardedStateDict(ckpt, cache_shards=1)
        n_shards = len(set(state.weight_map.values()))
        assert n_shards > 1, "tiny model did not shard; lower max_shard_size"

        cfg_s, params_s = convert_hf_checkpoint(ckpt, cache_shards=1)
        cfg_m, params_m = convert_hf_model(hf)
        assert cfg_s == cfg_m
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params_s), jax.tree_util.tree_leaves_with_path(params_m)
        ):
            assert pa == pb
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sharded_loader_cache_bounded(self, tmp_path):
        hf = _tiny_gpt2()
        ckpt = str(tmp_path / "ckpt")
        hf.save_pretrained(ckpt, max_shard_size="30kB", safe_serialization=True)
        from deepspeed_tpu.module_inject.load_checkpoint import ShardedStateDict

        state = ShardedStateDict(ckpt, cache_shards=1)
        for k in state.keys():
            _ = state[k]
        assert len(state._cache) == 1  # never more than cache_shards resident

    def test_init_inference_from_checkpoint_path(self, tmp_path):
        """init_inference auto-dispatches a checkpoint dir through the
        sharded loader + policy (reference inference/engine.py:338)."""
        import deepspeed_tpu

        hf = _tiny_gpt2()
        ckpt = str(tmp_path / "ckpt")
        hf.save_pretrained(ckpt, max_shard_size="30kB", safe_serialization=True)
        engine = deepspeed_tpu.init_inference(ckpt, config={"dtype": "float32"})
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (1, 8)), jnp.int32)
        out = engine.generate(tokens, max_new_tokens=4)
        assert out.shape == (1, 12)

    def test_policy_dispatch_unknown(self):
        from deepspeed_tpu.module_inject.policies import policy_for

        class FakeCfg:
            architectures = ["T5ForConditionalGeneration"]
            model_type = "t5"

        with pytest.raises(ValueError, match="no injection policy"):
            policy_for(FakeCfg())


class TestKVCache:
    def test_cached_forward_matches_full(self):
        from deepspeed_tpu.models.transformer import (
            TransformerConfig, TransformerModel, forward_with_cache, init_cache,
        )

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                num_kv_heads=2, max_seq_len=32, pos_embedding="rope",
                                norm_type="rmsnorm", activation="silu_glu", use_bias=False,
                                tie_embeddings=False)
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 12)), jnp.int32)

        full = model.apply(params, tokens)

        cache = init_cache(cfg, 2, 32)
        logits_p, cache = forward_with_cache(params, cfg, tokens[:, :8], cache, 0)
        # decode the remaining 4 tokens one by one
        outs = [logits_p]
        for i in range(8, 12):
            step, cache = forward_with_cache(params, cfg, tokens[:, i:i + 1], cache, i)
            outs.append(step)
        cached = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(cached), np.asarray(full), rtol=2e-4, atol=2e-4)


class TestInferenceEngine:
    def test_generate_greedy_matches_hf(self):
        import torch

        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        hf = _tiny_gpt2()
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.module_inject.policies import convert_hf_model
        from deepspeed_tpu.models.transformer import TransformerModel

        cfg, params = convert_hf_model(hf)
        engine = init_inference(TransformerModel(cfg), config={"dtype": "float32"},
                                params=jax.tree.map(jnp.asarray, params))
        prompt = np.random.RandomState(1).randint(0, 128, (1, 8)).astype(np.int64)
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
                              pad_token_id=0).numpy()
        ours = np.asarray(engine.generate(prompt, max_new_tokens=8, temperature=0.0))
        np.testing.assert_array_equal(ours, ref)

    def test_tensor_parallel_generate(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": 2, "tensor": 4}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32")
        engine = init_inference(TransformerModel(cfg), config={"dtype": "float32",
                                                               "tensor_parallel": {"tp_size": 4}})
        # qkv weights sharded over tensor axis
        assert "tensor" in str(engine.params["layers"]["attn"]["wq"].sharding.spec)
        prompt = np.random.RandomState(0).randint(0, 64, (2, 8))
        out = engine.generate(prompt, max_new_tokens=4)
        assert out.shape == (2, 12)

    def test_int8_weight_quant_path(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32")
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        fp = init_inference(model, config={"dtype": "float32"}, params=params)
        q8 = init_inference(model, config={"dtype": "int8"}, params=params)
        prompt = np.random.RandomState(0).randint(0, 64, (1, 8))
        lf = np.asarray(fp.forward(prompt))
        lq = np.asarray(q8.forward(prompt)).astype(np.float32)
        # int8 weight quantization should stay close to fp32 logits
        assert np.mean(np.abs(lf - lq)) < 0.35

    def test_config_compat_mp_size(self):
        from deepspeed_tpu.inference.config import InferenceConfig

        c = InferenceConfig.parse({"mp_size": 4, "dtype": "float16"})
        assert c.tensor_parallel.tp_size == 4
        assert c.dtype == "float16"

    def test_fused_generate_matches_per_token_loop(self):
        """The fused whole-generation jit (fused_generate=True, the default)
        must emit the SAME token stream as the per-token dispatch loop —
        greedy and sampled (identical rng split order by construction)."""
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32")
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        fused = init_inference(model, config={"dtype": "float32"}, params=params)
        loop = init_inference(model, config={"dtype": "float32",
                                             "fused_generate": False}, params=params)
        assert fused.config.fused_generate and not loop.config.fused_generate
        prompt = np.random.RandomState(0).randint(0, 64, (2, 8))
        for kwargs in ({"temperature": 0.0},
                       {"temperature": 0.8, "top_k": 8, "top_p": 0.9,
                        "rng": jax.random.PRNGKey(7)}):
            a = np.asarray(fused.generate(prompt, max_new_tokens=6, **kwargs))
            b = np.asarray(loop.generate(prompt, max_new_tokens=6, **kwargs))
            np.testing.assert_array_equal(a, b)
        # single-token edge: scan length 0
        a = np.asarray(fused.generate(prompt, max_new_tokens=1))
        assert a.shape == (2, 9)
        # zero-token edge: prompt returned unchanged (decode_loop contract)
        z = np.asarray(fused.generate(prompt, max_new_tokens=0))
        np.testing.assert_array_equal(z, prompt)


class TestSampling:
    def test_top_p_restricts_support(self):
        """Nucleus sampling must only ever emit tokens from the smallest
        prefix of the sorted distribution with cumulative mass >= p."""
        from deepspeed_tpu.inference.decoding import select_token

        # one peaked distribution: token 0 has ~0.97 mass
        logits = jnp.asarray([[8.0, 2.0, 1.0, 0.0, -1.0]])
        draws = {
            int(select_token(logits, 1.0, 0, jax.random.PRNGKey(i), top_p=0.5)[0])
            for i in range(50)
        }
        assert draws == {0}  # only the top token is inside the 0.5 nucleus

    def test_top_p_one_is_plain_sampling(self):
        from deepspeed_tpu.inference.decoding import select_token

        logits = jnp.asarray([[0.0, 0.0, 0.0, 0.0]])
        draws = {
            int(select_token(logits, 1.0, 0, jax.random.PRNGKey(i), top_p=1.0)[0])
            for i in range(60)
        }
        assert len(draws) > 1  # uniform distribution stays unrestricted

    def test_generate_with_top_p(self):
        import deepspeed_tpu
        from deepspeed_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2, max_seq_len=32
        )
        engine = deepspeed_tpu.init_inference(cfg, config={"dtype": "float32"})
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 4)), jnp.int32)
        out = engine.generate(
            tokens, max_new_tokens=4, temperature=0.8, top_p=0.9,
            rng=jax.random.PRNGKey(0),
        )
        assert out.shape == (1, 8)


class TestTopLevelAPI:
    def test_package_init_inference(self):
        """deepspeed_tpu.init_inference must forward params/mesh and accept
        reference-style kwargs (regression: a broken duplicate once shadowed
        the working definition)."""
        import deepspeed_tpu
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel
        import jax

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
                                max_seq_len=16, dtype="float32")
        model = TransformerModel(cfg)
        params = jax.jit(model.init)(jax.random.PRNGKey(0))
        engine = deepspeed_tpu.init_inference(model, dtype="float32", params=params)
        out = engine(jnp.zeros((1, 4), jnp.int32))
        assert out.shape == (1, 4, 64)
        # params= must actually reach the engine (not be swallowed into config)
        imported = engine.params["embed"]["tok"]
        np.testing.assert_allclose(np.asarray(imported), np.asarray(params["embed"]["tok"]))

    def test_eos_truncation(self):
        """generate(eos_token_id=...) must not crash on the read-only host
        buffer (regression) and must pad past-eos positions."""
        from deepspeed_tpu.inference.engine import InferenceEngine

        tokens = jnp.array([[5, 6, 7, 2, 9, 9], [5, 6, 7, 8, 9, 9]], jnp.int32)
        out = InferenceEngine._truncate_eos(tokens, prompt_len=3, eos_id=2)
        assert list(np.asarray(out[0])) == [5, 6, 7, 2, 2, 2]
        assert list(np.asarray(out[1])) == [5, 6, 7, 8, 9, 9]


class TestRealInt8:
    """dtype="int8" must mean REAL int8 storage (HBM bandwidth halves), not
    fake-quant numerics in bf16."""

    def test_weights_stored_int8(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                                max_seq_len=64, dtype="float32", tie_embeddings=False)
        eng = init_inference(TransformerModel(cfg), config={"dtype": "int8"})
        leaves = jax.tree_util.tree_leaves_with_path(eng.params)
        q8 = {jax.tree_util.keystr(p) for p, l in leaves if l.dtype == jnp.int8}
        # every attn/mlp matmul weight and the untied lm head must be int8
        for want in ("wq", "wk", "wv", "wo", "wi", "'w'"):
            assert any(want in k and "q8" in k for k in q8), (want, sorted(q8))
        # each q8 has a float32 scale sibling
        scales = {jax.tree_util.keystr(p) for p, l in leaves
                  if l.dtype == jnp.float32 and "'s'" in jax.tree_util.keystr(p)}
        assert len(scales) == len(q8)
        # embeddings / norms / biases stay float
        assert any("embed" in jax.tree_util.keystr(p) and l.dtype != jnp.int8 for p, l in leaves)

        # generate must run on the quantized tree end to end
        out = eng.generate(np.random.RandomState(0).randint(0, 64, (2, 6)), max_new_tokens=4)
        arr = np.asarray(out)
        assert arr.shape == (2, 10) and (arr >= 0).all() and (arr < 64).all()

    def test_int8_linear_matches_dequant_matmul(self):
        from deepspeed_tpu.ops.quantizer import int8_linear

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(4, 16), jnp.float32)
        w = jnp.asarray(rs.randn(16, 8), jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(w), axis=0, keepdims=True) / 127.0, 1e-12)
        q8 = jnp.clip(jnp.round(w / s), -128, 127).astype(jnp.int8)
        got = np.asarray(int8_linear(x, q8, s))
        # reference: dequantized weight matmul with exactly-quantized activations
        sx = jnp.maximum(jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0, 1e-12)
        xq = jnp.round(x / sx)
        want = np.asarray((xq * sx) @ (q8.astype(jnp.float32) * s))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        # and close to the unquantized product (W8A8 error ~ 1/127 per factor)
        np.testing.assert_allclose(got, np.asarray(x @ w), atol=0.15)


class TestNewPolicies:
    def test_distilbert_hidden_state_parity(self):
        import torch
        from transformers import DistilBertConfig, DistilBertModel

        torch.manual_seed(0)
        hf = DistilBertModel(DistilBertConfig(
            vocab_size=128, dim=32, hidden_dim=64, n_layers=2, n_heads=4,
            max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        )).eval()
        from deepspeed_tpu.models.transformer import encode
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        rs = np.random.RandomState(0)
        tokens = rs.randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).last_hidden_state.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(encode(params, cfg, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_gptneo_logits_parity(self):
        """GPT-Neo: separate bias-free q/k/v Linears, UNSCALED attention,
        and global/local layer alternation — seq > window so the local mask
        actually bites (reference: containers/gptneo.py)."""
        import torch
        from transformers import GPTNeoConfig, GPTNeoForCausalLM

        torch.manual_seed(0)
        hf = GPTNeoForCausalLM(GPTNeoConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            attention_types=[[["global", "local"], 1]], window_size=4,
            max_position_embeddings=64, intermediate_size=64,
            resid_dropout=0.0, embed_dropout=0.0, attention_dropout=0.0,
        )).eval()
        from deepspeed_tpu.models.transformer import TransformerModel
        from deepspeed_tpu.module_inject.policies import GPTNeoPolicy, convert_hf_model, policy_for

        assert isinstance(policy_for(hf.config), GPTNeoPolicy)
        cfg, params = convert_hf_model(hf)
        assert cfg.attn_scale == 1.0
        assert cfg.local_attn_windows == (0, 4)
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_gptneo_greedy_decode_parity(self):
        """The cached decode path must honor the per-layer local windows:
        greedy generate matches HF token-for-token past the window size."""
        import torch
        from transformers import GPTNeoConfig, GPTNeoForCausalLM

        torch.manual_seed(0)
        hf = GPTNeoForCausalLM(GPTNeoConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            attention_types=[[["global", "local"], 1]], window_size=4,
            max_position_embeddings=64, intermediate_size=64,
            resid_dropout=0.0, embed_dropout=0.0, attention_dropout=0.0,
        )).eval()
        import deepspeed_tpu
        from deepspeed_tpu.models.transformer import TransformerModel
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        engine = deepspeed_tpu.init_inference(
            TransformerModel(cfg), config={"dtype": "float32"}, params=params
        )
        prompt = np.random.RandomState(1).randint(0, 128, (2, 7)).astype(np.int64)
        with torch.no_grad():
            ref = hf.generate(
                torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
                pad_token_id=0,
            ).numpy()
        ours = np.asarray(engine.generate(prompt.astype(np.int32), max_new_tokens=8))
        np.testing.assert_array_equal(ours, ref)

    def test_distilbert_mlm_logits_parity(self):
        """DistilBertForMaskedLM: vocab_transform + vocab_layer_norm +
        projector bias must fold into the exported head (_vocab_head) —
        tied-embedding-only projection deviates from HF numerics."""
        import torch
        from transformers import DistilBertConfig, DistilBertForMaskedLM

        torch.manual_seed(0)
        hf = DistilBertForMaskedLM(DistilBertConfig(
            vocab_size=128, dim=32, hidden_dim=64, n_layers=2, n_heads=4,
            max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        )).eval()
        from deepspeed_tpu.models.transformer import TransformerModel
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        assert "mlm_head" in params, "MLM head weights must be exported"
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_bert_mlm_logits_parity(self):
        """BertForMaskedLM: cls.predictions.transform + decoder bias parity."""
        import torch
        from transformers import BertConfig, BertForMaskedLM

        torch.manual_seed(0)
        hf = BertForMaskedLM(BertConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        )).eval()
        from deepspeed_tpu.models.transformer import TransformerModel
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        assert "mlm_head" in params
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_megatron_fused_qkv_split(self):
        """Synthetic megatron-format state dict: the fused query_key_value
        splits must land in the right wq/wk/wv slots for BOTH row layouts
        (reference fix_query_key_value_ordering)."""
        from deepspeed_tpu.module_inject.policies import MegatronGPTPolicy

        D, L, nh, hd, V, S = 8, 1, 2, 4, 32, 16
        rs = np.random.RandomState(0)

        class FakeCfg:
            vocab_size = V
            hidden_size = D
            num_layers = L
            num_attention_heads = nh
            max_position_embeddings = S

        def mk_state(version):
            # build fused (3D, D) torch-layout weight whose per-head q/k/v
            # blocks carry distinct constants
            wq = np.full((D, D), 1.0); wk = np.full((D, D), 2.0); wv = np.full((D, D), 3.0)
            if version >= 2:
                # rows per head: [h0q(hd) h0k h0v h1q ...] in (out, in)
                rows = []
                for h in range(nh):
                    rows += [wq.T[h * hd:(h + 1) * hd], wk.T[h * hd:(h + 1) * hd], wv.T[h * hd:(h + 1) * hd]]
                fused = np.concatenate(rows, axis=0)
            else:
                fused = np.concatenate([wq.T, wk.T, wv.T], axis=0)
            bias = np.arange(3 * D, dtype=np.float32)
            state = {
                "embedding.word_embeddings.weight": rs.randn(V, D).astype(np.float32),
                "embedding.position_embeddings.weight": rs.randn(S, D).astype(np.float32),
                "transformer.layers.0.attention.query_key_value.weight": fused.astype(np.float32),
                "transformer.layers.0.attention.query_key_value.bias": bias,
                "transformer.layers.0.attention.dense.weight": rs.randn(D, D).astype(np.float32),
                "transformer.layers.0.attention.dense.bias": np.zeros(D, np.float32),
                "transformer.layers.0.mlp.dense_h_to_4h.weight": rs.randn(4 * D, D).astype(np.float32),
                "transformer.layers.0.mlp.dense_h_to_4h.bias": np.zeros(4 * D, np.float32),
                "transformer.layers.0.mlp.dense_4h_to_h.weight": rs.randn(D, 4 * D).astype(np.float32),
                "transformer.layers.0.mlp.dense_4h_to_h.bias": np.zeros(D, np.float32),
                "transformer.layers.0.input_layernorm.weight": np.ones(D, np.float32),
                "transformer.layers.0.input_layernorm.bias": np.zeros(D, np.float32),
                "transformer.layers.0.post_attention_layernorm.weight": np.ones(D, np.float32),
                "transformer.layers.0.post_attention_layernorm.bias": np.zeros(D, np.float32),
                "transformer.final_layernorm.weight": np.ones(D, np.float32),
                "transformer.final_layernorm.bias": np.zeros(D, np.float32),
            }
            return state

        for version in (0, 2):
            policy = MegatronGPTPolicy(checkpoint_version=version)
            cfg = policy.config(FakeCfg())
            params = policy.params(mk_state(version), cfg)
            np.testing.assert_array_equal(params["layers"]["attn"]["wq"][0], np.full((D, D), 1.0))
            np.testing.assert_array_equal(params["layers"]["attn"]["wk"][0], np.full((D, D), 2.0))
            np.testing.assert_array_equal(params["layers"]["attn"]["wv"][0], np.full((D, D), 3.0))
            if version == 0:
                np.testing.assert_array_equal(params["layers"]["attn"]["bq"][0], np.arange(D))

    def test_policy_dispatch_new_archs(self):
        from deepspeed_tpu.module_inject.policies import (
            DistilBertPolicy, MegatronGPTPolicy, policy_for)

        class C1:
            architectures = ["DistilBertForMaskedLM"]
            model_type = "distilbert"

        class C2:
            architectures = ["MegatronGPT2LMHeadModel"]
            model_type = "megatron_gpt2"

        assert isinstance(policy_for(C1()), DistilBertPolicy)
        assert isinstance(policy_for(C2()), MegatronGPTPolicy)

    def test_clip_text_hidden_state_parity(self):
        import torch
        from transformers import CLIPTextConfig, CLIPTextModel

        torch.manual_seed(0)
        hf = CLIPTextModel(CLIPTextConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, hidden_act="quick_gelu",
            attention_dropout=0.0,
        )).eval()
        from deepspeed_tpu.models.transformer import encode
        from deepspeed_tpu.module_inject.policies import convert_hf_model

        cfg, params = convert_hf_model(hf)
        assert cfg.activation == "quick_gelu" and cfg.causal
        rs = np.random.RandomState(0)
        tokens = rs.randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).last_hidden_state.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(encode(params, cfg, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


class TestRaggedGenerate:
    """attention_mask generate (HF semantics): padded rows must produce the
    same continuations as each row generated alone, for both paddings."""

    def _engine(self):
        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                                max_seq_len=128, dtype="float32")
        return init_inference(TransformerModel(cfg), config={"dtype": "float32"})

    @pytest.mark.parametrize("side", [
        "left",
        # right-padding probes the same masking math; left is the hard case
        pytest.param("right", marks=pytest.mark.slow),
    ])
    def test_padding_parity(self, side):
        eng = self._engine()
        rs = np.random.RandomState(0)
        lens = [5, 9, 3]
        S = max(lens)
        rows = [rs.randint(0, 128, (n,)).astype(np.int32) for n in lens]
        toks = np.zeros((3, S), np.int32)
        mask = np.zeros((3, S), np.float32)
        for b, r in enumerate(rows):
            if side == "left":
                toks[b, S - lens[b]:] = r
                mask[b, S - lens[b]:] = 1
            else:
                toks[b, :lens[b]] = r
                mask[b, :lens[b]] = 1
        out = np.asarray(eng.generate(toks, max_new_tokens=8, attention_mask=mask))
        assert out.shape == (3, S + 8)
        for b, r in enumerate(rows):
            solo = np.asarray(eng.generate(r[None, :], max_new_tokens=8))
            np.testing.assert_array_equal(out[b, S:], solo[0, lens[b]:],
                                          err_msg=f"row {b} ({side} padding)")

    def test_full_mask_matches_plain(self):
        eng = self._engine()
        rs = np.random.RandomState(1)
        toks = rs.randint(0, 128, (2, 7)).astype(np.int32)
        plain = np.asarray(eng.generate(toks, max_new_tokens=6))
        ragged = np.asarray(eng.generate(toks, max_new_tokens=6,
                                         attention_mask=np.ones((2, 7), np.float32)))
        np.testing.assert_array_equal(plain, ragged)

    def test_max_length_padding_allowed(self):
        """padding='max_length' batches (padded width == max_seq_len) are
        legal when the real prompts + new tokens fit."""
        eng = self._engine()
        S = eng.cfg.max_seq_len  # 128
        toks = np.zeros((2, S), np.int32)
        mask = np.zeros((2, S), np.float32)
        rs = np.random.RandomState(2)
        toks[0, S - 6:] = rs.randint(0, 128, 6)
        mask[0, S - 6:] = 1
        toks[1, S - 3:] = rs.randint(0, 128, 3)
        mask[1, S - 3:] = 1
        out = np.asarray(eng.generate(toks, max_new_tokens=4, attention_mask=mask))
        assert out.shape == (2, S + 4)


class TestFlashPrefill:
    def test_pallas_prefill_matches_xla(self):
        """attn_impl=pallas routes inference PREFILL through the flash
        kernel (no (B,H,S,T) logits materialization); greedy decode output
        must match the einsum path exactly."""
        import dataclasses

        comm.destroy()
        comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                                max_seq_len=128, dtype="float32", pos_embedding="rope")
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        xla = init_inference(model, config={"dtype": "float32"}, params=params)
        pallas = init_inference(
            TransformerModel(dataclasses.replace(cfg, attn_impl="pallas")),
            config={"dtype": "float32"}, params=params)
        prompt = np.random.RandomState(0).randint(0, 128, (2, 32)).astype(np.int32)
        a = np.asarray(xla.generate(prompt, max_new_tokens=8))
        b = np.asarray(pallas.generate(prompt, max_new_tokens=8))
        np.testing.assert_array_equal(a, b)

    def test_pallas_prefill_odd_length_falls_back(self):
        """Prompt lengths that don't tile by 128 must stay on the einsum
        path instead of failing at trace time."""
        import dataclasses

        from deepspeed_tpu.inference.engine import init_inference
        from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=1, num_heads=4,
                                max_seq_len=256, dtype="float32")
        model = TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        xla = init_inference(model, config={"dtype": "float32"}, params=params)
        pallas = init_inference(
            TransformerModel(dataclasses.replace(cfg, attn_impl="pallas")),
            config={"dtype": "float32"}, params=params)
        prompt = np.random.RandomState(0).randint(0, 128, (1, 200)).astype(np.int32)
        a = np.asarray(xla.generate(prompt, max_new_tokens=4))
        b = np.asarray(pallas.generate(prompt, max_new_tokens=4))
        np.testing.assert_array_equal(a, b)


def _tiny_qwen2():
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(0)
    cfg = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        tie_word_embeddings=False, attention_dropout=0.0,
    )
    return Qwen2ForCausalLM(cfg).eval()


def _tiny_stablelm():
    import torch
    from transformers import StableLmConfig, StableLmForCausalLM

    torch.manual_seed(0)
    cfg = StableLmConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=64,
        partial_rotary_factor=0.5, attention_dropout=0.0, hidden_dropout=0.0,
        tie_word_embeddings=False,
    )
    return StableLmForCausalLM(cfg).eval()


class TestAutoTPFallback:
    """Generic AutoTP fallback policy (VERDICT r3 #9; reference
    module_inject/auto_tp.py): archs with NO explicit policy entry convert
    via name/shape heuristics. Qwen2 (GQA + qkv-bias + silu-glu + rms) and
    StableLM (partial rotary + layernorm) are deliberately NOT in POLICIES."""

    @pytest.mark.parametrize("maker", [_tiny_qwen2, _tiny_stablelm],
                             ids=["qwen2", "stablelm"])
    def test_logits_parity_unknown_arch(self, maker):
        import torch

        hf = maker()
        from deepspeed_tpu.module_inject.policies import convert_hf_model, policy_for
        from deepspeed_tpu.models.transformer import TransformerModel

        with pytest.raises(ValueError):
            policy_for(hf.config)  # really not in the explicit list
        cfg, params = convert_hf_model(hf)
        model = TransformerModel(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = jax.tree.map(jnp.asarray, params)
        ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
        np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)

    def test_auto_converted_model_runs_tp_inference(self):
        """The fallback-converted model must drive the full inference
        engine under TP=2 (the point of AutoTP: shard anything)."""
        import deepspeed_tpu

        comm.destroy()
        hf = _tiny_qwen2()
        from deepspeed_tpu.module_inject.policies import convert_hf_model
        from deepspeed_tpu.models.transformer import TransformerModel

        cfg, params = convert_hf_model(hf)
        engine = deepspeed_tpu.init_inference(
            TransformerModel(cfg), params=params,
            config={"dtype": "float32", "tensor_parallel": {"tp_size": 2},
                    "mesh": {"data": 4, "tensor": 2}},
        )
        prompts = np.random.RandomState(0).randint(0, 128, (2, 8)).astype(np.int32)
        out = engine.generate(prompts, max_new_tokens=4)
        assert np.asarray(out).shape == (2, 12)
