"""Multi-chip tensor-parallel serving (parallel/partition.py + the
InferenceConfig ``mesh`` block): regex partition rules, subset serving
meshes over the virtual 8-CPU-device host, and the acceptance invariant —
the tensor width may change WHERE the math runs, never WHAT tokens come
out. Token streams are bitwise identical sharded (tensor 2/4) vs
single-chip, greedy AND sampled, across pipeline depths, fused/separate
prefill, bucket migration, and prefix splice; ``kv_bytes_read`` becomes
exact PER-CHIP bytes under a sharded cache."""

import json

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu import comm
from deepspeed_tpu.inference.config import InferenceConfig, MeshConfig
from deepspeed_tpu.inference.continuous import ContinuousBatchingEngine
from deepspeed_tpu.inference.decoding import decode_kv_bytes, read_bucket
from deepspeed_tpu.models.transformer import (
    TransformerConfig,
    TransformerModel,
    kv_read_bytes_per_row,
)
from deepspeed_tpu.parallel.partition import (
    DEFAULT_RULES,
    kv_shard_width,
    match_partition_rules,
    parse_mesh_arg,
    partition_params,
    serving_mesh,
)

FLOOR = 16  # small tight-read floor so tiny pools cross read buckets


@pytest.fixture(scope="module")
def setup():
    comm.destroy()
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=128, dtype="float32")
    model = TransformerModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _prompts(ns, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).astype(np.int32) for n in ns]


def _cb(setup, tensor=None, **kw):
    """Continuous engine, optionally on a 1xTENSOR serving mesh.
    Donation stays OFF: the CPU backend implements donation by blocking
    at dispatch (docs/serving.md caveat), and parity across pipeline
    depths is exactly what these tests sweep."""
    model, params = setup
    cfg = {"dtype": "float32", "kv_read_floor": FLOOR}
    if tensor is not None:
        cfg["mesh"] = {"shape": {"data": 1, "tensor": tensor}}
    cfg.update(kw.pop("config", {}))
    kw.setdefault("max_slots", 3)
    kw.setdefault("cache_len", 64)
    kw.setdefault("donate_cache", False)
    return ContinuousBatchingEngine(model, params=params, config=cfg, **kw)


def _serve(cb, submissions, max_ticks=400):
    """Drive ``cb`` over [(tick, prompt, max_new)]; returns the finished
    arrays in submission order."""
    results = {}
    pending = list(submissions)
    rid_of = {}
    tick = 0
    while pending or cb.has_work():
        assert tick < max_ticks, "scheduler did not drain"
        for item in [s for s in pending if s[0] <= tick]:
            rid_of[id(item)] = cb.submit(item[1], max_new_tokens=item[2])
        pending = [s for s in pending if s[0] > tick]
        cb.step()
        results.update(cb.finished())
        tick += 1
    return [results[rid_of[id(s)]] for s in submissions]


class TestPartitionRules:
    def test_first_match_wins_and_scalars_replicate(self):
        params = {"attn": {"wq": np.zeros((8, 8)), "scale": np.zeros(())},
                  "mlp": {"wi": np.zeros((8, 16))}}
        rules = [(r"attn/wq", PartitionSpec(None, "tensor")),
                 (r"attn", PartitionSpec("tensor")),  # never reached for wq
                 (r".*", PartitionSpec())]
        specs = match_partition_rules(rules, params)
        assert specs["attn"]["wq"] == PartitionSpec(None, "tensor")
        assert specs["attn"]["scale"] == PartitionSpec()  # scalar
        assert specs["mlp"]["wi"] == PartitionSpec()      # catch-all

    def test_unmatched_param_raises_by_default(self):
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules([(r"attn", PartitionSpec())],
                                  {"mlp": {"wi": np.zeros((4, 4))}})
        specs = match_partition_rules([(r"attn", PartitionSpec())],
                                      {"mlp": {"wi": np.zeros((4, 4))}},
                                      on_miss="replicate")
        assert specs["mlp"]["wi"] == PartitionSpec()

    def test_json_rule_form_and_stacked_layers_dim(self):
        # config-file rule shape: [regex, [axis|None, ...]]; a stacked
        # layers/ leaf gets None prepended for the scan dim
        params = {"layers": {"attn": {"wq": np.zeros((3, 8, 8))}}}
        specs = match_partition_rules(
            [["attn/wq", [None, "tensor"]], [".*", []]], params)
        assert specs["layers"]["attn"]["wq"] == PartitionSpec(None, None, "tensor")

    def test_specs_align_to_trailing_dims(self):
        # rules name a weight's TRAILING (matmul) dims: a stacked MoE wi
        # (layers, expert, embed, mlp) must land "tensor" on mlp hidden,
        # never on the expert dim a trailing pad would hit
        params = {"layers": {"mlp": {"wi": np.zeros((4, 8, 16, 32)),
                                     "wo": np.zeros((4, 8, 32, 16))}}}
        specs = match_partition_rules(DEFAULT_RULES, params)
        assert specs["layers"]["mlp"]["wi"] == \
            PartitionSpec(None, None, None, "tensor")
        assert specs["layers"]["mlp"]["wo"] == \
            PartitionSpec(None, None, "tensor", None)

    def test_partition_params_clips_non_divisible_dims(self):
        mesh = serving_mesh(1, 2)
        params = {"attn": {"wq": np.zeros((8, 8)), "wk": np.zeros((8, 3))}}
        sh = partition_params(mesh, params,
                              rules=[[r"attn/w[qk]$", [None, "tensor"]]])
        assert sh["attn"]["wq"].spec == PartitionSpec(None, "tensor")
        # 3 doesn't divide over tensor=2: the weight replicates instead
        # of raising — per-weight fallback, the rest stays sharded
        assert sh["attn"]["wk"].spec == PartitionSpec(None, None)

    def test_default_rules_cover_builtin_naming(self, setup):
        model, params = setup
        specs = match_partition_rules(DEFAULT_RULES, params)
        assert specs["layers"]["attn"]["wq"] == PartitionSpec(None, None, "tensor")
        assert specs["layers"]["mlp"]["wo"] == PartitionSpec(None, "tensor", None)
        assert specs["embed"]["tok"] == PartitionSpec("tensor", None)
        assert specs["layers"]["ln1"]["scale"] == PartitionSpec()

    def test_module_inject_exports_family_rules(self):
        from deepspeed_tpu.module_inject import partition_rules

        table = partition_rules()
        assert table[-len(DEFAULT_RULES):] == tuple(DEFAULT_RULES)

    def test_parse_mesh_arg_forms(self):
        assert parse_mesh_arg("1:2") == {"data": 1, "tensor": 2}
        assert parse_mesh_arg("data=2,tensor=4") == {"data": 2, "tensor": 4}
        with pytest.raises(ValueError):
            parse_mesh_arg("3")

    def test_serving_mesh_subset_and_bounds(self):
        mesh = serving_mesh(1, 2)
        assert mesh.shape["tensor"] == 2 and mesh.devices.size == 2
        with pytest.raises(ValueError, match="devices"):
            serving_mesh(4, 4)  # 16 > the 8 virtual devices


class TestMeshConfig:
    def test_plain_dict_is_shape_and_block_form_parses(self):
        old = InferenceConfig.parse({"mesh": {"data": 1, "tensor": 2}})
        assert old.mesh.shape == {"data": 1, "tensor": 2}
        assert old.mesh.rules is None and not old.mesh.use_rules
        block = InferenceConfig.parse(
            {"mesh": {"shape": {"data": 1, "tensor": 4},
                      "rules": [["attn/", []]], "use_rules": True}})
        assert block.mesh.shape == {"data": 1, "tensor": 4}
        assert block.mesh.rules == [["attn/", []]] and block.mesh.use_rules

    def test_default_is_degenerate(self):
        cfg = InferenceConfig.parse({"dtype": "float32"})
        assert isinstance(cfg.mesh, MeshConfig)
        assert cfg.mesh.shape is None and not cfg.mesh.use_rules

    def test_engine_builds_subset_mesh_and_shards_params(self, setup):
        model, params = setup
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32",
                    "mesh": {"shape": {"data": 1, "tensor": 2}}})
        assert dict(eng.mesh.shape)["tensor"] == 2
        assert eng.mesh.devices.size == 2  # subset of the 8-device host
        wq = eng.params["layers"]["attn"]["wq"]
        assert "tensor" in [ax for ax in wq.sharding.spec if ax is not None]
        # each device holds half the heads dim
        shard_shapes = {s.data.shape for s in wq.addressable_shards}
        assert shard_shapes == {(2, 64, 32)}

    def test_rule_overrides_replicate_attention(self, setup):
        """use_rules=True: the whole-tree regex path — the user rule
        fronts DEFAULT_RULES, which still shard the rest."""
        model, params = setup
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32",
                    "mesh": {"shape": {"data": 1, "tensor": 2},
                             "use_rules": True, "rules": [["attn/", []]]}})
        wq = eng.params["layers"]["attn"]["wq"]
        assert all(ax is None for ax in wq.sharding.spec)
        wi = eng.params["layers"]["mlp"]["wi"]  # defaults still apply
        assert "tensor" in [ax for ax in wi.sharding.spec if ax is not None]

    def test_rules_overlay_per_leaf_on_annotated_model(self, setup):
        """rules WITHOUT use_rules on a model carrying logical_specs:
        only matched leaves change placement — unmatched params keep
        their annotation-derived sharding (one attention override must
        not strip the rest of the tree's intent)."""
        model, params = setup
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32",
                    "mesh": {"shape": {"data": 1, "tensor": 2},
                             "rules": [["attn/", []]]}})
        wq = eng.params["layers"]["attn"]["wq"]
        assert all(ax is None for ax in wq.sharding.spec)  # overridden
        wi = eng.params["layers"]["mlp"]["wi"]  # annotation survives
        assert "tensor" in [ax for ax in wi.sharding.spec if ax is not None]
        tok = eng.params["embed"]["tok"]       # annotation survives
        assert "tensor" in [ax for ax in tok.sharding.spec if ax is not None]


class TestStreamParity:
    """Sharded vs single-chip bitwise token-stream parity — the PR
    acceptance gate. The single-chip reference is served once per class
    (module params are shared, streams are deterministic)."""

    SUBS = None  # (tick, prompt, max_new) — prompts cross read buckets

    @classmethod
    def _submissions(cls):
        if cls.SUBS is None:
            cls.SUBS = list(zip((0, 0, 1, 3), _prompts((5, 20, 9, 7), 1),
                                (12, 10, 24, 8)))
        return cls.SUBS

    def test_greedy_parity_across_depths_and_widths(self, setup):
        subs = self._submissions()
        base = _serve(_cb(setup), subs)
        for tensor in (2, 4):
            for depth in (0, 1, 2):
                outs = _serve(_cb(setup, tensor=tensor, pipeline_depth=depth),
                              subs)
                for a, b in zip(base, outs):
                    np.testing.assert_array_equal(a, b)

    def test_sampled_parity(self, setup):
        subs = self._submissions()
        kw = dict(temperature=0.8, top_k=8, seed=3)
        base = _serve(_cb(setup, **kw), subs)
        for tensor in (2, 4):
            outs = _serve(_cb(setup, tensor=tensor, **kw), subs)
            for a, b in zip(base, outs):
                np.testing.assert_array_equal(a, b)

    def test_separate_prefill_and_burst_parity(self, setup):
        subs = self._submissions()
        base = _serve(_cb(setup), subs)
        sep = _serve(_cb(setup, tensor=2, fused_prefill=False), subs)
        burst = _serve(_cb(setup, tensor=2, fused_prefill=False,
                           tokens_per_tick=4), subs)
        for a, b, c in zip(base, sep, burst):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)

    def test_bucketed_pools_parity(self, setup):
        # mixed pool lengths: admission placement + per-pool tick
        # programs, each pool sharded on the same mesh
        subs = self._submissions()
        base = _serve(_cb(setup, max_slots=None, cache_len=None,
                          cache_buckets=[(2, 32), (2, 64)]), subs)
        outs = _serve(_cb(setup, tensor=2, max_slots=None, cache_len=None,
                          cache_buckets=[(2, 32), (2, 64)]), subs)
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)

    def test_prefix_splice_parity(self, setup):
        prefix = np.arange(1, 11, dtype=np.int32)
        sufs = _prompts((4, 6), 5)

        def run(cb):
            pid = cb.register_prefix(prefix)
            rids = [cb.submit_with_prefix(pid, s, max_new_tokens=10)
                    for s in sufs]
            while cb.has_work():
                cb.step()
            res = cb.finished()
            return [res[r] for r in rids]

        base = run(_cb(setup))
        outs = run(_cb(setup, tensor=2))
        for a, b in zip(base, outs):
            np.testing.assert_array_equal(a, b)

    def test_degenerate_mesh_is_bit_identical(self, setup):
        subs = self._submissions()
        base = _serve(_cb(setup), subs)
        one = _serve(_cb(setup, tensor=1), subs)
        for a, b in zip(base, one):
            np.testing.assert_array_equal(a, b)

    def test_engine_generate_parity_fused_and_migrating(self, setup):
        """InferenceEngine paths on the mesh: the fused whole-generation
        program and the bucket-migrated per-token loop both match their
        single-chip streams."""
        model, params = setup
        toks = np.asarray(_prompts((9,), 7)[0])[None, :]

        def gen(mesh_cfg, fused):
            cfg = {"dtype": "float32", "kv_read_floor": FLOOR,
                   "fused_generate": fused}
            if mesh_cfg:
                cfg["mesh"] = mesh_cfg
            eng = deepspeed_tpu.init_inference(model, params=params, config=cfg)
            return np.asarray(eng.generate(toks, max_new_tokens=40))

        for fused in (True, False):
            base = gen(None, fused)
            out = gen({"shape": {"data": 1, "tensor": 2}}, fused)
            np.testing.assert_array_equal(base, out)


class TestPerChipKvBytes:
    def _events(self, path):
        with open(path) as fh:
            return [json.loads(l) for l in fh if l.strip()]

    def test_continuous_event_is_per_chip(self, setup, tmp_path):
        """Exact per-chip accounting on a 1x2 virtual mesh: each chip
        holds half the kv heads, so every tick's row-read bytes halve —
        asserted against the same simulated-tick walk the single-chip
        test uses, divided by the shard width."""
        model, params = setup
        trace = tmp_path / "t2.jsonl"
        cb = ContinuousBatchingEngine(
            model, params=params,
            config={"dtype": "float32", "kv_read_floor": FLOOR,
                    "mesh": {"shape": {"data": 1, "tensor": 2}},
                    "telemetry": {"enabled": True, "trace_file": str(trace)}},
            max_slots=1, cache_len=64, donate_cache=False)
        assert kv_shard_width(cb.mesh, cb.cfg) == 2
        prompt = np.arange(2, 9, dtype=np.int32)  # len 7
        rid = cb.submit(prompt, max_new_tokens=12)
        while cb.has_work():
            cb.step()
        cb.finished()
        expect = 0
        for i in range(12):
            r = read_bucket(7 + i, 64, FLOOR)
            expect += kv_read_bytes_per_row(cb.cfg, r if r < 64 else 64, tp=2)
        ev = [e for e in self._events(trace)
              if e.get("path") == "continuous" and e.get("request") == rid][0]
        assert ev["kv_bytes_read"] == expect
        # per-chip bytes are EXACTLY half the replicated-cache bytes
        assert ev["kv_bytes_read"] * 2 == sum(
            kv_read_bytes_per_row(cb.cfg, min(read_bucket(7 + i, 64, FLOOR), 64))
            for i in range(12))

    def test_engine_event_is_per_chip(self, setup, tmp_path):
        model, params = setup
        trace = tmp_path / "eng.jsonl"
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "kv_read_floor": FLOOR,
                    "mesh": {"shape": {"data": 1, "tensor": 4}},
                    "telemetry": {"enabled": True, "trace_file": str(trace)}})
        toks = np.asarray(_prompts((6,), 9)[0])[None, :]
        eng.generate(toks, max_new_tokens=20)
        ev = [e for e in self._events(trace)
              if e.get("kind") == "inference_request"][-1]
        expect = decode_kv_bytes(eng.cfg, 6, 20, ev["cache_len"], FLOOR, tp=4)
        assert ev["kv_bytes_read"] == expect

    def test_non_divisible_heads_fall_back_to_full_rows(self, setup):
        mesh = serving_mesh(1, 2)
        cfg = TransformerConfig(vocab_size=64, hidden_size=60, num_layers=1,
                                num_heads=3, max_seq_len=64, dtype="float32")
        assert kv_shard_width(mesh, cfg) == 1  # 3 heads don't split 2 ways
        assert kv_read_bytes_per_row(cfg, 32, tp=1) == \
            kv_read_bytes_per_row(cfg, 32)


class TestTickStateSharding:
    def test_row_state_and_packed_fetch_replicated(self, setup):
        """The per-row scheduling state threads through ticks FULLY
        replicated on the mesh (the host fetch stays one coalesced get)
        while the pool KV cache shards its heads axis on ``tensor``."""
        cb = _cb(setup, tensor=2)
        rid = cb.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=4)
        while cb.has_work():
            cb.step()
        cb.finished()
        pool = cb._pools[0]
        assert pool.last_tok_dev.sharding.is_fully_replicated
        assert pool.done_dev.sharding.is_fully_replicated
        k_spec = jax.tree.leaves(pool.cache)[0].sharding.spec
        assert "tensor" in [ax for ax in k_spec if ax is not None]
