"""KV-cache-centric decode geometry: tight reads (bucketed active-length
attention), bucket-migrated cache growth, int8 KV composition — token-stream
parity across every decode path plus deterministic ``kv_bytes_read``
accounting (the CPU-mesh-measurable form of the decode-bandwidth win)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import comm
from deepspeed_tpu.inference.continuous import ContinuousBatchingEngine
from deepspeed_tpu.inference.decoding import (
    decode_kv_bytes,
    read_bucket,
    read_stages,
)
from deepspeed_tpu.models.transformer import (
    TransformerConfig,
    TransformerModel,
    kv_read_bytes_per_row,
)

FLOOR = 16  # small bucket floor so tiny test models cross several buckets


@pytest.fixture(scope="module")
def setup():
    comm.destroy()
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=128, dtype="float32")
    model = TransformerModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _engine(model, params, **over):
    cfg = {"dtype": "float32", "kv_read_floor": FLOOR}
    cfg.update(over)
    return deepspeed_tpu.init_inference(model, params=params, config=cfg)


def _toks(n, batch=2, seed=0):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randint(0, 128, (batch, n)), jnp.int32)


class TestReadGeometry:
    def test_read_stages_bucket_boundaries(self):
        # 40 decode steps from prompt 5: extents 6..45 cross 16/32/64
        assert read_stages(5, 40, 128, 16) == [(16, 11), (32, 16), (64, 13)]
        # the bucket reaching the allocation degenerates to a full read
        assert read_stages(5, 40, 32, 16) == [(16, 11), (None, 29)]
        # tight off = one full-length stage; no steps = no stages
        assert read_stages(5, 40, 128, None) == [(None, 40)]
        assert read_stages(5, 0, 128, 16) == []

    def test_stage_reads_cover_every_step(self):
        for prompt in (1, 7, 16, 33):
            j = 0
            for r, n in read_stages(prompt, 50, 256, 16):
                for _ in range(n):
                    extent = prompt + j + 1
                    assert (r if r is not None else 256) >= extent
                    if r is not None:
                        assert r == read_bucket(extent, 256, 16)
                    j += 1
            assert j == 50

    def test_row_read_bytes_int8_vs_dense(self):
        cfg = TransformerConfig(hidden_size=64, num_layers=2, num_heads=4,
                                dtype="bfloat16")
        dense = kv_read_bytes_per_row(cfg, 64)
        assert dense == 2 * 2 * 64 * 4 * 16 * 2  # K+V, L, slots, heads, hd, bf16
        cfg8 = TransformerConfig(hidden_size=64, num_layers=2, num_heads=4,
                                 dtype="bfloat16", kv_cache_dtype="int8")
        # int8 payload + 4-byte scale per (token, head)
        assert kv_read_bytes_per_row(cfg8, 64) == 2 * 2 * 64 * 4 * (16 + 4)


class TestTokenStreamParity:
    def test_tight_matches_full_across_bucket_migrations(self, setup):
        """40 new tokens from prompt 5 cross the 16->32->64 buckets: the
        fused (staged-scan) and per-token (migrating-cache) tight paths
        must reproduce the full-read streams exactly."""
        model, params = setup
        toks = _toks(5)
        want = np.asarray(_engine(model, params, kv_tight_read=False)
                          .generate(toks, max_new_tokens=40))
        for fused in (True, False):
            got = _engine(model, params, fused_generate=fused).generate(
                toks, max_new_tokens=40)
            np.testing.assert_array_equal(np.asarray(got), want)

    def test_loop_fused_burst_identical_under_int8_kv(self, setup):
        """Satellite acceptance: greedy token streams identical across the
        decode_loop / fused_generate / burst-segment (continuous) paths for
        the int8-KV tight-read cache config, fixed rng."""
        model, params = setup
        prompts = [np.arange(1, 6, dtype=np.int32), np.arange(3, 12, dtype=np.int32)]
        cfg = {"kv_cache_dtype": "int8"}
        fused = _engine(model, params, **cfg)
        loop = _engine(model, params, fused_generate=False, **cfg)
        refs = {}
        for i, p in enumerate(prompts):
            a = np.asarray(fused.generate(p[None, :], max_new_tokens=24))[0]
            b = np.asarray(loop.generate(p[None, :], max_new_tokens=24))[0]
            np.testing.assert_array_equal(a, b)
            refs[i] = a
        cb = ContinuousBatchingEngine(
            model, params=params,
            config={"dtype": "float32", "kv_cache_dtype": "int8",
                    "kv_read_floor": FLOOR},
            max_slots=2, cache_len=64, tokens_per_tick=4)
        rids = [cb.submit(p, max_new_tokens=24) for p in prompts]
        while cb.has_work():
            cb.step()
        done = cb.finished()
        for i, rid in enumerate(rids):
            np.testing.assert_array_equal(done[rid], refs[i])

    def test_ragged_tight_matches_full(self, setup):
        """attention_mask generation (per-row segment tail) under tight
        reads equals the full-read stream, left padding included."""
        model, params = setup
        rs = np.random.RandomState(3)
        toks = rs.randint(0, 128, (2, 9)).astype(np.int32)
        mask = np.ones((2, 9), np.int32)
        mask[0, :4] = 0  # left padding
        toks[0, :4] = 0
        full = _engine(model, params, kv_tight_read=False).generate(
            jnp.asarray(toks), max_new_tokens=30, attention_mask=mask)
        tight = _engine(model, params).generate(
            jnp.asarray(toks), max_new_tokens=30, attention_mask=mask)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(tight))

    def test_mixed_bucket_admission_with_tight_read(self, setup):
        """Bucketed slot pools + tight-read ticks: requests landing in
        different-length pools (and one queued past a full pool) still
        reproduce plain generate exactly."""
        model, params = setup
        plain = _engine(model, params)
        rs = np.random.RandomState(5)
        prompts = [rs.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 9, 3, 20)]  # the 20-prompt only fits the 64 pool
        refs = [np.asarray(plain.generate(p[None, :], max_new_tokens=10))[0]
                for p in prompts]
        cb = ContinuousBatchingEngine(
            model, params=params,
            config={"dtype": "float32", "kv_read_floor": FLOOR},
            cache_buckets=[(2, 32), (2, 64)])
        rids = [cb.submit(p, max_new_tokens=10) for p in prompts]
        while cb.has_work():
            cb.step()
        done = cb.finished()
        for rid, want in zip(rids, refs):
            np.testing.assert_array_equal(done[rid], want)


class TestKvBytesAccounting:
    def _trace_events(self, path):
        with open(path) as fh:
            return [json.loads(l) for l in fh if l.strip()]

    def test_engine_event_matches_host_math(self, setup, tmp_path):
        model, params = setup
        trace = tmp_path / "trace.jsonl"
        eng = _engine(model, params, fused_generate=False,
                      telemetry={"enabled": True, "trace_file": str(trace)})
        toks = _toks(5)
        eng.generate(toks, max_new_tokens=40)
        ev = [e for e in self._trace_events(trace)
              if e["kind"] == "inference_request"][-1]
        # bounded_cache_len(45, 128, 1024) = 128: the DEFAULT config keeps
        # the full-seq-len allocation — exactly the geometry tight reads fix
        max_len = 128
        expect = 2 * decode_kv_bytes(eng.cfg, 5, 40, max_len, FLOOR)
        assert ev["kv_bytes_read"] == expect
        assert ev["kv_dtype"] == "float32"
        assert 0 < ev["cache_utilization"] <= 1.0
        assert ev["kv_bytes_per_token"] == round(expect / 2 / 39, 1)

    def test_tight_read_halves_default_config_bytes(self, setup):
        """The CPU-mesh acceptance gate: at the DEFAULT allocation (no
        max_out_tokens bound beyond max_seq_len) the tight geometry reads
        <= 0.5x the full-read bytes per decoded token."""
        model, params = setup
        cfg = _engine(model, params).cfg
        cache_len = 128  # default allocation for this model (max_seq_len)
        full = decode_kv_bytes(cfg, 8, 56, cache_len, None)
        tight = decode_kv_bytes(cfg, 8, 56, cache_len, FLOOR)
        assert tight <= 0.5 * full
        # int8 KV halves it again
        cfg8 = _engine(model, params, kv_cache_dtype="int8").cfg
        assert decode_kv_bytes(cfg8, 8, 56, cache_len, FLOOR) < tight

    def test_continuous_event_matches_simulated_ticks(self, setup, tmp_path):
        model, params = setup
        trace = tmp_path / "trace.jsonl"
        cb = ContinuousBatchingEngine(
            model, params=params,
            config={"dtype": "float32", "kv_read_floor": FLOOR,
                    "telemetry": {"enabled": True, "trace_file": str(trace)}},
            max_slots=1, cache_len=64)
        prompt = np.arange(2, 9, dtype=np.int32)  # len 7
        rid = cb.submit(prompt, max_new_tokens=12)
        while cb.has_work():
            cb.step()
        cb.finished()
        # simulate: EVERY token rides a pool tick now (the admission tick
        # itself samples token 1 — fused prefill — and each later tick
        # feeds the previous token). Tick i reads the bucket covering
        # (prompt + i) cached slots: the first tick attends exactly the
        # prompt, the last attends prompt + 11.
        expect = 0
        for i in range(12):
            extent = 7 + i
            r = read_bucket(extent, 64, FLOOR)
            expect += kv_read_bytes_per_row(cb.cfg, r if r < 64 else 64)
        ev = [e for e in self._trace_events(trace)
              if e.get("path") == "continuous" and e["request"] == rid][0]
        assert ev["kv_bytes_read"] == expect
        assert ev["new_tokens"] == 12
        assert ev["kv_bytes_per_token"] == round(expect / 12, 1)

    def test_cache_utilization_gauge(self, setup):
        model, params = setup
        cb = ContinuousBatchingEngine(
            model, params=params,
            config={"dtype": "float32", "kv_read_floor": FLOOR,
                    "telemetry": {"enabled": True, "trace_file": ""}},
            max_slots=2, cache_len=32)
        cb.submit(np.arange(1, 6, dtype=np.int32), max_new_tokens=4)
        cb.step()
        gauges = cb._eng.telemetry.registry.dump()["gauges"]
        # one slot of two holds 5-6 cached tokens out of 2*32 reserved
        assert 0 < gauges["cache_utilization"] <= 1.0
        while cb.has_work():
            cb.step()
