"""Chunked prefill: a fixed (B, chunk) prefill program serves every prompt
length (one compile instead of one per length — each costs 20-40s through
the remote-compile link) with prefill memory bounded by the chunk. Token
streams must be identical to the unchunked engine."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import comm
from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel


def _model(**kw):
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=128, dtype="float32", **kw)
    model = TransformerModel(cfg)
    return model, model.init(jax.random.PRNGKey(0))


class TestChunkedPrefill:
    @pytest.mark.parametrize("prompt_len,chunk", [(16, 8), (13, 8), (5, 8), (8, 8)],
                             ids=["even", "ragged-tail", "prompt-lt-chunk", "exact"])
    def test_greedy_parity_with_plain(self, prompt_len, chunk):
        comm.destroy()
        model, params = _model()
        chunked = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prefill_chunk_size": chunk})
        comm.destroy()
        plain = deepspeed_tpu.init_inference(model, params=params,
                                             config={"dtype": "float32"})
        toks = np.random.RandomState(0).randint(0, 128, (2, prompt_len)).astype(np.int32)
        a = np.asarray(chunked.generate(toks, max_new_tokens=8))
        b = np.asarray(plain.generate(toks, max_new_tokens=8))
        np.testing.assert_array_equal(a, b)

    def test_one_compile_serves_all_lengths(self):
        """The whole point: distinct prompt lengths reuse the same chunk
        program (the jit wrapper retraces per input shape; every chunk is
        the same shape)."""
        comm.destroy()
        model, params = _model()
        eng = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prefill_chunk_size": 8,
                    "max_out_tokens": 64})
        rs = np.random.RandomState(1)
        for S in (3, 9, 17, 24):
            out = np.asarray(eng.generate(
                rs.randint(0, 128, (1, S)).astype(np.int32), max_new_tokens=4))
            assert out.shape == (1, S + 4)
        # one ragged-prefill family entry, compiled for (B=1, cache 64)
        from deepspeed_tpu.inference.decoding import cached_fn  # noqa: F401
        prefill_fn, _, _ = eng._ragged_fns_for(1, 64)
        traces = prefill_fn._cache_size() if hasattr(prefill_fn, "_cache_size") else None
        if traces is not None:
            assert traces == 1, f"chunk program retraced {traces}x"

    @pytest.mark.parametrize("side", ["right", "left"])
    def test_attention_mask_parity_with_ragged(self, side):
        """The motivating serving workload: varied-width padded batches must
        both WORK under chunking and match the unchunked ragged path."""
        comm.destroy()
        model, params = _model()
        chunked = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prefill_chunk_size": 8})
        comm.destroy()
        plain = deepspeed_tpu.init_inference(model, params=params,
                                             config={"dtype": "float32"})
        rs = np.random.RandomState(3)
        toks = rs.randint(0, 128, (2, 20)).astype(np.int32)
        mask = np.ones((2, 20), np.float32)
        if side == "right":
            mask[1, 13:] = 0
        else:
            mask[1, :9] = 0
        a = np.asarray(chunked.generate(toks, max_new_tokens=6, attention_mask=mask))
        b = np.asarray(plain.generate(toks, max_new_tokens=6, attention_mask=mask))
        np.testing.assert_array_equal(a, b)

    def test_composes_with_int8_kv_and_windows(self):
        comm.destroy()
        model, params = _model(attn_impl="pallas", pos_embedding="rope",
                               norm_type="rmsnorm", use_bias=False,
                               num_kv_heads=2, local_attn_windows=(12, 12))
        chunked = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "prefill_chunk_size": 8,
                    "kv_cache_dtype": "int8"})
        comm.destroy()
        plain = deepspeed_tpu.init_inference(
            model, params=params,
            config={"dtype": "float32", "kv_cache_dtype": "int8",
                    "rolling_kv_cache": False})
        toks = np.random.RandomState(2).randint(0, 128, (2, 20)).astype(np.int32)
        a = np.asarray(chunked.generate(toks, max_new_tokens=6))
        b = np.asarray(plain.generate(toks, max_new_tokens=6))
        np.testing.assert_array_equal(a, b)
