"""Speculative decoding tests: lossless acceptance (greedy output must be
token-for-token identical to plain greedy decode), per-row divergence, and
the sampling path's support restriction."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel


def _engine(seed=0, layers=2, hidden=64):
    cfg = TransformerConfig(
        vocab_size=128, hidden_size=hidden, num_layers=layers, num_heads=4,
        max_seq_len=128, dtype="float32",
    )
    model = TransformerModel(cfg)
    return deepspeed_tpu.init_inference(model, config={"dtype": "float32"}, seed=seed)


@pytest.fixture(scope="module")
def engines():
    target = _engine(seed=0, layers=2, hidden=64)
    draft = _engine(seed=1, layers=1, hidden=32)
    return target, draft


def _prompt(B=3, S=9):
    rs = np.random.RandomState(0)
    return rs.randint(0, 128, (B, S)).astype(np.int32)


class TestSpeculative:
    def test_greedy_matches_plain_decode(self, engines):
        """Greedy speculative output == plain greedy decode output exactly
        — acceptance is lossless by construction. With an unrelated draft,
        rows accept different counts per round, so this also exercises the
        per-row position path."""
        target, draft = engines
        prompt = _prompt()
        plain = np.asarray(target.generate(prompt, max_new_tokens=16))
        spec = np.asarray(target.generate(prompt, max_new_tokens=16, draft=draft,
                                          num_draft_tokens=4))
        np.testing.assert_array_equal(plain, spec)

    def test_self_draft_accepts_everything(self, engines):
        """Drafting with the target itself must accept every proposal in
        greedy mode (argmax of the same model) and still emit the exact
        greedy continuation."""
        target, _ = engines
        prompt = _prompt(B=2)
        plain = np.asarray(target.generate(prompt, max_new_tokens=12))
        spec = np.asarray(target.generate(prompt, max_new_tokens=12, draft=target,
                                          num_draft_tokens=3))
        np.testing.assert_array_equal(plain, spec)

    def test_gamma_one_and_long(self, engines):
        target, draft = engines
        prompt = _prompt(B=2, S=5)
        plain = np.asarray(target.generate(prompt, max_new_tokens=10))
        for gamma in (1, 8):
            spec = np.asarray(target.generate(prompt, max_new_tokens=10, draft=draft,
                                              num_draft_tokens=gamma))
            np.testing.assert_array_equal(plain, spec)

    def test_sampling_stays_in_topk_support(self, engines):
        """Sampled speculative tokens must come from the target's filtered
        support: with top_k=1 sampling degenerates to greedy, so the output
        must equal plain greedy decode even through the accept/resample
        path."""
        target, draft = engines
        prompt = _prompt(B=2, S=6)
        plain = np.asarray(target.generate(prompt, max_new_tokens=8))
        spec = np.asarray(target.generate(
            prompt, max_new_tokens=8, draft=draft, num_draft_tokens=3,
            temperature=0.7, top_k=1, rng=jax.random.PRNGKey(3),
        ))
        np.testing.assert_array_equal(plain, spec)

    def test_sampling_runs_finite(self, engines):
        target, draft = engines
        prompt = _prompt(B=2, S=6)
        out = np.asarray(target.generate(
            prompt, max_new_tokens=8, draft=draft, num_draft_tokens=4,
            temperature=1.0, top_k=0, top_p=0.9, rng=jax.random.PRNGKey(5),
        ))
        assert out.shape == (2, 14)
        assert ((out >= 0) & (out < 128)).all()

    def test_config_block_parsed(self):
        from deepspeed_tpu.inference.config import InferenceConfig

        cfg = InferenceConfig.parse({"speculative": {"enabled": True, "num_draft_tokens": 6}})
        assert cfg.speculative.enabled and cfg.speculative.num_draft_tokens == 6
        assert InferenceConfig.parse({}).speculative.num_draft_tokens == 4

    @pytest.mark.slow  # 20s; the draft/verify math is covered fast by greedy_matches_plain_decode + self_draft
    def test_config_driven_draft_engine(self):
        """speculative.enabled + draft_model= on init_inference: every
        generate() uses the attached draft without per-call plumbing."""
        target_cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                                       num_heads=4, max_seq_len=128, dtype="float32")
        draft_cfg = TransformerConfig(vocab_size=128, hidden_size=32, num_layers=1,
                                      num_heads=4, max_seq_len=128, dtype="float32")
        engine = deepspeed_tpu.init_inference(
            TransformerModel(target_cfg),
            config={"dtype": "float32", "speculative": {"enabled": True, "num_draft_tokens": 3}},
            draft_model=TransformerModel(draft_cfg),
        )
        plain_engine = deepspeed_tpu.init_inference(
            TransformerModel(target_cfg), config={"dtype": "float32"}
        )
        prompt = _prompt(B=2, S=6)
        spec = np.asarray(engine.generate(prompt, max_new_tokens=10))
        plain = np.asarray(plain_engine.generate(prompt, max_new_tokens=10))
        np.testing.assert_array_equal(plain, spec)

        # enabled without any draft anywhere must fail loudly, not silently
        # fall back to plain decode
        bare = deepspeed_tpu.init_inference(
            TransformerModel(target_cfg),
            config={"dtype": "float32", "speculative": {"enabled": True}},
        )
        with pytest.raises(ValueError, match="draft"):
            bare.generate(prompt, max_new_tokens=4)

    def test_eos_early_stop_matches_plain(self, engines):
        """With an eos id the spec loop stops gating on rows that hit eos;
        post-truncation output must still equal the plain path's."""
        target, draft = engines
        prompt = _prompt(B=3, S=7)
        # pick the token the model actually emits first so eos really fires
        first = int(np.asarray(target.generate(prompt, max_new_tokens=1))[0, -1])
        plain = np.asarray(target.generate(prompt, max_new_tokens=12, eos_token_id=first))
        spec = np.asarray(target.generate(prompt, max_new_tokens=12, draft=draft,
                                          num_draft_tokens=4, eos_token_id=first))
        np.testing.assert_array_equal(plain, spec)


class TestAcceptRound:
    """The vectorized whole-batch accept/correct step (decoding._accept_round)
    against a scalar reference implementation of the standard speculative
    accept rule, plus the B=32 host-cost bound (VERDICT r2 weak #6)."""

    def _scalar_reference(self, drafts, active, lens, max_new, eos, tgt):
        """Greedy-mode reference: direct transcription of the original
        per-row accept loop (accept while draft matches target argmax; stop
        at quota or an accepted eos; bonus only if the row isn't done)."""
        B, gamma = drafts.shape
        n_take = np.zeros(B, np.int32)
        bonus_ok = np.zeros(B, bool)
        took_eos = np.zeros(B, bool)
        bonus = np.zeros(B, np.int32)
        for b in range(B):
            if not active[b]:
                continue
            ln = int(lens[b])
            rejected = False
            for i in range(gamma):
                if ln >= max_new:
                    break
                if drafts[b, i] != tgt[b, i]:
                    rejected = True
                    break
                n_take[b] += 1
                ln += 1
                if eos is not None and drafts[b, i] == eos:
                    took_eos[b] = True
                    break
            done = took_eos[b] or ln >= max_new
            if not done and (rejected or n_take[b] == gamma):
                bonus_ok[b] = True
                bonus[b] = tgt[b, n_take[b]]
        return n_take, bonus, bonus_ok, took_eos

    def test_greedy_parity_with_scalar_rule(self):
        from deepspeed_tpu.inference.decoding import _accept_round

        rs = np.random.RandomState(3)
        for trial in range(20):
            B, gamma, V = 8, 4, 12
            drafts = rs.randint(0, V, (B, gamma)).astype(np.int32)
            tgt = rs.randint(0, V, (B, gamma + 1)).astype(np.int32)
            # force high accept rates on some rows
            tgt[: B // 2, :gamma] = drafts[: B // 2]
            active = rs.rand(B) > 0.2
            lens = rs.randint(1, 10, B).astype(np.int32)
            max_new = 10
            eos = 5 if trial % 2 == 0 else None
            got = _accept_round(drafts, active, lens, max_new, eos, tgt=tgt)
            want = self._scalar_reference(drafts, active, lens, max_new, eos, tgt)
            for g, w, name in zip(got, want, ["n_take", "bonus", "bonus_ok", "took_eos"]):
                if name == "bonus":  # only meaningful where bonus_ok
                    g = np.where(got[2], g, 0)
                    w = np.where(want[2], w, 0)
                np.testing.assert_array_equal(g, w, err_msg=f"{name} trial {trial}")

    def test_sampling_mode_shapes_and_support(self):
        from deepspeed_tpu.inference.decoding import _accept_round

        rs = np.random.RandomState(0)
        B, gamma, V = 6, 3, 16
        drafts = rs.randint(0, V, (B, gamma)).astype(np.int32)
        p = rs.rand(B, gamma + 1, V); p /= p.sum(-1, keepdims=True)
        q = rs.rand(B, gamma, V); q /= q.sum(-1, keepdims=True)
        active = np.ones(B, bool)
        lens = np.zeros(B, np.int32)
        n_take, bonus, bonus_ok, took_eos = _accept_round(
            drafts, active, lens, 20, None, pdists=p, qstack=q,
            host_rng=np.random.default_rng(0))
        assert n_take.shape == (B,) and (0 <= n_take).all() and (n_take <= gamma).all()
        assert ((0 <= bonus) & (bonus < V)).all()
        assert bonus_ok.all()  # quota is far away, no eos
        assert not took_eos.any()

    def test_b32_accept_is_fast(self):
        """The accept step must be O(1) host work per round: 200 rounds at
        B=32 (sampling mode, V=1024) in well under a second."""
        import time

        from deepspeed_tpu.inference.decoding import _accept_round

        rs = np.random.RandomState(1)
        B, gamma, V = 32, 5, 1024
        drafts = rs.randint(0, V, (B, gamma)).astype(np.int32)
        p = rs.rand(B, gamma + 1, V).astype(np.float32); p /= p.sum(-1, keepdims=True)
        q = rs.rand(B, gamma, V).astype(np.float32); q /= q.sum(-1, keepdims=True)
        active = np.ones(B, bool)
        lens = np.zeros(B, np.int32)
        rng = np.random.default_rng(0)
        _accept_round(drafts, active, lens, 100, 2, pdists=p, qstack=q, host_rng=rng)
        t0 = time.time()
        for _ in range(200):
            _accept_round(drafts, active, lens, 100, 2, pdists=p, qstack=q, host_rng=rng)
        assert time.time() - t0 < 2.0, "vectorized accept should be ~ms per round"
