"""UNet/VAE (diffusers) injection: state-dict conversion + numeric parity.

The ``diffusers`` package is not in this image, so the torch reference here
reimplements the EXACT math of diffusers' ``BasicTransformerBlock`` (LN ->
self-attn -> LN -> cross-attn -> LN -> GEGLU feed-forward, exact-erf gelu)
and of the AutoencoderKL mid-block ``Attention`` (GroupNorm + biased q/k/v +
residual), with module names chosen so ``state_dict()`` carries diffusers'
key layout — the same keys a real checkpoint has. Reference:
module_inject/replace_policy.py (UNetPolicy/VAEPolicy),
model_implementations/diffusers/unet.py:15.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as nn
import torch.nn.functional as F


class _TorchAttn(nn.Module):
    def __init__(self, C, K, heads, qkv_bias=False):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(C, C, bias=qkv_bias)
        self.to_k = nn.Linear(K, C, bias=qkv_bias)
        self.to_v = nn.Linear(K, C, bias=qkv_bias)
        self.to_out = nn.ModuleList([nn.Linear(C, C, bias=True)])

    def forward(self, x, context=None):
        ctx = x if context is None else context
        B, T, C = x.shape
        h = self.heads
        q = self.to_q(x).view(B, T, h, C // h).transpose(1, 2)
        k = self.to_k(ctx).view(B, ctx.shape[1], h, C // h).transpose(1, 2)
        v = self.to_v(ctx).view(B, ctx.shape[1], h, C // h).transpose(1, 2)
        scores = q @ k.transpose(-1, -2) / (C // h) ** 0.5
        o = scores.softmax(dim=-1) @ v
        o = o.transpose(1, 2).reshape(B, T, C)
        return self.to_out[0](o)


class _GEGLU(nn.Module):
    def __init__(self, C, Fh):
        super().__init__()
        self.proj = nn.Linear(C, 2 * Fh)

    def forward(self, x):
        h, gate = self.proj(x).chunk(2, dim=-1)
        return h * F.gelu(gate)  # exact erf gelu, diffusers GEGLU


class _FF(nn.Module):
    def __init__(self, C, Fh):
        super().__init__()
        self.net = nn.ModuleList([_GEGLU(C, Fh), nn.Identity(), nn.Linear(Fh, C)])

    def forward(self, x):
        for m in self.net:
            x = m(x)
        return x


class _TorchBasicBlock(nn.Module):
    """diffusers BasicTransformerBlock, SD-1.x layer_norm variant."""

    def __init__(self, C, ctx_dim, heads, ff_mult=2):
        super().__init__()
        self.norm1 = nn.LayerNorm(C)
        self.attn1 = _TorchAttn(C, C, heads)
        self.norm2 = nn.LayerNorm(C)
        self.attn2 = _TorchAttn(C, ctx_dim, heads)
        self.norm3 = nn.LayerNorm(C)
        self.ff = _FF(C, C * ff_mult)

    def forward(self, x, context):
        x = self.attn1(self.norm1(x)) + x
        x = self.attn2(self.norm2(x), context) + x
        return self.ff(self.norm3(x)) + x


class _TorchVAEAttn(nn.Module):
    """AutoencoderKL mid-block Attention (heads=1, biased q/k/v)."""

    def __init__(self, C):
        super().__init__()
        self.group_norm = nn.GroupNorm(32, C, eps=1e-6)
        self.to_q = nn.Linear(C, C, bias=True)
        self.to_k = nn.Linear(C, C, bias=True)
        self.to_v = nn.Linear(C, C, bias=True)
        self.to_out = nn.ModuleList([nn.Linear(C, C, bias=True)])

    def forward(self, x):  # x NCHW
        res = x
        B, C, H, W = x.shape
        h = self.group_norm(x).view(B, C, H * W).transpose(1, 2)  # (B, T, C)
        q, k, v = self.to_q(h), self.to_k(h), self.to_v(h)
        scores = q @ k.transpose(-1, -2) / C ** 0.5
        o = scores.softmax(dim=-1) @ v
        o = self.to_out[0](o)
        return o.transpose(1, 2).view(B, C, H, W) + res


class TestUNetInjection:
    def _built(self):
        torch.manual_seed(0)
        C, ctx_dim, heads = 32, 24, 4
        parent = nn.Module()
        parent.transformer_blocks = nn.ModuleList(
            [_TorchBasicBlock(C, ctx_dim, heads) for _ in range(2)]
        )
        return parent.eval(), C, ctx_dim, heads

    def test_block_discovery_and_parity(self):
        from deepspeed_tpu.module_inject.diffusers_policies import UNetPolicy

        parent, C, ctx_dim, heads = self._built()
        state = parent.state_dict()
        converted = UNetPolicy.convert(state, num_heads=heads)
        assert sorted(converted) == ["transformer_blocks.0", "transformer_blocks.1"]

        from deepspeed_tpu.ops.transformer.diffusers_attention import apply_transformer_block

        rs = np.random.RandomState(0)
        x = rs.normal(size=(2, 16, C)).astype(np.float32)
        ctx = rs.normal(size=(2, 5, ctx_dim)).astype(np.float32)
        for i, path in enumerate(sorted(converted)):
            cfg, params = converted[path]
            assert cfg.context_dim == ctx_dim and cfg.channels == C
            with torch.no_grad():
                ref = parent.transformer_blocks[i](
                    torch.from_numpy(x), torch.from_numpy(ctx)
                ).numpy()
            params = jax.tree.map(jnp.asarray, params)
            ours = np.asarray(apply_transformer_block(params, cfg, jnp.asarray(x), jnp.asarray(ctx)))
            np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_injected_blocks_compile_once_per_config(self):
        from deepspeed_tpu.module_inject.diffusers_policies import (
            InjectedDiffusersBlocks, UNetPolicy)

        parent, C, ctx_dim, heads = self._built()
        converted = UNetPolicy.convert(parent.state_dict(), num_heads=heads)
        blocks = InjectedDiffusersBlocks(converted)
        x = jnp.zeros((1, 16, C))
        ctx = jnp.zeros((1, 5, ctx_dim))
        for path in converted:
            blocks(path, x, ctx)
        # identical configs share ONE compiled fn (jit playback ~ the
        # reference's CUDA-graph replay)
        assert len(blocks._fns) == 1


class TestVAEInjection:
    def test_mid_attention_parity(self):
        from deepspeed_tpu.module_inject.diffusers_policies import VAEPolicy
        from deepspeed_tpu.ops.transformer.diffusers_attention import apply_vae_attention

        torch.manual_seed(1)
        C = 64
        parent = nn.Module()
        parent.mid_block = nn.Module()
        parent.mid_block.attentions = nn.ModuleList([_TorchVAEAttn(C)])
        parent.eval()

        state = parent.state_dict()
        paths = VAEPolicy.attention_paths(state)
        assert paths == ["mid_block.attentions.0"]
        cfg, params = VAEPolicy.convert_attention(state, paths[0], num_heads=1)

        rs = np.random.RandomState(0)
        x_nchw = rs.normal(size=(2, C, 8, 8)).astype(np.float32)
        with torch.no_grad():
            ref = parent.mid_block.attentions[0](torch.from_numpy(x_nchw)).numpy()
        params = jax.tree.map(jnp.asarray, params)
        x_nhwc = jnp.asarray(np.transpose(x_nchw, (0, 2, 3, 1)))
        ours = np.asarray(apply_vae_attention(params, cfg, x_nhwc))
        np.testing.assert_allclose(
            np.transpose(ours, (0, 3, 1, 2)), ref, rtol=2e-4, atol=2e-4
        )
