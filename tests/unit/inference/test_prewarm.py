"""dstpu_prewarm CLI: precompile the serving program set into the
persistent XLA cache (cold-start cost on TPU is 20-40s per program through
the remote compiler; the reference ships prebuilt CUDA .so instead)."""

import os

import jax
import pytest

from deepspeed_tpu import comm

TINY = ["--override", "num_layers=2", "--override", "hidden_size=64",
        "--override", "num_heads=4", "--override", "vocab_size=128",
        "--override", "max_seq_len=64"]


@pytest.fixture
def restore_jax_cache_config():
    """prewarm main() redirects the global compile-cache config; later test
    modules must keep the conftest's shared cache."""
    saved = (jax.config.jax_compilation_cache_dir,
             jax.config.jax_persistent_cache_min_compile_time_secs)
    yield
    jax.config.update("jax_compilation_cache_dir", saved[0])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", saved[1])
    try:  # re-point the live cache instance back at the shared dir
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


def test_value_parsing():
    from deepspeed_tpu.inference.prewarm import _parse_value

    assert _parse_value("128") == 128
    assert _parse_value("0.125") == 0.125
    assert _parse_value("true") is True and _parse_value("False") is False
    assert _parse_value("none") is None
    assert _parse_value("rope") == "rope"


def test_prewarm_fused_only(tmp_path, restore_jax_cache_config):
    """FAST sibling: the CLI surface end-to-end on the tiny model, fused
    generate only (the chunk/continuous arms ride the same plumbing and
    are covered by the slow variant)."""
    from deepspeed_tpu.inference.prewarm import main

    comm.destroy()
    cache = str(tmp_path / "xla_cache")
    rc = main(["--batch", "1", "--prompt", "8", "--new", "2",
               "--dtype", "float32", "--cache-dir", cache, *TINY])
    assert rc == 0
    assert os.path.isdir(cache) and os.listdir(cache)


def test_prewarm_mesh_widths(tmp_path, restore_jax_cache_config):
    """--mesh 1:1,1:2 warms the program set once PER tensor width (a
    sharded executable is a distinct program — warming 1:1 does nothing
    for a 1:2 serve); both passes land in the same cache dir."""
    from deepspeed_tpu.inference.prewarm import main

    comm.destroy()
    cache = str(tmp_path / "xla_cache")
    rc = main(["--batch", "1", "--prompt", "8", "--new", "2",
               "--dtype", "float32", "--mesh", "1:1,1:2",
               "--cache-dir", cache, *TINY])
    assert rc == 0
    assert os.path.isdir(cache) and os.listdir(cache)


@pytest.mark.slow  # full serving program set (chunked + continuous pool)
def test_prewarm_full_set_persists(tmp_path, restore_jax_cache_config):
    from deepspeed_tpu.inference.prewarm import main

    comm.destroy()
    cache = str(tmp_path / "xla_cache")
    rc = main([
        "--batch", "1", "--prompt", "16", "--new", "4", "--dtype", "float32",
        "--chunk", "8", "--continuous", "--slots", "2", "--cache-len", "64",
        "--burst", "2", "--cache-dir", cache, *TINY,
    ])
    assert rc == 0
    assert os.path.isdir(cache) and len(os.listdir(cache)) >= 3, \
        os.listdir(cache) if os.path.isdir(cache) else "no cache dir"


def test_prewarm_audit_flag(tmp_path, restore_jax_cache_config, capsys):
    """--audit runs ds-audit over the captured program set at the end of
    the warm and exits 0 when the contracts hold. The fused-generate
    path has no capture site (not a registered family yet), so this
    fast sibling proves the CLI surface + clean exit; the continuous
    arm of the slow test below captures the real pool families."""
    from deepspeed_tpu.analysis.program import capture
    from deepspeed_tpu.inference.prewarm import main

    comm.destroy()
    cache = str(tmp_path / "xla_cache")
    rc = main(["--batch", "1", "--prompt", "8", "--new", "2",
               "--dtype", "float32", "--cache-dir", cache, "--audit", *TINY])
    assert rc == 0
    assert not capture.active()  # the hook was cleared on the way out
    assert "ds-audit over" in capsys.readouterr().out


@pytest.mark.slow  # continuous pool warm + a full audit of its programs
def test_prewarm_audit_captures_pool_programs(tmp_path,
                                              restore_jax_cache_config,
                                              capsys):
    from deepspeed_tpu.inference.prewarm import main

    comm.destroy()
    cache = str(tmp_path / "xla_cache")
    rc = main([
        "--batch", "1", "--prompt", "16", "--new", "4", "--dtype", "float32",
        "--continuous", "--slots", "2", "--cache-len", "64",
        "--cache-dir", cache, "--audit", *TINY,
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "ds-audit over" in out and "clean" in out
    # the pool warm built (and the audit therefore saw) real programs
    import re

    m = re.search(r"ds-audit over (\d+) captured", out)
    assert m and int(m.group(1)) > 0, out
