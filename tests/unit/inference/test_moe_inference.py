"""MoE inference (reference: ops/transformer/inference/moe_inference.py +
module_inject/containers/base_moe.py): expert routing inside the KV-cached
decode path, expert-parallel sharding from the inference config."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import transformer as tf


def _moe_cfg(E=4, **over):
    base = dict(
        vocab_size=128,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        max_seq_len=64,
        dtype="float32",
        moe_num_experts=E,
        moe_top_k=1,
        # big capacity: routing never drops, so cached decode and full
        # forward see identical expert assignments
        moe_capacity_factor=8.0,
        moe_min_capacity=64,
        moe_use_rts=False,
    )
    base.update(over)
    return tf.TransformerConfig(**base)


def _prompt(bs=2, seq=8, vocab=128, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randint(0, vocab, (bs, seq)).astype(np.int32)


class TestMoEInference:
    def test_e1_matches_dense(self):
        """A 1-expert MoE (gate prob == 1) must generate exactly what the
        dense model with the same MLP weights generates."""
        dense_cfg = _moe_cfg(E=0)
        dense_cfg = dataclasses.replace(dense_cfg, moe_num_experts=0)
        moe_cfg = _moe_cfg(E=1)

        dense = tf.TransformerModel(dense_cfg)
        params_d = dense.init(jax.random.PRNGKey(0))

        # transplant dense weights into the 1-expert layout
        params_m = jax.tree.map(lambda x: x, params_d)  # copy structure
        mlp_d = params_d["layers"]["mlp"]
        L = moe_cfg.num_layers
        params_m["layers"]["mlp"] = {
            "gate": jnp.zeros((L, moe_cfg.hidden_size, 1), jnp.float32),
            "wi": mlp_d["wi"][:, None],
            "wo": mlp_d["wo"][:, None],
            "bi": mlp_d["bi"][:, None],
            "bo": mlp_d["bo"][:, None],
        }

        eng_d = deepspeed_tpu.init_inference(
            tf.TransformerModel(dense_cfg), config={"dtype": "float32"}, params=params_d
        )
        eng_m = deepspeed_tpu.init_inference(
            tf.TransformerModel(moe_cfg), config={"dtype": "float32"}, params=params_m
        )
        prompt = _prompt()
        out_d = np.asarray(eng_d.generate(prompt, max_new_tokens=6))
        out_m = np.asarray(eng_m.generate(prompt, max_new_tokens=6))
        np.testing.assert_array_equal(out_d, out_m)

    def test_routed_decode_matches_full_forward(self):
        """Greedy cached decode over an E=4 routed model must agree with the
        uncached full forward at every generated position."""
        cfg = _moe_cfg(E=4)
        model = tf.TransformerModel(cfg)
        params = model.init(jax.random.PRNGKey(1))
        eng = deepspeed_tpu.init_inference(model, config={"dtype": "float32"}, params=params)
        prompt = _prompt(bs=2, seq=6, seed=3)
        out = np.asarray(eng.generate(prompt, max_new_tokens=5))
        assert out.shape == (2, 11)

        logits, _ = tf.forward(jax.tree.map(jnp.asarray, eng.params), cfg, jnp.asarray(out))
        for pos in range(6, 11):
            expect = np.asarray(jnp.argmax(logits[:, pos - 1], axis=-1))
            np.testing.assert_array_equal(out[:, pos], expect, err_msg=f"pos {pos}")

    def test_expert_parallel_sharding(self):
        """moe.ep_size in the inference config creates an expert mesh axis and
        shards expert weights over it (EP dryrun on the virtual mesh)."""
        from deepspeed_tpu import comm

        comm.destroy()
        cfg = _moe_cfg(E=4, dtype="bfloat16")
        model = tf.TransformerModel(cfg)
        eng = deepspeed_tpu.init_inference(
            model, config={"moe": {"enabled": True, "ep_size": 4}, "dtype": "bfloat16"}
        )
        assert eng.mesh.shape["expert"] == 4
        wi_spec = eng.params["layers"]["mlp"]["wi"].sharding.spec
        assert "expert" in jax.tree.leaves(tuple(wi_spec)), wi_spec
        out = eng.generate(_prompt(bs=2, seq=4, seed=5), max_new_tokens=3)
        assert np.asarray(out).shape == (2, 7)
        comm.destroy()

    @pytest.mark.slow  # int8 x EP composition; int8 decode and EP decode are each covered fast
    def test_int8_weight_quant_moe(self):
        """int8 weight-only quantization composes with expert weights."""
        cfg = _moe_cfg(E=2, dtype="bfloat16")
        model = tf.TransformerModel(cfg)
        eng = deepspeed_tpu.init_inference(model, config={"dtype": "int8"})
        out = eng.generate(_prompt(bs=2, seq=4, seed=9), max_new_tokens=3)
        assert np.all(np.isfinite(np.asarray(out)))
