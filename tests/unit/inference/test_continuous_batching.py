"""Continuous (in-flight) batching (inference/continuous.py) — slot-pool
serving beyond the v0.9.1 reference's static-batch generate."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import comm
from deepspeed_tpu.inference.continuous import ContinuousBatchingEngine
from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel


@pytest.fixture(scope="module")
def setup():
    comm.destroy()
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=128, dtype="float32")
    model = TransformerModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    plain = deepspeed_tpu.init_inference(model, params=params, config={"dtype": "float32"})
    return model, params, plain


def _prompts(ns, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).astype(np.int32) for n in ns]


class TestContinuousBatching:
    def test_staggered_admission_matches_plain_generate(self, setup):
        """4 requests through 3 slots, one admitted mid-flight: every
        output must equal the plain engine's greedy generate."""
        model, params, plain = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=3, cache_len=64)
        prompts = _prompts((5, 9, 3, 7))
        refs = [np.asarray(plain.generate(p[None, :], max_new_tokens=8))[0]
                for p in prompts]
        rids = [cb.submit(p, max_new_tokens=8) for p in prompts[:3]]
        cb.step()
        cb.step()
        rids.append(cb.submit(prompts[3], max_new_tokens=8))  # slot reuse
        while cb.has_work():
            cb.step()
        done = cb.finished()
        for rid, want in zip(rids, refs):
            np.testing.assert_array_equal(done[rid], want)

    def test_burst_tick_matches_single_step(self, setup):
        """tokens_per_tick=4 (k decode steps fused into one compiled scan)
        must produce the SAME greedy outputs as the per-token tick,
        including a mid-flight admission and an EOS finishing mid-burst."""
        model, params, plain = setup
        prompts = _prompts((5, 9, 3, 7), seed=2)
        refs = [np.asarray(plain.generate(p[None, :], max_new_tokens=10))[0]
                for p in prompts]
        # eos chosen from request 0's stream so it finishes mid-burst
        eos = int(refs[0][len(prompts[0]) + 2])
        want = {}
        for i, r in enumerate(refs):
            gen = r[len(prompts[i]):]
            cut = np.nonzero(gen == eos)[0]
            end = cut[0] + 1 if cut.size else len(gen)
            want[i] = np.concatenate([prompts[i], gen[:end]])
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=3, cache_len=64,
                                      eos_token_id=eos, tokens_per_tick=4)
        rids = [cb.submit(p, max_new_tokens=10) for p in prompts[:3]]
        cb.step()
        rids.append(cb.submit(prompts[3], max_new_tokens=10))  # slot reuse
        while cb.has_work():
            cb.step()
        done = cb.finished()
        for i, rid in enumerate(rids):
            np.testing.assert_array_equal(done[rid], want[i])

    def test_eos_frees_slot_early(self, setup):
        """A request hitting EOS releases its slot while others continue."""
        model, params, plain = setup
        # pick an EOS id we KNOW the greedy path emits: generate once and
        # use the first generated token of prompt A as the eos id
        prompts = _prompts((4, 6), seed=1)
        probe = np.asarray(plain.generate(prompts[0][None, :], max_new_tokens=1))[0]
        eos = int(probe[-1])
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64,
                                      eos_token_id=eos)
        ra = cb.submit(prompts[0], max_new_tokens=8)
        rb = cb.submit(prompts[1], max_new_tokens=8)
        done = {}
        ticks = 0
        while cb.status(ra) in ("pending", "active"):
            cb.step()
            done.update(cb.finished())
            ticks += 1
        done.update(cb.finished())
        # finished at its very first token (admission tick + the pipelined
        # retire lag), freeing the slot while rb keeps decoding
        assert ticks <= 2 + cb.pipeline_depth
        assert ra in done
        assert len(done[ra]) == len(prompts[0]) + 1 and done[ra][-1] == eos
        assert cb.status(rb) == "active"  # unaffected by ra's early exit
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        out_b = done[rb]
        assert len(out_b) >= len(prompts[1]) + 1

    def test_queue_longer_than_slots_drains(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        rids = [cb.submit(p, max_new_tokens=4) for p in _prompts((3, 4, 5, 6, 7), seed=2)]
        ticks = 0
        while cb.has_work():
            cb.step()
            ticks += 1
            assert ticks < 100, "scheduler did not drain"
        done = cb.finished()
        assert set(done) == set(rids)
        for rid in rids:
            assert len(done[rid]) >= 4

    def test_oversized_request_rejected(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=32)
        with pytest.raises(ValueError, match="cache_len"):
            cb.submit(np.arange(30, dtype=np.int32), max_new_tokens=8)

    def test_step_stream_matches_results(self, setup):
        """Concatenating step() returns per request reproduces the
        generated stream exactly (review r4: the admission tick emits two
        tokens and must return both)."""
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        prompts = _prompts((4, 6, 5), seed=3)
        rids = [cb.submit(p, max_new_tokens=4) for p in prompts]
        streams = {r: [] for r in rids}
        while cb.has_work():
            for rid, toks in cb.step().items():
                streams[rid].extend(toks)
        done = cb.finished()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                np.asarray(streams[rid], np.int32), done[rid][len(p):]
            )

    def test_prefix_caching_exact_parity(self, setup):
        """register_prefix computes the shared-prefix KV once; requests
        submitted with it must match full-prompt greedy generate EXACTLY,
        even while another slot is mid-decode (no cross-slot corruption
        from the suffix segment's parked rows)."""
        model, params, plain = setup
        rs = np.random.RandomState(5)
        prefix = rs.randint(0, 128, (11,)).astype(np.int32)
        sufs = [rs.randint(0, 128, (n,)).astype(np.int32) for n in (4, 7)]
        other = rs.randint(0, 128, (6,)).astype(np.int32)

        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        pid = cb.register_prefix(prefix)
        r_other = cb.submit(other, max_new_tokens=10)
        cb.step()
        cb.step()
        r0 = cb.submit_with_prefix(pid, sufs[0], max_new_tokens=6)
        cb.step()
        r1 = cb.submit_with_prefix(pid, sufs[1], max_new_tokens=6)
        done = {}
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        for rid, full, mnt in [(r0, np.concatenate([prefix, sufs[0]]), 6),
                               (r1, np.concatenate([prefix, sufs[1]]), 6),
                               (r_other, other, 10)]:
            want = np.asarray(plain.generate(full[None, :], max_new_tokens=mnt))[0]
            np.testing.assert_array_equal(done[rid], want)

    def test_prefix_capacity_checked(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=32)
        pid = cb.register_prefix(np.arange(20, dtype=np.int32) % 128)
        with pytest.raises(ValueError, match="cache_len"):
            cb.submit_with_prefix(pid, np.arange(8, dtype=np.int32), max_new_tokens=8)

    def test_zero_max_new_tokens_rejected(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        with pytest.raises(ValueError, match="max_new_tokens"):
            cb.submit(np.arange(4, dtype=np.int32), max_new_tokens=0)
        with pytest.raises(ValueError, match="empty prompt"):
            cb.submit([], max_new_tokens=4)

    def test_unregister_prefix_releases(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        p1 = cb.register_prefix(np.arange(5, dtype=np.int32))
        p2 = cb.register_prefix(np.arange(7, dtype=np.int32))
        assert p1 != p2
        cb.unregister_prefix(p1)
        assert p1 not in cb._prefixes and p2 in cb._prefixes
        p3 = cb.register_prefix(np.arange(3, dtype=np.int32))
        assert p3 not in (p1, p2)  # counter-based ids are never recycled
        with pytest.raises(KeyError):
            cb.submit_with_prefix(p1, np.arange(2, dtype=np.int32))
        with pytest.raises(KeyError, match="unknown prefix id"):
            cb.unregister_prefix(p1)  # double release fails loudly, names the id

    def test_unregister_does_not_strand_queued_request(self, setup):
        """A submit_with_prefix request still in the queue must survive
        unregister_prefix (the entry is snapshotted at submit time)."""
        model, params, plain = setup
        rs = np.random.RandomState(9)
        prefix = rs.randint(0, 128, (6,)).astype(np.int32)
        suffix = rs.randint(0, 128, (4,)).astype(np.int32)
        blockers = [rs.randint(0, 128, (3,)).astype(np.int32) for _ in range(2)]
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=2, cache_len=64)
        pid = cb.register_prefix(prefix)
        for b in blockers:  # fill both slots so the prefix request queues
            cb.submit(b, max_new_tokens=6)
        cb.step()
        rid = cb.submit_with_prefix(pid, suffix, max_new_tokens=4)
        cb.unregister_prefix(pid)  # while rid is still pending
        done = {}
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        full = np.concatenate([prefix, suffix])
        want = np.asarray(plain.generate(full[None, :], max_new_tokens=4))[0]
        np.testing.assert_array_equal(done[rid], want)
        with pytest.raises(ValueError, match="max_new_tokens"):
            cb.submit_with_prefix(cb.register_prefix(prefix), suffix, max_new_tokens=0)


class TestRequestLifecycle:
    """status/peek/result/cancel — the polling + cancellation surface the
    serving layer (deepspeed_tpu/serving) is built on."""

    def test_status_and_peek_across_lifecycle(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=1, cache_len=64)
        p_a, p_b = _prompts((4, 5), seed=7)
        # admission emits 1 token and the same step() decodes 1 more, so
        # max_new_tokens=4 keeps the request active past the first tick
        ra = cb.submit(p_a, max_new_tokens=4)
        rb = cb.submit(p_b, max_new_tokens=4)  # queues behind ra (1 slot)
        assert cb.status(ra) == "pending" and cb.status(rb) == "pending"
        cb.step()
        assert cb.status(ra) == "active" and cb.status(rb) == "pending"
        assert cb.peek(ra) is None  # not finished: peek stays empty
        while cb.status(ra) in ("pending", "active"):
            cb.step()
        assert cb.status(ra) == "finished"
        got = cb.peek(ra)
        assert got is not None and len(got) == len(p_a) + 4
        np.testing.assert_array_equal(cb.result(ra), got)  # peek didn't consume
        assert cb.status(ra) == "unknown"  # collected
        assert cb.status(12345) == "unknown"
        while cb.has_work():
            cb.step()
        cb.finished()

    def test_result_error_names_rid_and_state(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=1, cache_len=64)
        rid = cb.submit(_prompts((4,), seed=8)[0], max_new_tokens=4)
        with pytest.raises(KeyError, match=f"request {rid}: pending"):
            cb.result(rid)
        cb.step()  # admission + first decode: 2 of 4 tokens, still active
        with pytest.raises(KeyError, match=f"request {rid}: active"):
            cb.result(rid)
        with pytest.raises(KeyError, match="request 999: unknown"):
            cb.result(999)
        while cb.has_work():
            cb.step()
        cb.finished()

    def test_cancel_pending_and_active_frees_slot(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=1, cache_len=64)
        p_a, p_b, p_c = _prompts((4, 5, 6), seed=9)
        ra = cb.submit(p_a, max_new_tokens=8)
        rb = cb.submit(p_b, max_new_tokens=8)
        cb.step()
        assert cb.cancel(rb) is True          # pending: leaves the queue
        assert cb.status(rb) == "cancelled" and not cb._pending
        assert cb.cancel(ra) is True          # active: frees the slot NOW
        assert cb.status(ra) == "cancelled"
        assert cb.pool_state() == [{"length": 64, "slots": 1, "free": 1}]
        rc = cb.submit(p_c, max_new_tokens=2)  # freed slot is reusable
        while cb.has_work():
            cb.step()
        out = cb.finished()
        assert set(out) == {rc}
        assert len(out[rc]) == len(p_c) + 2
        assert cb.cancel(rc) is False          # already collected: too late
        with pytest.raises(KeyError, match="cancelled"):
            cb.result(ra)

    def test_cancelled_memory_is_bounded(self, setup):
        """A long-running server cancels routinely; the engine remembers
        only a bounded window of cancelled rids (evicted ones age back to
        'unknown', same as collected results)."""
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      max_slots=1, cache_len=64)
        cb._cancelled_cap = 4
        prompt = _prompts((3,), seed=10)[0]
        rids = []
        for _ in range(6):  # cancel while pending: no decode involved
            rid = cb.submit(prompt, max_new_tokens=2)
            assert cb.cancel(rid) is True
            rids.append(rid)
        assert len(cb._cancelled) == 4
        assert cb.status(rids[0]) == "unknown"   # evicted
        assert cb.status(rids[-1]) == "cancelled"


class TestBucketedKV:
    """cache_buckets (VERDICT r4 #9): slot pools with different cache
    lengths — static-shape TPU analogue of paged KV. Footprint shrinks to
    sum(slots_i * len_i); outputs must match the fixed-slot engine."""

    def test_parity_with_fixed_slots(self, setup):
        """Mixed-length requests through bucketed pools equal the plain
        engine's greedy generate (and therefore the fixed-slot engine)."""
        model, params, plain = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      cache_buckets=[(2, 32), (1, 64)])
        prompts = _prompts((5, 9, 3, 20), seed=3)
        refs = [np.asarray(plain.generate(p[None, :], max_new_tokens=8))[0]
                for p in prompts]
        rids = [cb.submit(p, max_new_tokens=8) for p in prompts]
        done = {}
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        for rid, ref in zip(rids, refs):
            np.testing.assert_array_equal(done[rid], ref)

    def test_placement_smallest_fit_with_fallback(self, setup):
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      cache_buckets=[(1, 32), (1, 64)])
        short1, short2, long1 = _prompts((4, 6, 40), seed=4)
        r_short1 = cb.submit(short1, max_new_tokens=4)
        r_long = cb.submit(long1, max_new_tokens=8)   # only fits pool 1
        cb.step()
        assert cb._pools[0].active and cb._pools[1].active
        assert cb._pools[0].active[0].rid == r_short1
        assert cb._pools[1].active[0].rid == r_long
        # short pool full; a second short request falls back to... nothing
        # free -> queues; after the short request finishes it is admitted
        r_short2 = cb.submit(short2, max_new_tokens=4)
        done = {}
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        assert set(done) == {r_short1, r_long, r_short2}

    def test_long_request_does_not_block_short_behind_it(self, setup):
        """FIFO-with-skip: a queued long request waiting for the long pool
        must not starve short requests that fit the free short pool."""
        model, params, _ = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      cache_buckets=[(1, 32), (1, 64)])
        long_a, long_b, short = _prompts((40, 44, 4), seed=5)
        cb.submit(long_a, max_new_tokens=8)
        r_b = cb.submit(long_b, max_new_tokens=8)   # queues behind long_a
        r_s = cb.submit(short, max_new_tokens=6)    # must skip ahead
        cb.step()
        assert cb._pools[0].active[0].rid == r_s, "short request was blocked"
        assert any(r.rid == r_b for r in cb._pending)
        while cb.has_work():
            cb.step()
        assert not cb._pending

    def test_footprint_shrinks_vs_fixed(self, setup):
        """The PERF.md footprint claim: bucketed pools hold strictly fewer
        KV bytes than the same slot count at the max length."""
        model, params, _ = setup
        fixed = ContinuousBatchingEngine(model, params=params,
                                         config={"dtype": "float32"},
                                         max_slots=4, cache_len=128)
        bucketed = ContinuousBatchingEngine(model, params=params,
                                            config={"dtype": "float32"},
                                            cache_buckets=[(3, 32), (1, 128)])
        assert fixed.kv_cache_bytes() == 4 * 128 * _kv_row_bytes(model.cfg)
        assert bucketed.kv_cache_bytes() == (3 * 32 + 128) * _kv_row_bytes(model.cfg)
        assert bucketed.kv_cache_bytes() < 0.45 * fixed.kv_cache_bytes()

    def test_prefix_respects_pool_length(self, setup):
        """A prefix whose splice bucket exceeds a short pool must be placed
        in a pool that can hold the full bucket-length slice."""
        model, params, plain = setup
        cb = ContinuousBatchingEngine(model, params=params,
                                      config={"dtype": "float32"},
                                      cache_buckets=[(1, 16), (1, 64)])
        prefix, suffix = _prompts((20, 4), seed=6)
        pid = cb.register_prefix(prefix)          # bucket = 32 > short pool
        rid = cb.submit_with_prefix(pid, suffix, max_new_tokens=4)
        done = {}
        while cb.has_work():
            cb.step()
            done.update(cb.finished())
        full = np.concatenate([prefix, suffix])
        ref = np.asarray(plain.generate(full[None, :], max_new_tokens=4))[0]
        np.testing.assert_array_equal(done[rid], ref)


def _kv_row_bytes(cfg):
    """bytes of one (layer-stacked) KV row per cached position."""
    kv_heads = cfg.kv_heads
    hd = cfg.head_dim
    return 2 * cfg.num_layers * kv_heads * hd * 4  # k+v, fp32
