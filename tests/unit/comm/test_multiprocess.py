"""2-process DistributedTest equivalent (VERDICT r4 #3).

The reference forks N processes with a localhost TCP-store rendezvous
(tests/unit/common.py:277 DistributedTest). Every other test in this suite
uses the in-process 8-device virtual mesh, which cannot exercise the
multi-controller surfaces; this one actually spawns 2 OS processes x 4 CPU
devices that rendezvous through jax.distributed.initialize
(comm/comm.py _maybe_init_multi_controller, driven by the same DSTPU_* env
the launcher sets) and proves:

- the coordinator join + one global 8-device mesh across 2 processes,
- TpuDataLoader per-process striding (runtime/dataloader.py),
- engine batch globalization from process-local rows (engine._shard_batch
  via jax.make_array_from_process_local_data),
- Orbax multi-process save -> load -> loss parity,
- loss parity with the single-process 8-device run on the same data/seed.
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(os.path.dirname(HERE)))


def _load_worker_module():
    spec = importlib.util.spec_from_file_location(
        "mp_worker", os.path.join(HERE, "mp_worker.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(port: int, pid: int, mesh_json=None) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # parent may force a device count
    env["PALLAS_AXON_POOL_IPS"] = ""  # never touch the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".pytest_jax_cache")
    env["DSTPU_REPO_ROOT"] = REPO
    env["DSTPU_COORDINATOR"] = f"127.0.0.1:{port}"
    env["DSTPU_NUM_PROCESSES"] = "2"
    env["DSTPU_PROCESS_ID"] = str(pid)
    if mesh_json:
        env["DSTPU_TEST_MESH"] = mesh_json
    return env


class TestTwoProcessDistributed:
    # default mesh: cross-process DATA-parallel collectives + per-process
    # batch striding. {"tensor": 8}: TP spans the process boundary (matmul
    # partial-sum psums over "DCN") with a replicated dp=1 batch both
    # processes must feed identically.
    @pytest.mark.xfail(
        reason="this jaxlib's CPU backend cannot execute multi-controller "
               "computations: the worker dies in engine build with "
               "XlaRuntimeError 'Multiprocess computations aren't "
               "implemented on the CPU backend' (pre-existing since seed; "
               "mp_worker.py's device-count setup was additionally fixed "
               "for jax<0.4.38 in PR 10 — the backend limitation is what "
               "remains). Runs on real multi-host TPU or a newer jaxlib. "
               "docs/known_failures.md", strict=False)
    @pytest.mark.parametrize("mesh_json", [None, '{"tensor": 8}'],
                             ids=["data-fsdp", "tensor-spanning"])
    def test_train_save_load_parity(self, tmp_path, mesh_json, monkeypatch):
        # --- single-process 8-device reference on the same data/config ---
        from deepspeed_tpu import comm

        comm.destroy()
        if mesh_json:
            monkeypatch.setenv("DSTPU_TEST_MESH", mesh_json)
        w = _load_worker_module()
        engine, _, loader, _ = w.build_engine()
        ref_losses = []
        it = iter(loader)
        for _ in range(w.STEPS):
            batch = next(it)
            loss = engine.forward(batch)
            engine.backward(loss)
            engine.step()
            ref_losses.append(float(loss))
        probe = w.collate(w.build_dataset()[: w.GLOBAL_BS])
        ref_trained = float(engine.eval_batch(probe))

        # --- 2 real processes x 4 CPU devices, localhost coordinator ------
        port = _free_port()
        ckpt = str(tmp_path / "ckpt")
        outs = [str(tmp_path / f"out{i}.json") for i in range(2)]
        procs = [
            subprocess.Popen(
                [sys.executable, os.path.join(HERE, "mp_worker.py"), outs[i], ckpt],
                env=_worker_env(port, i, mesh_json),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        logs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=420)
                logs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            # only communicate() with the killed stragglers: a pipe already
            # drained by a successful communicate() is closed and would
            # raise, masking the logs collected so far
            for p in procs[len(logs):]:
                try:
                    logs.append(p.communicate()[0])
                except ValueError:
                    logs.append("<no output captured>")
            pytest.fail("2-process workers hung (coordinator rendezvous or "
                        "collective deadlock):\n"
                        + "\n".join(log[-2000:] for log in logs))
        for p, log in zip(procs, logs):
            assert p.returncode == 0, f"worker rc={p.returncode}:\n{log[-4000:]}"

        results = []
        for o in outs:
            with open(o) as fh:
                results.append(json.load(fh))
        by_pid = {r["process_index"]: r for r in results}
        assert set(by_pid) == {0, 1}
        for r in results:
            assert r["process_count"] == 2
            assert r["device_count"] == 8
            assert r["local_device_count"] == 4
            assert r["global_steps"] == w.STEPS

        # both processes observed the same (replicated) global loss
        np.testing.assert_allclose(by_pid[0]["losses"], by_pid[1]["losses"],
                                   rtol=1e-6)
        # parity with the single-process 8-device run: same data, same
        # mesh logical shape -> same math (reduction order may differ)
        np.testing.assert_allclose(by_pid[0]["losses"], ref_losses, rtol=1e-4)
        np.testing.assert_allclose(by_pid[0]["loss_trained"], ref_trained,
                                   rtol=1e-4)
        # Orbax multi-process round-trip restored the trained state exactly
        for r in results:
            np.testing.assert_allclose(r["loss_restored"], r["loss_trained"],
                                       rtol=1e-6)
