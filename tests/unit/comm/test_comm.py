"""Mesh + collective facade tests (reference: tests/unit/comm/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:
    from jax.experimental.shard_map import shard_map

from deepspeed_tpu import comm


def test_default_mesh_all_data():
    comm.destroy()
    mesh = comm.init_distributed(verbose=False)
    assert mesh.shape["data"] == jax.device_count()
    assert comm.get_world_size() == jax.device_count()
    assert comm.get_rank() == 0


def test_mesh_shape_wildcard():
    comm.destroy()
    mesh = comm.init_distributed(mesh_shape={"data": 2, "tensor": -1}, verbose=False)
    assert mesh.shape["data"] == 2
    assert mesh.shape["tensor"] == jax.device_count() // 2


def test_mesh_shape_invalid():
    comm.destroy()
    with pytest.raises(ValueError):
        comm.init_distributed(mesh_shape={"data": 3}, verbose=False)
    comm.destroy()
    with pytest.raises(ValueError):
        comm.init_distributed(mesh_shape={"bogus_axis": 2}, verbose=False)


def test_all_reduce_inside_shard_map():
    comm.destroy()
    mesh = comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
    n = mesh.shape["data"]
    x = jnp.arange(n, dtype=jnp.float32)

    def f(x):
        return comm.all_reduce(x, group="data")

    y = shard_map(f, mesh=mesh, in_specs=PartitionSpec("data"), out_specs=PartitionSpec("data"))(x)
    np.testing.assert_allclose(np.asarray(y), np.full(n, x.sum()))


def test_reduce_scatter_matches_allreduce_shard():
    comm.destroy()
    mesh = comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
    n = mesh.shape["data"]
    x = jnp.arange(n * n, dtype=jnp.float32).reshape(n, n)

    def f(x):  # each rank holds one row; scatter the sum
        return comm.reduce_scatter(x.reshape(-1), group="data").reshape(1, -1)

    y = shard_map(f, mesh=mesh, in_specs=PartitionSpec("data"), out_specs=PartitionSpec("data"))(x)
    expected = np.asarray(x).sum(axis=0).reshape(n, -1).sum(axis=1)  # summed rows, chunked
    np.testing.assert_allclose(np.asarray(y).reshape(-1), np.asarray(x).sum(0))


def test_all_to_all_transposes_shards():
    comm.destroy()
    mesh = comm.init_distributed(mesh_shape={"expert": -1, "data": 1}, verbose=False)
    n = mesh.shape["expert"]
    x = jnp.arange(n * n, dtype=jnp.float32).reshape(n, n)

    def f(x):
        return comm.all_to_all(x, group="expert", split_axis=1, concat_axis=0)

    y = shard_map(f, mesh=mesh, in_specs=PartitionSpec("expert", None), out_specs=PartitionSpec("expert", None))(x)
    # rank r ends up holding column r => global result is x transposed
    np.testing.assert_allclose(np.asarray(y).reshape(n, n), np.asarray(x).T)


def test_hybrid_dcn_mesh_shapes():
    """Multi-slice mesh: per-axis size = dcn x ici, DCN outermost (the
    scaling-book layout: data over DCN, fsdp/tensor intra-slice)."""
    from deepspeed_tpu import comm

    comm.destroy()
    mesh = comm.init_distributed(
        mesh_shape={"data": 1, "fsdp": 4}, dcn_mesh_shape={"data": 2}, verbose=False
    )
    assert mesh.shape["data"] == 2 and mesh.shape["fsdp"] == 4
    # DCN-outer layout: the two data-axis groups are contiguous device blocks
    devs = mesh.devices.reshape(2, 4)
    ids = [[d.id for d in row] for row in devs]
    assert ids[0] == sorted(ids[0]) and max(ids[0]) < min(ids[1])


def test_hybrid_dcn_mesh_via_config_key():
    from deepspeed_tpu import comm

    comm.destroy()
    mesh = comm.init_distributed(
        mesh_shape={"data": 1, "fsdp": 2, "tensor": 2, "dcn": {"data": 2}}, verbose=False
    )
    assert dict(mesh.shape)["data"] == 2
    assert mesh.devices.size == 8


def test_hybrid_dcn_mesh_trains():
    import deepspeed_tpu
    import jax.numpy as jnp
    from deepspeed_tpu import comm

    comm.destroy()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"data": 1, "fsdp": 4, "dcn": {"data": 2}},
    }

    def loss_fn(params, batch, rng):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    params = {"w": jnp.ones((8, 8), jnp.float32)}
    engine, *_ = deepspeed_tpu.initialize(loss_fn=loss_fn, params=params, config=cfg)
    batch = {"x": np.ones((8, 8), np.float32)}
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 1


def test_broadcast_from_src():
    comm.destroy()
    mesh = comm.init_distributed(mesh_shape={"data": -1}, verbose=False)
    n = mesh.shape["data"]
    x = jnp.arange(n, dtype=jnp.float32) + 1.0

    def f(x):
        return comm.broadcast(x, src=2, group="data")

    y = shard_map(f, mesh=mesh, in_specs=PartitionSpec("data"), out_specs=PartitionSpec("data"))(x)
    np.testing.assert_allclose(np.asarray(y), np.full(n, 3.0))


def test_group_world_sizes():
    comm.destroy()
    comm.init_distributed(mesh_shape={"data": 2, "fsdp": 2, "tensor": 2}, verbose=False)
    assert comm.get_world_size("data") == 2
    assert comm.get_world_size(("data", "fsdp")) == 4
    assert comm.get_world_size() == 8
    assert comm.dp_world_size() == 4


def test_dstpu_bench_comm_sweep():
    """dstpu_bench (reference bin/ds_bench): every collective produces a
    bandwidth record over the sweep on the virtual mesh."""
    from deepspeed_tpu import comm
    from deepspeed_tpu.launcher.bench_comm import run

    comm.destroy()
    report = run(sizes_mb=[0.125], iters=1, axis="data")
    assert report["devices"] == 8
    ops = {r["op"] for r in report["results"]}
    assert ops == {"all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute"}
    for r in report["results"]:
        assert "error" not in r, r
        assert r["algbw_gbps"] >= 0 and r["busbw_gbps"] >= 0
