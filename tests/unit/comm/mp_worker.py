"""Worker + shared fixtures for the 2-process DistributedTest equivalent.

TPU translation of the reference's forked-process harness
(tests/unit/common.py:277 DistributedTest, :132 forkserver + localhost
rendezvous): the parent test spawns 2 of these workers, each with 4 virtual
CPU devices; they join a jax.distributed coordinator through the SAME env
surface the dstpu launcher sets (DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID,
consumed by comm._maybe_init_multi_controller), build one global 8-device
mesh, stride the dataloader per process, train, checkpoint through Orbax
multi-process save/load, and report losses for parity against the
single-process 8-device run.

Run directly:  python mp_worker.py <out_json> <ckpt_dir>
(with the DSTPU_* env set by the parent test)
"""

import json
import os
import sys

# DSTPU_TEST_MESH selects the parallelism under test: the default exercises
# cross-process DATA-parallel collectives; {"tensor": 8} exercises
# cross-process TENSOR-parallel collectives (matmul partial-sum psums over
# the process boundary) with a replicated batch both processes must feed
# identically (dataloader dp=1 path).
MESH = json.loads(os.environ.get("DSTPU_TEST_MESH", '{"data": 2, "fsdp": 4}'))
DP = MESH.get("data", 1) * MESH.get("fsdp", 1)
MICRO_BS = 2  # >1 so the tensor mesh hits batch%nprocs==0 with dp=1 — the
#               loader must NOT stride there (the engine passes
#               process_shard=False); regression for a silent wrong-data bug
GLOBAL_BS = MICRO_BS * DP
SEQ = 16
VOCAB = 64
STEPS = 2


def build_dataset():
    import numpy as np

    rs = np.random.RandomState(1234)
    return [rs.randint(0, VOCAB, (SEQ,)).astype(np.int32) for _ in range(GLOBAL_BS * STEPS)]


def collate(rows):
    import numpy as np

    return {"input_ids": np.stack(rows)}


def build_engine():
    import deepspeed_tpu

    from deepspeed_tpu.models.transformer import TransformerConfig, TransformerModel

    # heads/dims divide every mesh under test (tensor up to 8)
    cfg = TransformerConfig(
        vocab_size=VOCAB, hidden_size=64, num_layers=2, num_heads=8,
        max_seq_len=SEQ, dtype="float32",
    )
    config = {
        "train_micro_batch_size_per_gpu": MICRO_BS,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "mesh": MESH,
        "steps_per_print": 1000000,
    }
    return deepspeed_tpu.initialize(
        model=TransformerModel(cfg), config=config, training_data=build_dataset(),
        collate_fn=collate,
    )


def run(out_path: str, ckpt_dir: str):
    import jax

    engine, _, loader, _ = build_engine()
    assert engine.mesh.devices.size == 8, dict(engine.mesh.shape)
    losses = []
    it = iter(loader)
    for _ in range(STEPS):
        batch = next(it)
        loss = engine.forward(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    engine.save_checkpoint(ckpt_dir, tag="mp")
    # a fresh engine restores the trained state and reproduces the loss on
    # a fixed batch — proves Orbax multi-process save produced a loadable,
    # consistent checkpoint (not just rank-0's shards)
    engine2, _, _, _ = build_engine()
    engine2.load_checkpoint(ckpt_dir, tag="mp")
    probe = collate(build_dataset()[:GLOBAL_BS])
    l_trained = float(engine.eval_batch(probe))
    l_restored = float(engine2.eval_batch(probe))
    result = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "losses": losses,
        "loss_trained": l_trained,
        "loss_restored": l_restored,
        "global_steps": engine.global_steps,
    }
    with open(out_path, "w") as fh:
        json.dump(result, fh)
    print("WORKER_OK", json.dumps(result), flush=True)


if __name__ == "__main__":
    # 4 virtual CPU devices per process, BEFORE the backend initializes.
    # jax < 0.4.38 has no jax_num_cpu_devices config option — there the
    # device count is only reachable through the XLA flag, which must be
    # in the environment before the first jax import touches the backend
    # (same fallback as tests/conftest.py).
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:
        pass  # older jax: the XLA flag above already set the count
    sys.path.insert(0, os.environ["DSTPU_REPO_ROOT"])
    run(sys.argv[1], sys.argv[2])
