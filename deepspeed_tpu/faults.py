"""Deterministic fault injection shared by the serving AND training columns.

TPU pods are preemptible by design: a tick dispatch can raise, a device
fetch can hang, a whole engine can vanish mid-generation — or mid-step.
This module makes those failures *expressible and replayable* so both
recovery layers (serving/engine.py "Fault tolerance", runtime/resilience.py
TrainSupervisor) can be tested to the same bitwise-parity bar as every
perf change:

- a **fault plan** is a seeded, deterministic schedule of faults keyed on
  a monotonically increasing clock — the global serving tick for the
  serving domain, the global optimizer step for the train domain —
  replayable JSONL exactly like the loadgen workloads (``dump``/``load``
  round-trip, ``synth`` for seeded random plans);
- an **injector** is the plan, armed: installed as the engine's
  ``fault_hook`` (an explicit injection point the engine calls — no
  monkeypatching), it raises the planned exception when its clock value
  comes up.

Two domains instantiate the machinery:

=======  =========================================  =======================
domain   hook points                                clock
=======  =========================================  =======================
serving  ``dispatch`` / ``retire`` / ``set_row``    serving ticks, counted
         (:data:`HOOK_POINTS`)                      by the injector itself
train    ``micro_dispatch`` / ``step_fetch`` /      global optimizer step,
         ``checkpoint_write`` / ``preempt``         read from ``info``
         (:data:`TRAIN_HOOK_POINTS`)
=======  =========================================  =======================

The exception taxonomies the recovery ladders decide by:

- serving: :class:`TickDispatchError` (raised before any engine mutation —
  retryable), :class:`FetchHang` (poisons the tick pipeline → rebuild),
  :class:`EnginePreempted` (whole-engine loss, optionally degraded).
- train: :class:`MicroDispatchError` (raised at the top of a micro-step,
  before the RNG splits or ``grad_acc`` is donated — cleanly retryable),
  :class:`StepFetchHang` (the loss/grad-norm fetch hung past the
  watchdog — in-flight state is poisoned, rebuild from snapshot),
  :class:`TornCheckpointWrite` (the commit marker was never placed — the
  tag on disk is torn and must be refused at load),
  :class:`TrainPreempted` (process loss; host snapshots are gone, resume
  comes from the last committed tag on disk, optionally at a degraded
  chip count).

The train domain additionally carries **numeric fault kinds**
(:data:`TRAIN_NUMERIC_KINDS`: ``grad_bitflip`` / ``nan_loss`` /
``data_poison``) that model *silent* corruption — the math going wrong
without anything raising. They fire through the same hook points and
the same replayable plans, but instead of raising, the injector hands
the fired record back to the hook site, which applies the mutation
(flip one mantissa/exponent bit in a grad leaf, NaN the loss, scale a
batch into garbage). Nothing in the control flow fails: only the
``NumericSentinel`` (runtime/numerics.py) can catch these, which is the
point. ``synth`` draws from the exception kinds only
(:attr:`TrainFault.SYNTH_KINDS`) — numeric kinds are opted into
explicitly via ``kinds=`` so legacy chaos soaks stay corruption-free.

Deliberately jax-free (stdlib + numpy, like the supervisor policies):
plans are authored, validated and round-tripped without paying a jax
import — tools/ci_jaxfree_tests.py enforces it.
``serving/faults.py`` re-exports the serving domain unchanged.
"""

import json
import random
from dataclasses import dataclass, field
from typing import ClassVar, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# exception taxonomy — serving
# ---------------------------------------------------------------------------


class InjectedFault(RuntimeError):
    """Base class for injected faults; ``fault`` carries the plan entry
    that fired (tick/step, kind, point)."""

    def __init__(self, message: str, fault: Optional[dict] = None):
        super().__init__(message)
        self.fault = fault or {}


class TickDispatchError(InjectedFault):
    """A transient tick-dispatch failure raised at the ``dispatch`` hook,
    BEFORE the engine mutates any state — the retryable fault class."""


class FetchHang(InjectedFault, TimeoutError):
    """A device fetch that hung past the watchdog (injected stand-in for
    the real ``fetch_timeout_s`` timeout): the in-flight tick's results
    are unrecoverable, the engine is poisoned."""


class EnginePreempted(InjectedFault):
    """Whole-engine preemption (the pod slice was reclaimed). ``degrade``
    signals the replacement must be smaller — the graceful-degradation
    path rebuilds on the next configured subset mesh."""

    def __init__(self, message: str, fault: Optional[dict] = None,
                 degrade: bool = False):
        super().__init__(message, fault)
        self.degrade = degrade


# ---------------------------------------------------------------------------
# exception taxonomy — train
# ---------------------------------------------------------------------------


class MicroDispatchError(InjectedFault):
    """A transient micro-step dispatch failure raised at the
    ``micro_dispatch`` hook, BEFORE the engine consumed its RNG or donated
    ``grad_acc`` — the cleanly retryable train fault class (same batch,
    same RNG: a retried micro-step is bitwise the micro-step)."""


class StepFetchHang(InjectedFault, TimeoutError):
    """The optimizer-step metrics fetch (loss / grad-norm / overflow flag)
    hung past ``fetch_timeout_s``: the in-flight step's host view is
    unrecoverable and the engine is poisoned — rebuild from the last
    committed snapshot."""


class TornCheckpointWrite(InjectedFault):
    """The process died (or the writer failed) between the array commit
    and the commit-marker placement: the tag on disk is torn/markerless
    and ``load_checkpoint`` must refuse it."""


class TrainPreempted(InjectedFault):
    """Whole-process preemption mid-training: host snapshot buffers are
    lost with the process, so resume restores the last *committed* tag
    from disk. ``degrade`` signals the replacement slice is smaller — the
    supervisor escalates through the elastic triad recompute."""

    def __init__(self, message: str, fault: Optional[dict] = None,
                 degrade: bool = False):
        super().__init__(message, fault)
        self.degrade = degrade


# ---------------------------------------------------------------------------
# generic machinery
# ---------------------------------------------------------------------------


@dataclass
class PlannedFault:
    """One planned fault: fires at the first hook call at ``point`` whose
    clock has reached ``tick``, then ``count - 1`` more consecutive times
    (``count > 1`` models a persistent failure that exhausts the retry
    budget and forces escalation). Domain subclasses pin ``KINDS`` (fault
    kind → natural hook point), ``POINTS`` and the JSONL ``TICK_KEY``."""

    tick: int
    kind: str
    point: str = ""         # defaults to the kind's natural hook point
    count: int = 1
    degrade: bool = False   # preempt only: replacement capacity must shrink
    fired: int = field(default=0, compare=False)

    KINDS: ClassVar[Dict[str, str]] = {}
    POINTS: ClassVar[Tuple[str, ...]] = ()
    TICK_KEY: ClassVar[str] = "tick"
    # domain-specific payload fields round-tripped through JSONL when
    # they differ from their dataclass default
    EXTRA_FIELDS: ClassVar[Tuple[str, ...]] = ()
    # kinds ``synth`` draws from by default ("" sentinel = all of KINDS)
    SYNTH_KINDS: ClassVar[Tuple[str, ...]] = ()

    def __post_init__(self):
        cls = type(self)
        if self.kind not in cls.KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {sorted(cls.KINDS)})")
        if not self.point:
            self.point = cls.KINDS[self.kind]
        if self.point not in cls.POINTS:
            raise ValueError(f"unknown hook point {self.point!r} "
                             f"(choose from {cls.POINTS})")
        if self.tick < 0:
            raise ValueError(f"fault {cls.TICK_KEY} must be >= 0")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")

    def to_dict(self) -> dict:
        cls = type(self)
        out = {cls.TICK_KEY: self.tick, "kind": self.kind,
               "point": self.point}
        if self.count != 1:
            out["count"] = self.count
        if self.degrade:
            out["degrade"] = True
        for name in cls.EXTRA_FIELDS:
            value = getattr(self, name)
            if value != cls.__dataclass_fields__[name].default:
                out[name] = value
        return out


class PlannedFaultSchedule:
    """An ordered, replayable schedule of :class:`PlannedFault` entries."""

    fault_cls = PlannedFault

    def __init__(self, faults: List[PlannedFault]):
        self.faults = sorted(faults, key=lambda f: (f.tick, f.point, f.kind))

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self):
        return iter(self.faults)

    @classmethod
    def synth(cls, seed: int = 0, n_faults: int = 3, first_tick: int = 2,
              tick_span: int = 100, kinds: Optional[List[str]] = None,
              degrade_last: bool = False):
        """A seeded random plan: ``n_faults`` faults uniformly over
        ``[first_tick, first_tick + tick_span)``, kinds drawn from
        ``kinds`` (default: the domain's full taxonomy). Fully determined
        by ``seed`` — the chaos-soak analogue of ``synth_workload``."""
        rng = random.Random(seed)
        kinds = list(kinds or cls.fault_cls.SYNTH_KINDS
                     or cls.fault_cls.KINDS)
        ticks = sorted(rng.randrange(first_tick, first_tick + tick_span)
                       for _ in range(n_faults))
        faults = [cls.fault_cls(tick=t, kind=rng.choice(kinds))
                  for t in ticks]
        if degrade_last and faults:
            faults[-1].kind = "preempt"
            faults[-1].point = cls.fault_cls.KINDS["preempt"]
            faults[-1].degrade = True
        return cls(faults)

    def dump(self, path: str):
        """Write the plan as replayable JSONL (one fault per line)."""
        with open(path, "w") as fh:
            for f in self.faults:
                fh.write(json.dumps(f.to_dict()) + "\n")

    @classmethod
    def load(cls, path: str):
        key = cls.fault_cls.TICK_KEY
        faults = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                tick = rec.get(key, rec.get("tick"))
                extras = {name: rec[name]
                          for name in cls.fault_cls.EXTRA_FIELDS
                          if name in rec}
                faults.append(cls.fault_cls(
                    tick=int(tick), kind=rec["kind"],
                    point=rec.get("point", ""),
                    count=int(rec.get("count", 1)),
                    degrade=bool(rec.get("degrade", False)),
                    **extras))
        if not faults:
            raise ValueError(f"no fault records in {path}")
        return cls(faults)


class PlannedFaultInjector:
    """A fault plan, armed as an engine fault hook.

    Install with ``engine.fault_hook = injector``; the engine calls
    ``injector(point, info)`` at each hook point and the injector raises
    the planned exception when a fault is due. How the clock advances is
    the domain's choice: the serving injector counts ticks ITSELF (one
    per ``dispatch`` call) so a single plan stays meaningful across
    engine rebuilds; the train injector reads the global optimizer step
    from ``info`` so the clock survives rebuilds for free (a restored
    engine resumes the step counter)."""

    tick_point: ClassVar[Optional[str]] = None   # hook point that counts
    tick_info_key: ClassVar[Optional[str]] = None  # info key that sets it
    tick_label: ClassVar[str] = "tick"
    info_renames: ClassVar[Dict[str, str]] = {}
    EXCEPTIONS: ClassVar[Dict[str, type]] = {}
    PREEMPT_EXCEPTION: ClassVar[type] = EnginePreempted
    # kinds that corrupt values instead of raising: the fired record is
    # RETURNED to the hook site, which applies the mutation itself
    MUTATION_KINDS: ClassVar[FrozenSet[str]] = frozenset()

    def __init__(self, plan: PlannedFaultSchedule):
        self.plan = plan
        self.tick = 0                  # the domain clock, as observed
        self.fired: List[dict] = []    # log of injected faults, in order

    def pending(self) -> int:
        """Faults that have not fully fired yet."""
        return sum(1 for f in self.plan if f.fired < f.count)

    def _due(self, point: str) -> Optional[PlannedFault]:
        for f in self.plan:
            if f.point == point and f.fired < f.count and self.tick >= f.tick:
                return f
        return None

    def __call__(self, point: str, info: dict):
        cls = type(self)
        if (cls.tick_info_key is not None and info
                and cls.tick_info_key in info):
            self.tick = int(info[cls.tick_info_key])
        elif cls.tick_point is not None and point == cls.tick_point:
            self.tick += 1
        fault = self._due(point)
        if fault is None:
            return
        fault.fired += 1
        # plan fields win; the hook's engine-local clock (which resets on
        # every rebuild) is kept under its own key so a fired record can
        # be diffed against the plan without ambiguity
        record = dict(fault.to_dict(), fired_tick=self.tick)
        for key, value in (info or {}).items():
            record.setdefault(cls.info_renames.get(key, key), value)
        self.fired.append(record)
        msg = (f"injected {fault.kind} at {cls.tick_label} {self.tick} "
               f"(plan {type(fault).TICK_KEY} {fault.tick}, point {point})")
        if fault.kind in cls.MUTATION_KINDS:
            # numeric kinds corrupt VALUES rather than control flow: hand
            # the fired record back so the hook site applies the mutation
            # (engine._apply_numeric_fault) and the step keeps running —
            # only the NumericSentinel can catch what happens next
            return record
        exc = cls.EXCEPTIONS.get(fault.kind)
        if exc is not None:
            raise exc(msg, record)
        raise cls.PREEMPT_EXCEPTION(msg, record, degrade=fault.degrade)


# ---------------------------------------------------------------------------
# serving domain (re-exported unchanged by serving/faults.py)
# ---------------------------------------------------------------------------

# fault kind -> the engine hook point it fires at by default
FAULT_KINDS: Dict[str, str] = {
    "dispatch_error": "dispatch",  # raised before the tick mutates anything
    "fetch_hang": "retire",        # raised at the packed-result fetch
    "preempt": "dispatch",         # whole-engine loss (before mutation)
}
HOOK_POINTS = ("dispatch", "retire", "set_row")


@dataclass
class Fault(PlannedFault):
    """One planned serving fault, keyed on the global serving tick."""

    KINDS: ClassVar[Dict[str, str]] = FAULT_KINDS
    POINTS: ClassVar[Tuple[str, ...]] = HOOK_POINTS
    TICK_KEY: ClassVar[str] = "tick"


class FaultPlan(PlannedFaultSchedule):
    """An ordered, replayable schedule of serving :class:`Fault` entries."""

    fault_cls = Fault


class FaultInjector(PlannedFaultInjector):
    """The serving plan, armed as ``ContinuousBatchingEngine.fault_hook``.
    Counts serving ticks itself (one per ``dispatch`` call) so one plan
    spans engine rebuilds — the replacement engine's private tick counter
    restarts, the plan's does not. The serving layer re-installs the hook
    on every rebuilt engine."""

    tick_point = "dispatch"
    tick_label = "serving tick"
    info_renames = {"tick": "engine_tick"}
    EXCEPTIONS = {"dispatch_error": TickDispatchError,
                  "fetch_hang": FetchHang}
    PREEMPT_EXCEPTION = EnginePreempted


# ---------------------------------------------------------------------------
# train domain (consumed by runtime/engine.py + runtime/resilience.py)
# ---------------------------------------------------------------------------

# fault kind -> the train-engine hook point it fires at by default
TRAIN_FAULT_KINDS: Dict[str, str] = {
    "dispatch_error": "micro_dispatch",  # before RNG split / grad_acc donate
    "fetch_hang": "step_fetch",          # at the loss/grad-norm fetch
    "torn_write": "checkpoint_write",    # between array commit and marker
    "preempt": "preempt",                # process loss, between steps
    # numeric (silent-corruption) kinds — mutations, not exceptions
    "grad_bitflip": "micro_dispatch",    # flip one bit in a grad-acc leaf
    "nan_loss": "micro_dispatch",        # NaN the micro batch / loss
    "data_poison": "micro_dispatch",     # scale the micro batch to garbage
}
TRAIN_HOOK_POINTS = ("micro_dispatch", "step_fetch", "checkpoint_write",
                     "preempt")
# the silent-corruption subset: injected as value mutations on the happy
# path (the injector returns the fired record instead of raising)
TRAIN_NUMERIC_KINDS: FrozenSet[str] = frozenset(
    {"grad_bitflip", "nan_loss", "data_poison"})

#: default scale for ``data_poison`` when the plan leaves ``factor`` unset
DEFAULT_POISON_FACTOR = 1000.0


@dataclass
class TrainFault(PlannedFault):
    """One planned train fault, keyed on the global optimizer step (the
    fault becomes due once the engine's ``global_steps``-derived step
    index reaches ``tick``; JSONL spells the field ``step``).

    The numeric kinds carry optional targeting fields; their zero values
    mean "derive deterministically from the plan step" (see
    :func:`plan_bitflip`) so a bare ``{"step": 7, "kind": "grad_bitflip"}``
    record replays identically everywhere."""

    leaf: str = ""       # grad_bitflip: dotted grad-leaf path ("" = seeded)
    bit: int = -1        # grad_bitflip: fp32 bit index 0..31 (-1 = seeded)
    factor: float = 0.0  # data_poison: scale (0.0 = DEFAULT_POISON_FACTOR)

    KINDS: ClassVar[Dict[str, str]] = TRAIN_FAULT_KINDS
    POINTS: ClassVar[Tuple[str, ...]] = TRAIN_HOOK_POINTS
    TICK_KEY: ClassVar[str] = "step"
    EXTRA_FIELDS: ClassVar[Tuple[str, ...]] = ("leaf", "bit", "factor")
    SYNTH_KINDS: ClassVar[Tuple[str, ...]] = (
        "dispatch_error", "fetch_hang", "torn_write", "preempt")

    def __post_init__(self):
        super().__post_init__()
        if not -1 <= self.bit <= 31:
            raise ValueError("grad_bitflip bit must be in [-1, 31] "
                             f"(got {self.bit})")

    @property
    def step(self) -> int:
        return self.tick


class TrainFaultPlan(PlannedFaultSchedule):
    """An ordered, replayable schedule of :class:`TrainFault` entries."""

    fault_cls = TrainFault


class TrainFaultInjector(PlannedFaultInjector):
    """The train plan, armed as ``TpuEngine.fault_hook`` (the supervisor
    re-installs it on every rebuilt engine). The clock is the global
    optimizer step the hook site reports in ``info["step"]`` — it
    survives rebuilds because a restored engine resumes the counter, and
    a fault that fired during a replayed step does not re-fire
    (``fired`` lives in the plan, not the engine)."""

    tick_info_key = "step"
    tick_label = "global step"
    EXCEPTIONS = {"dispatch_error": MicroDispatchError,
                  "fetch_hang": StepFetchHang,
                  "torn_write": TornCheckpointWrite}
    PREEMPT_EXCEPTION = TrainPreempted
    MUTATION_KINDS = TRAIN_NUMERIC_KINDS


# ---------------------------------------------------------------------------
# numeric corruption helpers (pure numpy — host-side, seeded by plan step)
# ---------------------------------------------------------------------------


def plan_bitflip(step: int, sizes: Dict[str, int], leaf: str = "",
                 bit: int = -1) -> Tuple[str, int, int]:
    """Resolve a ``grad_bitflip`` record's target deterministically.

    ``sizes`` maps grad-leaf path -> element count. Unset plan fields
    derive from the plan step — leaf by round-robin over the sorted
    paths, bit from the exponent/high-mantissa byte (23..30, where a
    flip is large enough to surface), element by a Knuth-hash stride —
    so a bare record replays onto the same (leaf, element, bit) triple
    on every run. Returns ``(leaf_path, element_index, bit_index)``."""
    if not sizes:
        raise ValueError("plan_bitflip: no grad leaves to target")
    names = sorted(sizes)
    name = leaf if leaf else names[step % len(names)]
    if name not in sizes:
        raise KeyError(f"plan_bitflip: unknown grad leaf {name!r} "
                       f"(choose from {names})")
    b = bit if bit >= 0 else 23 + (step % 8)
    elem = (step * 2654435761) % max(int(sizes[name]), 1)
    return name, elem, b


def flip_float_bit(arr, elem: int, bit: int):
    """A copy of float32 ``arr`` with bit ``bit`` (0=LSB mantissa …
    30=MSB exponent, 31=sign) of flat element ``elem`` flipped — the
    classic SDC: one wrong bit in an accumulator, nothing raises."""
    a = np.ascontiguousarray(arr, dtype=np.float32)
    flat = a.reshape(-1).copy()
    words = flat.view(np.uint32)
    words[elem % flat.size] ^= np.uint32(1) << np.uint32(bit % 32)
    return flat.reshape(a.shape)


def poison_array(arr, factor: float = DEFAULT_POISON_FACTOR):
    """Deterministically corrupt one batch leaf: float leaves scale by
    ``factor`` (garbage magnitudes, still finite — the loss spikes but
    no inf check trips); integer leaves (token ids / targets) are
    scrambled in-range by an affine permutation so embedding lookups
    stay legal but the content is wrong."""
    a = np.asarray(arr)
    if np.issubdtype(a.dtype, np.floating):
        return a * np.asarray(factor, dtype=a.dtype)
    if np.issubdtype(a.dtype, np.integer) and a.size:
        hi = max(int(a.max()) + 1, 1)
        return ((a.astype(np.int64) * 31 + 7) % hi).astype(a.dtype)
    return a


def nan_poison_array(arr):
    """Float leaves become all-NaN (the loss and every grad touching the
    leaf follow); non-float leaves pass through unchanged."""
    a = np.asarray(arr)
    if np.issubdtype(a.dtype, np.floating):
        return np.full_like(a, np.nan)
    return a
