"""Deterministic fault injection shared by the serving AND training columns.

TPU pods are preemptible by design: a tick dispatch can raise, a device
fetch can hang, a whole engine can vanish mid-generation — or mid-step.
This module makes those failures *expressible and replayable* so both
recovery layers (serving/engine.py "Fault tolerance", runtime/resilience.py
TrainSupervisor) can be tested to the same bitwise-parity bar as every
perf change:

- a **fault plan** is a seeded, deterministic schedule of faults keyed on
  a monotonically increasing clock — the global serving tick for the
  serving domain, the global optimizer step for the train domain —
  replayable JSONL exactly like the loadgen workloads (``dump``/``load``
  round-trip, ``synth`` for seeded random plans);
- an **injector** is the plan, armed: installed as the engine's
  ``fault_hook`` (an explicit injection point the engine calls — no
  monkeypatching), it raises the planned exception when its clock value
  comes up.

Two domains instantiate the machinery:

=======  =========================================  =======================
domain   hook points                                clock
=======  =========================================  =======================
serving  ``dispatch`` / ``retire`` / ``set_row``    serving ticks, counted
         (:data:`HOOK_POINTS`)                      by the injector itself
train    ``micro_dispatch`` / ``step_fetch`` /      global optimizer step,
         ``checkpoint_write`` / ``preempt``         read from ``info``
         (:data:`TRAIN_HOOK_POINTS`)
=======  =========================================  =======================

The exception taxonomies the recovery ladders decide by:

- serving: :class:`TickDispatchError` (raised before any engine mutation —
  retryable), :class:`FetchHang` (poisons the tick pipeline → rebuild),
  :class:`EnginePreempted` (whole-engine loss, optionally degraded).
- train: :class:`MicroDispatchError` (raised at the top of a micro-step,
  before the RNG splits or ``grad_acc`` is donated — cleanly retryable),
  :class:`StepFetchHang` (the loss/grad-norm fetch hung past the
  watchdog — in-flight state is poisoned, rebuild from snapshot),
  :class:`TornCheckpointWrite` (the commit marker was never placed — the
  tag on disk is torn and must be refused at load),
  :class:`TrainPreempted` (process loss; host snapshots are gone, resume
  comes from the last committed tag on disk, optionally at a degraded
  chip count).

Deliberately jax-free (stdlib only): plans are authored, validated and
round-tripped without paying a jax import, same as the scheduler and
supervisor policies — tools/ci_jaxfree_tests.py enforces it.
``serving/faults.py`` re-exports the serving domain unchanged.
"""

import json
import random
from dataclasses import dataclass, field
from typing import ClassVar, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# exception taxonomy — serving
# ---------------------------------------------------------------------------


class InjectedFault(RuntimeError):
    """Base class for injected faults; ``fault`` carries the plan entry
    that fired (tick/step, kind, point)."""

    def __init__(self, message: str, fault: Optional[dict] = None):
        super().__init__(message)
        self.fault = fault or {}


class TickDispatchError(InjectedFault):
    """A transient tick-dispatch failure raised at the ``dispatch`` hook,
    BEFORE the engine mutates any state — the retryable fault class."""


class FetchHang(InjectedFault, TimeoutError):
    """A device fetch that hung past the watchdog (injected stand-in for
    the real ``fetch_timeout_s`` timeout): the in-flight tick's results
    are unrecoverable, the engine is poisoned."""


class EnginePreempted(InjectedFault):
    """Whole-engine preemption (the pod slice was reclaimed). ``degrade``
    signals the replacement must be smaller — the graceful-degradation
    path rebuilds on the next configured subset mesh."""

    def __init__(self, message: str, fault: Optional[dict] = None,
                 degrade: bool = False):
        super().__init__(message, fault)
        self.degrade = degrade


# ---------------------------------------------------------------------------
# exception taxonomy — train
# ---------------------------------------------------------------------------


class MicroDispatchError(InjectedFault):
    """A transient micro-step dispatch failure raised at the
    ``micro_dispatch`` hook, BEFORE the engine consumed its RNG or donated
    ``grad_acc`` — the cleanly retryable train fault class (same batch,
    same RNG: a retried micro-step is bitwise the micro-step)."""


class StepFetchHang(InjectedFault, TimeoutError):
    """The optimizer-step metrics fetch (loss / grad-norm / overflow flag)
    hung past ``fetch_timeout_s``: the in-flight step's host view is
    unrecoverable and the engine is poisoned — rebuild from the last
    committed snapshot."""


class TornCheckpointWrite(InjectedFault):
    """The process died (or the writer failed) between the array commit
    and the commit-marker placement: the tag on disk is torn/markerless
    and ``load_checkpoint`` must refuse it."""


class TrainPreempted(InjectedFault):
    """Whole-process preemption mid-training: host snapshot buffers are
    lost with the process, so resume restores the last *committed* tag
    from disk. ``degrade`` signals the replacement slice is smaller — the
    supervisor escalates through the elastic triad recompute."""

    def __init__(self, message: str, fault: Optional[dict] = None,
                 degrade: bool = False):
        super().__init__(message, fault)
        self.degrade = degrade


# ---------------------------------------------------------------------------
# generic machinery
# ---------------------------------------------------------------------------


@dataclass
class PlannedFault:
    """One planned fault: fires at the first hook call at ``point`` whose
    clock has reached ``tick``, then ``count - 1`` more consecutive times
    (``count > 1`` models a persistent failure that exhausts the retry
    budget and forces escalation). Domain subclasses pin ``KINDS`` (fault
    kind → natural hook point), ``POINTS`` and the JSONL ``TICK_KEY``."""

    tick: int
    kind: str
    point: str = ""         # defaults to the kind's natural hook point
    count: int = 1
    degrade: bool = False   # preempt only: replacement capacity must shrink
    fired: int = field(default=0, compare=False)

    KINDS: ClassVar[Dict[str, str]] = {}
    POINTS: ClassVar[Tuple[str, ...]] = ()
    TICK_KEY: ClassVar[str] = "tick"

    def __post_init__(self):
        cls = type(self)
        if self.kind not in cls.KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {sorted(cls.KINDS)})")
        if not self.point:
            self.point = cls.KINDS[self.kind]
        if self.point not in cls.POINTS:
            raise ValueError(f"unknown hook point {self.point!r} "
                             f"(choose from {cls.POINTS})")
        if self.tick < 0:
            raise ValueError(f"fault {cls.TICK_KEY} must be >= 0")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")

    def to_dict(self) -> dict:
        out = {type(self).TICK_KEY: self.tick, "kind": self.kind,
               "point": self.point}
        if self.count != 1:
            out["count"] = self.count
        if self.degrade:
            out["degrade"] = True
        return out


class PlannedFaultSchedule:
    """An ordered, replayable schedule of :class:`PlannedFault` entries."""

    fault_cls = PlannedFault

    def __init__(self, faults: List[PlannedFault]):
        self.faults = sorted(faults, key=lambda f: (f.tick, f.point, f.kind))

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self):
        return iter(self.faults)

    @classmethod
    def synth(cls, seed: int = 0, n_faults: int = 3, first_tick: int = 2,
              tick_span: int = 100, kinds: Optional[List[str]] = None,
              degrade_last: bool = False):
        """A seeded random plan: ``n_faults`` faults uniformly over
        ``[first_tick, first_tick + tick_span)``, kinds drawn from
        ``kinds`` (default: the domain's full taxonomy). Fully determined
        by ``seed`` — the chaos-soak analogue of ``synth_workload``."""
        rng = random.Random(seed)
        kinds = list(kinds or cls.fault_cls.KINDS)
        ticks = sorted(rng.randrange(first_tick, first_tick + tick_span)
                       for _ in range(n_faults))
        faults = [cls.fault_cls(tick=t, kind=rng.choice(kinds))
                  for t in ticks]
        if degrade_last and faults:
            faults[-1].kind = "preempt"
            faults[-1].point = cls.fault_cls.KINDS["preempt"]
            faults[-1].degrade = True
        return cls(faults)

    def dump(self, path: str):
        """Write the plan as replayable JSONL (one fault per line)."""
        with open(path, "w") as fh:
            for f in self.faults:
                fh.write(json.dumps(f.to_dict()) + "\n")

    @classmethod
    def load(cls, path: str):
        key = cls.fault_cls.TICK_KEY
        faults = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                tick = rec.get(key, rec.get("tick"))
                faults.append(cls.fault_cls(
                    tick=int(tick), kind=rec["kind"],
                    point=rec.get("point", ""),
                    count=int(rec.get("count", 1)),
                    degrade=bool(rec.get("degrade", False))))
        if not faults:
            raise ValueError(f"no fault records in {path}")
        return cls(faults)


class PlannedFaultInjector:
    """A fault plan, armed as an engine fault hook.

    Install with ``engine.fault_hook = injector``; the engine calls
    ``injector(point, info)`` at each hook point and the injector raises
    the planned exception when a fault is due. How the clock advances is
    the domain's choice: the serving injector counts ticks ITSELF (one
    per ``dispatch`` call) so a single plan stays meaningful across
    engine rebuilds; the train injector reads the global optimizer step
    from ``info`` so the clock survives rebuilds for free (a restored
    engine resumes the step counter)."""

    tick_point: ClassVar[Optional[str]] = None   # hook point that counts
    tick_info_key: ClassVar[Optional[str]] = None  # info key that sets it
    tick_label: ClassVar[str] = "tick"
    info_renames: ClassVar[Dict[str, str]] = {}
    EXCEPTIONS: ClassVar[Dict[str, type]] = {}
    PREEMPT_EXCEPTION: ClassVar[type] = EnginePreempted

    def __init__(self, plan: PlannedFaultSchedule):
        self.plan = plan
        self.tick = 0                  # the domain clock, as observed
        self.fired: List[dict] = []    # log of injected faults, in order

    def pending(self) -> int:
        """Faults that have not fully fired yet."""
        return sum(1 for f in self.plan if f.fired < f.count)

    def _due(self, point: str) -> Optional[PlannedFault]:
        for f in self.plan:
            if f.point == point and f.fired < f.count and self.tick >= f.tick:
                return f
        return None

    def __call__(self, point: str, info: dict):
        cls = type(self)
        if (cls.tick_info_key is not None and info
                and cls.tick_info_key in info):
            self.tick = int(info[cls.tick_info_key])
        elif cls.tick_point is not None and point == cls.tick_point:
            self.tick += 1
        fault = self._due(point)
        if fault is None:
            return
        fault.fired += 1
        # plan fields win; the hook's engine-local clock (which resets on
        # every rebuild) is kept under its own key so a fired record can
        # be diffed against the plan without ambiguity
        record = dict(fault.to_dict(), fired_tick=self.tick)
        for key, value in (info or {}).items():
            record.setdefault(cls.info_renames.get(key, key), value)
        self.fired.append(record)
        msg = (f"injected {fault.kind} at {cls.tick_label} {self.tick} "
               f"(plan {type(fault).TICK_KEY} {fault.tick}, point {point})")
        exc = cls.EXCEPTIONS.get(fault.kind)
        if exc is not None:
            raise exc(msg, record)
        raise cls.PREEMPT_EXCEPTION(msg, record, degrade=fault.degrade)


# ---------------------------------------------------------------------------
# serving domain (re-exported unchanged by serving/faults.py)
# ---------------------------------------------------------------------------

# fault kind -> the engine hook point it fires at by default
FAULT_KINDS: Dict[str, str] = {
    "dispatch_error": "dispatch",  # raised before the tick mutates anything
    "fetch_hang": "retire",        # raised at the packed-result fetch
    "preempt": "dispatch",         # whole-engine loss (before mutation)
}
HOOK_POINTS = ("dispatch", "retire", "set_row")


@dataclass
class Fault(PlannedFault):
    """One planned serving fault, keyed on the global serving tick."""

    KINDS: ClassVar[Dict[str, str]] = FAULT_KINDS
    POINTS: ClassVar[Tuple[str, ...]] = HOOK_POINTS
    TICK_KEY: ClassVar[str] = "tick"


class FaultPlan(PlannedFaultSchedule):
    """An ordered, replayable schedule of serving :class:`Fault` entries."""

    fault_cls = Fault


class FaultInjector(PlannedFaultInjector):
    """The serving plan, armed as ``ContinuousBatchingEngine.fault_hook``.
    Counts serving ticks itself (one per ``dispatch`` call) so one plan
    spans engine rebuilds — the replacement engine's private tick counter
    restarts, the plan's does not. The serving layer re-installs the hook
    on every rebuilt engine."""

    tick_point = "dispatch"
    tick_label = "serving tick"
    info_renames = {"tick": "engine_tick"}
    EXCEPTIONS = {"dispatch_error": TickDispatchError,
                  "fetch_hang": FetchHang}
    PREEMPT_EXCEPTION = EnginePreempted


# ---------------------------------------------------------------------------
# train domain (consumed by runtime/engine.py + runtime/resilience.py)
# ---------------------------------------------------------------------------

# fault kind -> the train-engine hook point it fires at by default
TRAIN_FAULT_KINDS: Dict[str, str] = {
    "dispatch_error": "micro_dispatch",  # before RNG split / grad_acc donate
    "fetch_hang": "step_fetch",          # at the loss/grad-norm fetch
    "torn_write": "checkpoint_write",    # between array commit and marker
    "preempt": "preempt",                # process loss, between steps
}
TRAIN_HOOK_POINTS = ("micro_dispatch", "step_fetch", "checkpoint_write",
                     "preempt")


@dataclass
class TrainFault(PlannedFault):
    """One planned train fault, keyed on the global optimizer step (the
    fault becomes due once the engine's ``global_steps``-derived step
    index reaches ``tick``; JSONL spells the field ``step``)."""

    KINDS: ClassVar[Dict[str, str]] = TRAIN_FAULT_KINDS
    POINTS: ClassVar[Tuple[str, ...]] = TRAIN_HOOK_POINTS
    TICK_KEY: ClassVar[str] = "step"

    @property
    def step(self) -> int:
        return self.tick


class TrainFaultPlan(PlannedFaultSchedule):
    """An ordered, replayable schedule of :class:`TrainFault` entries."""

    fault_cls = TrainFault


class TrainFaultInjector(PlannedFaultInjector):
    """The train plan, armed as ``TpuEngine.fault_hook`` (the supervisor
    re-installs it on every rebuilt engine). The clock is the global
    optimizer step the hook site reports in ``info["step"]`` — it
    survives rebuilds because a restored engine resumes the counter, and
    a fault that fired during a replayed step does not re-fire
    (``fired`` lives in the plan, not the engine)."""

    tick_info_key = "step"
    tick_label = "global step"
    EXCEPTIONS = {"dispatch_error": MicroDispatchError,
                  "fetch_hang": StepFetchHang,
                  "torn_write": TornCheckpointWrite}
    PREEMPT_EXCEPTION = TrainPreempted
