"""Decoder-only transformer (GPT-2 / Llama families), TPU-first.

This is the flagship model the engine trains and benches. Design choices that
matter on TPU (vs the reference's per-layer torch modules +
``csrc/transformer`` fused CUDA kernels):

  - layer params are *stacked* along a leading L dim and the decoder body is a
    single ``lax.scan`` — one compiled layer body regardless of depth (fast
    compile, and XLA pipelines the scan);
  - everything is static-shape, bf16-friendly, einsum-based so the MXU gets
    large batched GEMMs; elementwise chains (bias/residual/norm/activation)
    are left to XLA fusion — the CUDA fused-kernel inventory
    (softmax/gelu/layernorm/transform kernels, SURVEY §2.4 #5/#6) is the
    compiler's job here, with Pallas reserved for attention;
  - parameters carry logical axis names (embed/mlp/heads/vocab/layers) so the
    ZeRO/TP ShardingPolicy can place them (runtime/zero/sharding.py);
  - activation rematerialisation is a ``jax.checkpoint`` policy around the
    scanned layer body (reference: activation_checkpointing/checkpointing.py).

Functional API: ``init(rng, cfg) -> params``; ``apply(params, cfg, tokens)``;
``loss(params, cfg, batch)``. The TransformerModel class packages these for
the engine protocol.
"""

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None => MHA
    ffn_hidden_size: Optional[int] = None  # None => 4*hidden (gpt) / derived (llama)
    max_seq_len: int = 1024
    pos_embedding: str = "learned"  # learned | rope | alibi | none
    norm_type: str = "layernorm"  # layernorm | rmsnorm
    activation: str = "gelu"  # gelu | relu | silu_glu (SwiGLU)
    tie_embeddings: bool = True
    dtype: str = "float32"  # compute/storage dtype for params & activations
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dropout: float = 0.0
    remat: bool = False
    remat_policy: str = "nothing_saveable"  # nothing_saveable | dots_saveable | dots_with_no_batch_dims
    attn_impl: str = "xla"  # xla | pallas (flash) | block_sparse (layout kernel)
    # block-sparse attention pattern (attn_impl="block_sparse"): mode is one
    # of dense|fixed|bigbird|bslongformer|variable plus that mode's kwargs
    # (ops/sparse_attention/sparsity_config.py; reference
    # ops/sparse_attention/sparse_self_attention.py + docs "~10x longer
    # sequences"). Tuple-of-pairs so the frozen config stays hashable.
    sparse_attention: Optional[tuple] = None  # e.g. (("mode","fixed"),("block",128))
    use_bias: bool = True  # linear/ln biases (gpt2 yes, llama no)
    scan_layers: bool = True
    # --- architecture variants for the HF injection-policy families
    # (module_inject/policies.py; reference replace_policy.py:20-26) ---
    rope_dim: Optional[int] = None  # partial rotary over first rope_dim dims (GPT-J/NeoX)
    rope_interleaved: bool = False  # GPT-J even/odd pairing (vs llama/neox half-split)
    parallel_residual: bool = False  # x + attn(h) + mlp(h') in one residual (GPT-J/NeoX)
    shared_ln: bool = False  # parallel residual feeds mlp from ln1 too (GPT-J)
    norm_position: str = "pre"  # pre | post (post: BERT / OPT-350m ordering)
    causal: bool = True  # False = bidirectional encoder attention (BERT)
    type_vocab_size: int = 0  # token-type-embedding vocab (BERT; 0 = off)
    embed_norm: bool = False  # LayerNorm over summed embeddings (BERT, BLOOM)
    lm_head_bias: bool = False  # untied lm head carries a bias (GPT-J)
    attn_scale: Optional[float] = None  # None => 1/sqrt(head_dim); GPT-Neo uses 1.0
    # per-layer local-attention windows (GPT-Neo global/local alternation:
    # 0 = global, W = attend only the last W positions). Tuple of
    # num_layers ints; None = all-global.
    local_attn_windows: Optional[tuple] = None
    # flash-attention tile size (PERF.md block sweep; None = kernel default
    # of 128). Larger tiles amortize the softmax running-max bookkeeping
    # against HBM re-reads of K/V; the bench self-tune probes this.
    flash_block: Optional[int] = None
    # KV-cache storage: "model" dtype or "int8" (per-token-per-head scales;
    # decode reads half the cache bytes, context capacity doubles — the
    # quantize/dequantize lives in ops/transformer/inference_ops)
    kv_cache_dtype: str = "model"
    # rolling (ring-buffer) KV cache for uniform-sliding-window models
    # (Mistral): the cache holds only the last `window` positions — decode
    # memory and cache-read bandwidth are O(window) instead of O(total
    # generated length). Set by the inference engine when the conditions
    # hold (uniform window, rope/no pos-emb, flash prefill available);
    # slot absolute positions are derived modulo the cache length, so the
    # math degenerates to the plain cache whenever nothing wraps.
    rolling_kv_cache: bool = False
    # --- MoE (reference: deepspeed/moe/; 0 experts = dense MLP) ---
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    moe_drop_tokens: bool = True
    moe_use_rts: bool = False  # random token selection needs an rng at loss()
    # PR-MoE residual mixing (reference moe/layer.py:28,45): dense MLP +
    # expert mix with a learned per-token 2-way softmax coefficient
    moe_use_residual: bool = False
    # --- sequence/context parallelism (parallel/sequence.py) ---
    seq_parallel: str = "none"  # none | ring | ulysses
    # --- QAT activation fake-quant bits, 0 = off (compression/ wiring) ---
    act_quant_bits: int = 0
    # --- data efficiency (engine-driven schedules) ---
    # random-LTD: layers run on a random token subset of this length
    # (engine re-jits per scheduled value; 0 = off). Applies to all scanned
    # layers; per-layer subsets need scan_layers=False.
    random_ltd: bool = False
    # progressive layer drop: stochastic depth with keep prob
    # p_l = 1 - (l/L) * (1 - theta); theta is a dynamic scalar from the
    # engine's PLD schedule (runtime/progressive_layer_drop.py)
    pld_enabled: bool = False

    def __post_init__(self):
        # accept a dict for sparse_attention (user-facing) but store a
        # tuple-of-pairs so the frozen config stays hashable
        if isinstance(self.sparse_attention, dict):
            object.__setattr__(
                self, "sparse_attention", tuple(sorted(self.sparse_attention.items()))
            )

    @property
    def uniform_window(self) -> Optional[int]:
        """The single static sliding-window size when every layer shares one
        positive window (Mistral); None for no windows or per-layer mixes
        (GPT-Neo alternation)."""
        w = self.local_attn_windows
        if w is None or len(set(w)) != 1 or int(w[0]) <= 0:
            return None
        return int(w[0])

    @property
    def varying_windows(self) -> bool:
        """True when windows differ per layer (GPT-Neo alternation) and must
        ride the layer scan as traced scalars; uniform/absent windows stay
        static python ints (flash band kernel + rolling cache rely on it)."""
        w = self.local_attn_windows
        return w is not None and len(set(w)) > 1

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def ffn_size(self):
        if self.ffn_hidden_size is not None:
            return self.ffn_hidden_size
        return 4 * self.hidden_size

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[self.dtype]

    def num_params(self) -> int:
        D, V, L, F = self.hidden_size, self.vocab_size, self.num_layers, self.ffn_size
        kvd = self.kv_heads * self.head_dim
        attn = D * D + 2 * D * kvd + D * D  # q,k,v,o
        mlp = (3 if self.activation == "silu_glu" else 2) * D * F
        if self.moe_num_experts > 0:
            dense_mlp = mlp
            mlp = mlp * self.moe_num_experts + D * self.moe_num_experts  # experts + router
            if self.moe_use_residual:
                mlp += dense_mlp + 2 * D + 2  # residual MLP + coefficient
        per_layer = attn + mlp + 2 * D  # + ln scales
        if self.use_bias:
            mlp_bias = F + D
            if self.moe_num_experts > 0:
                mlp_bias *= self.moe_num_experts  # per-expert bi/bo
                if self.moe_use_residual:
                    mlp_bias += F + D  # dense residual MLP biases
            per_layer += (D + 2 * kvd + D) + mlp_bias + 2 * D  # attn/mlp/ln biases
        emb = V * D + (self.max_seq_len * D if self.pos_embedding == "learned" else 0)
        emb += self.type_vocab_size * D
        if self.embed_norm:
            emb += D + (D if self.use_bias else 0)
        head = 0 if self.tie_embeddings else V * D + (V if self.lm_head_bias else 0)
        final = D + (D if self.use_bias else 0)
        return emb + L * per_layer + final + head

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token, Megatron-style accounting (fwd+bwd):
        6*N over matmul params + the logits projection (the V×D matmul runs
        every step whether or not embeddings are tied) + causal attention."""
        n = self.num_params() - self.vocab_size * self.hidden_size * (1 if self.tie_embeddings else 2)
        lm_head_flops = 6 * self.vocab_size * self.hidden_size
        attn_flops = 12 * self.num_layers * self.hidden_size * seq_len  # 2*2*3 per token pair
        return 6.0 * n + lm_head_flops + attn_flops


# preset shapes for parity configs (BASELINE.md tracked configs)
PRESETS = {
    "gpt2-125m": dict(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12, max_seq_len=1024),
    "gpt2-350m": dict(vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16, max_seq_len=1024),
    "gpt2-760m": dict(vocab_size=50257, hidden_size=1280, num_layers=36, num_heads=20, max_seq_len=1024),
    "gpt2-1.5b": dict(vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25, max_seq_len=1024),
    "llama2-7b": dict(
        vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=32,
        ffn_hidden_size=11008, max_seq_len=4096, pos_embedding="rope", norm_type="rmsnorm",
        activation="silu_glu", tie_embeddings=False, use_bias=False,
    ),
    "llama2-70b": dict(
        vocab_size=32000, hidden_size=8192, num_layers=80, num_heads=64, num_kv_heads=8,
        ffn_hidden_size=28672, max_seq_len=4096, pos_embedding="rope", norm_type="rmsnorm",
        activation="silu_glu", tie_embeddings=False, use_bias=False,
    ),
    # BASELINE.json tracked inference config (BLOOM-7B kernel injection)
    "bloom-7b": dict(
        vocab_size=250880, hidden_size=4096, num_layers=30, num_heads=32,
        max_seq_len=2048, pos_embedding="alibi", embed_norm=True, tie_embeddings=True,
    ),
    "gptj-6b": dict(
        vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16,
        max_seq_len=2048, pos_embedding="rope", rope_dim=64, rope_interleaved=True,
        parallel_residual=True, shared_ln=True, tie_embeddings=False, lm_head_bias=True,
    ),
    "gpt-neox-20b": dict(
        vocab_size=50432, hidden_size=6144, num_layers=44, num_heads=64,
        ffn_hidden_size=24576, max_seq_len=2048, pos_embedding="rope", rope_dim=24,
        parallel_residual=True, tie_embeddings=False,
    ),
    # Reference headline-bench family (docs/_posts/2020-05-28-fastest-bert-training.md:
    # BERT-large pretrain, 64 TFLOPS/V100 @ seq 128). Bidirectional post-LN
    # encoder: tok+pos+type embeddings -> LayerNorm, no final norm (post-LN
    # already normalizes the last residual), MLM via labels+loss_mask in
    # loss_fn. Deviation from HF BERT: the MLM head ties directly to the
    # token embedding (no extra transform dense); pooler/NSP head omitted.
    "bert-large": dict(
        vocab_size=30522, hidden_size=1024, num_layers=24, num_heads=16,
        max_seq_len=512, pos_embedding="learned", type_vocab_size=2,
        embed_norm=True, norm_position="post", causal=False,
    ),
    "bert-base": dict(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=512, pos_embedding="learned", type_vocab_size=2,
        embed_norm=True, norm_position="post", causal=False,
    ),
}


def get_config(preset: str, **overrides) -> TransformerConfig:
    base = dict(PRESETS[preset])
    base.update(overrides)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_outer(rng, cfg: TransformerConfig):
    """Non-layer params: embeddings, final norm, lm head (all fp32)."""
    D, V, S = cfg.hidden_size, cfg.vocab_size, cfg.max_seq_len
    k_tok, k_pos, k_head = jax.random.split(rng, 3)
    params = {
        "embed": {"tok": jax.random.normal(k_tok, (V, D), jnp.float32) * 0.02},
        "final_norm": {"scale": jnp.ones((D,), jnp.float32)},
    }
    if cfg.pos_embedding == "learned":
        params["embed"]["pos"] = jax.random.normal(k_pos, (S, D), jnp.float32) * 0.02
    if cfg.type_vocab_size > 0:
        params["embed"]["type"] = (
            jax.random.normal(jax.random.fold_in(k_pos, 1), (cfg.type_vocab_size, D), jnp.float32) * 0.02
        )
    if cfg.embed_norm:
        params["embed_norm"] = {"scale": jnp.ones((D,), jnp.float32)}
        if cfg.use_bias:
            params["embed_norm"]["bias"] = jnp.zeros((D,), jnp.float32)
    if not cfg.tie_embeddings:
        params["lm_head"] = {
            "w": jax.random.normal(k_head, (D, V), jnp.float32) / math.sqrt(D)
        }
        if cfg.lm_head_bias:
            params["lm_head"]["b"] = jnp.zeros((V,), jnp.float32)
    if cfg.use_bias:
        params["final_norm"]["bias"] = jnp.zeros((D,), jnp.float32)
    return params


def _init_one_layer(key, cfg: TransformerConfig):
    """Unstacked params for a single decoder layer."""
    D, F, L = cfg.hidden_size, cfg.ffn_size, cfg.num_layers
    hd, nh, nkv, E = cfg.head_dim, cfg.num_heads, cfg.kv_heads, cfg.moe_num_experts
    ks = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) * (1.0 / math.sqrt(fan_in))

    def experts(maker):
        return jnp.stack([maker(k) for k in jax.random.split(next(ks), E)])

    if E > 0:
        mlp = {
            "gate": jax.random.normal(next(ks), (D, E), jnp.float32) * 0.02,
            "wi": experts(lambda k: dense(k, (D, F), D)),
            "wo": experts(lambda k: dense(k, (F, D), F) / math.sqrt(2 * L)),
        }
        if cfg.activation == "silu_glu":
            mlp["wg"] = experts(lambda k: dense(k, (D, F), D))
        if cfg.moe_use_residual:
            # PR-MoE (reference moe/layer.py:28,45): dense residual MLP +
            # per-token 2-way mixing coefficient
            mlp["res_wi"] = dense(next(ks), (D, F), D)
            mlp["res_wo"] = dense(next(ks), (F, D), F) / math.sqrt(2 * L)
            if cfg.activation == "silu_glu":
                mlp["res_wg"] = dense(next(ks), (D, F), D)
            mlp["coef_w"] = jax.random.normal(next(ks), (D, 2), jnp.float32) * 0.02
            mlp["coef_b"] = jnp.zeros((2,), jnp.float32)
    else:
        mlp = {
            "wi": dense(next(ks), (D, F), D),
            "wo": dense(next(ks), (F, D), F) / math.sqrt(2 * L),
        }
        if cfg.activation == "silu_glu":
            mlp["wg"] = dense(next(ks), (D, F), D)

    layer = {
        "attn": {
            "wq": dense(next(ks), (D, nh * hd), D),
            "wk": dense(next(ks), (D, nkv * hd), D),
            "wv": dense(next(ks), (D, nkv * hd), D),
            "wo": dense(next(ks), (nh * hd, D), nh * hd) / math.sqrt(2 * L),
        },
        "mlp": mlp,
        "ln1": {"scale": jnp.ones((D,), jnp.float32)},
        "ln2": {"scale": jnp.ones((D,), jnp.float32)},
    }
    if cfg.use_bias:
        layer["attn"]["bq"] = jnp.zeros((nh * hd,), jnp.float32)
        layer["attn"]["bk"] = jnp.zeros((nkv * hd,), jnp.float32)
        layer["attn"]["bv"] = jnp.zeros((nkv * hd,), jnp.float32)
        layer["attn"]["bo"] = jnp.zeros((D,), jnp.float32)
        if E > 0:
            layer["mlp"]["bi"] = jnp.zeros((E, F), jnp.float32)
            layer["mlp"]["bo"] = jnp.zeros((E, D), jnp.float32)
            if cfg.moe_use_residual:
                layer["mlp"]["res_bi"] = jnp.zeros((F,), jnp.float32)
                layer["mlp"]["res_bo"] = jnp.zeros((D,), jnp.float32)
        else:
            layer["mlp"]["bi"] = jnp.zeros((F,), jnp.float32)
            layer["mlp"]["bo"] = jnp.zeros((D,), jnp.float32)
        layer["ln1"]["bias"] = jnp.zeros((D,), jnp.float32)
        layer["ln2"]["bias"] = jnp.zeros((D,), jnp.float32)
    return layer


def init_layer_slice(rng, cfg: TransformerConfig, lo: int, hi: int):
    """Stacked params for layers [lo, hi) — per-layer keys are ``fold_in``
    of the absolute layer index, so any slicing yields identical leaves.
    This is the ZeRO-Infinity streaming-init hook (reference analogue:
    zero.Init partitioned construction, partition_parameters.py:601):
    the param-offload tier materialises one sub-group at a time."""
    keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(jnp.arange(lo, hi))
    return jax.vmap(lambda k: _init_one_layer(k, cfg))(keys)


def init(rng, cfg: TransformerConfig):
    """Build the parameter pytree (all leaves fp32; engine casts as needed)."""
    r_outer, r_layers = jax.random.split(rng)
    params = init_outer(r_outer, cfg)
    params["layers"] = init_layer_slice(r_layers, cfg, 0, cfg.num_layers)
    return params


def logical_specs(params, cfg: TransformerConfig):
    """Per-dimension logical axis names, mirroring the params pytree.

    The ShardingPolicy maps these through rules onto mesh axes; the 'layers'
    leading scan dim is never sharded.
    """

    def annotate(path, leaf):
        names = [p.key for p in path if hasattr(p, "key")]
        last = names[-1]
        stacked = "layers" in names
        pre = ("layers",) if stacked else ()
        if "attn" in names:
            table = {
                "wq": ("embed", "heads"), "wk": ("embed", "kv"), "wv": ("embed", "kv"),
                "wo": ("heads", "embed"), "bq": ("heads",), "bk": ("kv",), "bv": ("kv",), "bo": ("embed",),
            }
            return pre + table[last]
        if "mlp" in names:
            if cfg.moe_num_experts > 0 and last in ("wi", "wg", "wo", "bi", "bo"):
                table = {"wi": ("expert", "embed", "mlp"), "wg": ("expert", "embed", "mlp"),
                         "wo": ("expert", "mlp", "embed"), "bi": ("expert", "mlp"), "bo": ("expert", "embed")}
                return pre + table[last]
            table = {"wi": ("embed", "mlp"), "wg": ("embed", "mlp"), "wo": ("mlp", "embed"),
                     "bi": ("mlp",), "bo": ("embed",), "gate": ("embed", None),
                     # PR-MoE residual MLP + mixing coefficient (dense)
                     "res_wi": ("embed", "mlp"), "res_wg": ("embed", "mlp"),
                     "res_wo": ("mlp", "embed"), "res_bi": ("mlp",), "res_bo": ("embed",),
                     "coef_w": ("embed", None), "coef_b": (None,)}
            return pre + table[last]
        if "ln1" in names or "ln2" in names:
            return pre + ("norm",)
        if "final_norm" in names or "embed_norm" in names:
            return ("norm",)
        if "embed" in names:
            if last == "tok":
                return ("vocab", "embed")
            # pos table shards over seq; the tiny type table stays unsharded
            return ("seq", "embed") if last == "pos" else (None, "embed")
        if "lm_head" in names:
            return ("embed", "vocab") if last == "w" else ("vocab",)
        if "mlm_head" in names:
            table = {"w": ("embed", None), "b": (None,), "ln_scale": ("norm",),
                     "ln_bias": ("norm",), "proj_bias": ("vocab",)}
            return table[last]
        return tuple(None for _ in leaf.shape)

    return jax.tree_util.tree_map_with_path(annotate, params)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _norm(x, scale, bias, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.norm_type == "rmsnorm":
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + cfg.norm_eps)
    else:
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        x32 = (x32 - mu) * jax.lax.rsqrt(var + cfg.norm_eps)
    out = x32 * scale
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


# rotary embedding: the op-registry surface IS the implementation
# (ops/transformer/inference_ops.apply_rotary_pos_emb; reference analogue
# csrc/transformer/inference apply_rotary_pos_emb.cu)
from deepspeed_tpu.ops.transformer.fused_ops import fused_softmax  # noqa: E402
from deepspeed_tpu.ops.transformer.inference_ops import (  # noqa: E402
    apply_rotary_pos_emb as _rope,
    softmax_context,
    update_kv_cache,
)


def _alibi_slopes(n_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (press et al.; reference: BLOOM container's
    alibi path in module_inject/containers/bloom.py lineage)."""

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        slopes = pow2_slopes(n_heads)
    else:
        closest = 2 ** math.floor(math.log2(n_heads))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)[0::2][: n_heads - closest]
        slopes = slopes + extra
    return jnp.asarray(slopes, jnp.float32)


import functools


@functools.lru_cache(maxsize=32)
def _sparse_layout(sparse_attention: tuple, num_heads: int, seq_len: int):
    """Static block-sparse layout for (pattern, heads, seq) — numpy, built
    once per shape and embedded as a jit constant. Returns (layout, block)."""
    from deepspeed_tpu.ops.sparse_attention import sparsity_config as sc

    opts = dict(sparse_attention)
    mode = opts.pop("mode", "fixed")
    cls = {
        "dense": sc.DenseSparsityConfig,
        "fixed": sc.FixedSparsityConfig,
        "bigbird": sc.BigBirdSparsityConfig,
        "bslongformer": sc.BSLongformerSparsityConfig,
        "variable": sc.VariableSparsityConfig,
    }[mode]
    config = cls(num_heads=num_heads, **opts)
    return config.make_layout(seq_len), config.block


def _attention(q, k, v, cfg: TransformerConfig, segment_positions, window=None):
    """Causal multi-head / grouped-query attention.

    xla impl: einsum softmax einsum (fp32 logits). pallas impl: flash kernel
    (ops/pallas/flash_attention.py) once available. ``window`` restricts each
    query to the last ``window`` positions (0 = unlimited) — GPT-Neo local
    layers, Mistral sliding windows. A STATIC python-int window rides the
    flash kernel's tile-pruned sliding-window path (O(S*window) compute and
    HBM — the layer stack passes static ints whenever the config allows);
    a traced i32 scalar (per-layer windows inside the layer scan) takes the
    masked einsum path.
    """
    B, S, nh, hd = q.shape
    had_window = window is not None
    if isinstance(window, int) and (window <= 0 or window >= S):
        # static 0 = a global layer; a window covering the whole sequence
        # is a numerical no-op — elide it so e.g. Mistral (sliding_window
        # 4096) at seq <= 4096 keeps the unwindowed fast paths, including
        # sequence parallelism below. had_window still gates the
        # block-sparse branch: elision must not reroute a windowed config
        # onto an APPROXIMATE kernel it never used before.
        window = None
    static_window = window if isinstance(window, int) else None
    nkv = k.shape[2]
    if cfg.seq_parallel in ("ring", "ulysses"):
        from deepspeed_tpu import comm
        from deepspeed_tpu.parallel.sequence import sequence_parallel_attention

        mesh = comm.get_mesh()
        if mesh.shape.get("sequence", 1) > 1:
            if cfg.pos_embedding == "alibi":
                raise NotImplementedError("ALiBi bias is not supported under sequence parallelism")
            if window is not None:
                raise NotImplementedError(
                    "local attention windows are not supported under sequence parallelism"
                )
            return sequence_parallel_attention(
                q, k, v, impl=cfg.seq_parallel, causal=cfg.causal, mesh=mesh,
                attn_impl=cfg.attn_impl, sm_scale=cfg.attn_scale,
            )
    if not had_window and window is None and cfg.attn_impl == "block_sparse":
        # layout-aware Pallas kernel: long-sequence training/prefill path
        # (reference SparseSelfAttention; decode stays dense — the KV-cache
        # loop attends a single query row)
        if cfg.pos_embedding == "alibi":
            raise NotImplementedError("ALiBi bias is not supported with block-sparse attention")
        from deepspeed_tpu.ops.pallas.block_sparse_attention import block_sparse_attention

        if nkv != nh:
            k = jnp.repeat(k, nh // nkv, axis=2)
            v = jnp.repeat(v, nh // nkv, axis=2)
        layout, block = _sparse_layout(cfg.sparse_attention or (("mode", "fixed"),), nh, S)
        # kernel convention matches the model: (B, S, H, hd)
        info = _tp_head_shard(B, nh, nh)
        if info is not None:
            # same GSPMD-unpartitionable story as flash (_head_shard_map):
            # heads and their layout rows shard over 'tensor'
            from jax.sharding import PartitionSpec

            mesh, spec = info
            lspec = PartitionSpec("tensor", None, None)
            fn = _head_shard_map(
                mesh,
                lambda q_, k_, v_, l_: block_sparse_attention(
                    q_, k_, v_, l_, causal=cfg.causal, block=block,
                    sm_scale=cfg.attn_scale),
                (spec, spec, spec, lspec), spec)
            return fn(q, k, v, jnp.asarray(layout))
        return block_sparse_attention(q, k, v, layout, causal=cfg.causal, block=block,
                                      sm_scale=cfg.attn_scale)
    if ((window is None or (static_window is not None and cfg.causal))
            and cfg.attn_impl == "pallas" and cfg.pos_embedding != "alibi"):
        return _flash_sharded(q, k, v, cfg, causal=cfg.causal, window=static_window)
    if nkv != nh:
        k = jnp.repeat(k, nh // nkv, axis=2)
        v = jnp.repeat(v, nh // nkv, axis=2)
    scale = cfg.attn_scale if cfg.attn_scale is not None else 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if cfg.pos_embedding == "alibi":
        pos = jnp.arange(S, dtype=jnp.float32)
        rel = pos[None, :] - pos[:, None]  # (q, k): negative into the past
        logits = logits + _alibi_slopes(nh)[None, :, None, None] * rel[None, None]
    mask = None
    if cfg.causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    if window is not None:
        qp = jnp.arange(S, dtype=jnp.int32)[:, None]
        kp = jnp.arange(S, dtype=jnp.int32)[None, :]
        local_ok = (qp - kp < window) | (window <= 0)
        mask = local_ok if mask is None else mask & local_ok
    if mask is not None:
        logits = jnp.where(mask[None, None, :, :], logits, jnp.float32(-1e30))
    probs = fused_softmax(logits).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _tp_head_shard(B, nh, nkv):
    """(mesh, qkv_spec) when a live mesh has tensor>1 and the head counts
    divide it — the precondition for running a Pallas attention kernel
    per-shard under shard_map; None otherwise. The spec shards (B, S, H,
    hd): heads over 'tensor' (the qkv projections' output sharding, so the
    common case reshards nothing), batch over its data-parallel axes when
    it divides them."""
    from jax.sharding import PartitionSpec

    from deepspeed_tpu import comm

    if not comm.is_initialized():
        return None
    mesh = comm.get_mesh()
    tp = mesh.shape.get("tensor", 1)
    if tp <= 1 or nh % tp or nkv % tp:
        return None
    batch_axes = tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)
    if batch_axes and B % math.prod(mesh.shape[a] for a in batch_axes):
        batch_axes = ()
    return mesh, PartitionSpec(batch_axes or None, None, "tensor", None)


def _head_shard_map(mesh, fn, in_specs, out_spec):
    """shard_map wrapper for Pallas attention kernels (GSPMD cannot
    partition a pallas_call custom call: left alone it ALL-GATHERS the
    operands and computes every head replicated on every chip — measured
    as 15 all-gathers and full-head operand shapes in a TP-2 step's HLO).
    Semantics are preserved for every caller — shard_map reshards inputs
    to the stated specs and back, so a mismatched sharding pays a
    reshard, never a wrong answer."""
    import inspect

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map

    check_kw = ({"check_vma": False}
                if "check_vma" in inspect.signature(shard_map).parameters
                else {"check_rep": False})
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_spec,
                     **check_kw)


def _flash_sharded(q, k, v, cfg: TransformerConfig, causal: bool, window=None):
    """Flash attention, partitioned under tensor parallelism when a mesh
    is live (see _head_shard_map)."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    blk = {"block_q": cfg.flash_block, "block_k": cfg.flash_block} if cfg.flash_block else {}
    kwargs = dict(causal=causal, sm_scale=cfg.attn_scale, window=window, **blk)

    info = _tp_head_shard(q.shape[0], q.shape[2], k.shape[2])
    if info is None:
        return flash_attention(q, k, v, **kwargs)
    mesh, spec = info
    fn = _head_shard_map(
        mesh, lambda q_, k_, v_: flash_attention(q_, k_, v_, **kwargs),
        (spec, spec, spec), spec)
    return fn(q, k, v)


def _quick_gelu(x):
    # CLIP's approximation: x * sigmoid(1.702 x)
    return x * jax.nn.sigmoid(1.702 * x)


def _dense_act(cfg: TransformerConfig):
    return {"relu": jax.nn.relu, "quick_gelu": _quick_gelu}.get(cfg.activation, jax.nn.gelu)


def _mlp_block(h, mlp_p, cfg: TransformerConfig, dropout_rng=None, decode=False):
    """Shared MLP/MoE block: h (B,S,D) -> (out (B,S,D), moe aux loss)."""
    if cfg.moe_num_experts > 0:
        from deepspeed_tpu.moe.sharded_moe import moe_forward

        def expert_fn(ep, t):
            if cfg.activation == "silu_glu":
                a = jax.nn.silu(_linear(t, ep["wg"])) * _linear(t, ep["wi"])
            else:
                a = _linear(t, ep["wi"])
                if cfg.use_bias:
                    a = a + ep["bi"]
                a = _dense_act(cfg)(a)
            out = _linear(a, ep["wo"])
            if cfg.use_bias:
                out = out + ep["bo"]
            return out

        _residual_keys = ("res_wi", "res_wg", "res_wo", "res_bi", "res_bo",
                          "coef_w", "coef_b")
        expert_params = {k: v for k, v in mlp_p.items()
                         if k != "gate" and k not in _residual_keys}
        mlp_out, aux, _ = moe_forward(
            h,
            mlp_p["gate"],
            expert_fn,
            expert_params,
            k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor * (2 if decode else 1),
            min_capacity=cfg.moe_min_capacity,
            rng=dropout_rng if (cfg.moe_use_rts and not decode) else None,
            use_rts=cfg.moe_use_rts and not decode,
            drop_tokens=cfg.moe_drop_tokens,
        )
        if cfg.moe_use_residual:
            # PR-MoE (reference moe/layer.py:28,45): every token also runs
            # the dense residual MLP; a learned 2-way softmax mixes the two
            res_p = {k[len("res_"):]: v for k, v in mlp_p.items()
                     if k.startswith("res_")}
            dense_out = expert_fn(res_p, h)
            coef = jax.nn.softmax(h @ mlp_p["coef_w"] + mlp_p["coef_b"], axis=-1)
            # channel 0 scales the expert branch, channel 1 the dense MLP
            # (reference moe/layer.py:123 coefficient order)
            mlp_out = mlp_out * coef[..., 0:1] + dense_out * coef[..., 1:2]
        return mlp_out, aux
    aux = jnp.float32(0.0)
    if cfg.activation == "silu_glu":
        up = _linear(h, mlp_p["wi"])
        gate = _linear(h, mlp_p["wg"])
        act = jax.nn.silu(gate) * up
    else:
        act = _linear(h, mlp_p["wi"])
        if cfg.use_bias:
            act = act + mlp_p["bi"]
        act = _dense_act(cfg)(act)
    mlp_out = _linear(act, mlp_p["wo"])
    if cfg.use_bias:
        mlp_out = mlp_out + mlp_p["bo"]
    return mlp_out, aux


def _cast_layers(tree, dtype):
    """fp32->model-dtype cast for layer params that leaves int8-quantized
    weights' fp32 per-channel scales ("s" siblings of "q8") untouched —
    downcasting scales to bf16 would add dequant error comparable to the
    int8 quantization error itself."""
    def cast(path, p):
        if getattr(path[-1], "key", None) == "s":
            return p
        return p.astype(dtype) if p.dtype == jnp.float32 else p

    return jax.tree_util.tree_map_with_path(cast, tree)


def _linear(x, w):
    """Last-dim contraction ``x @ w`` that also accepts a REAL-int8 weight
    ({"q8": int8 (K,N), "s": per-channel scales} — built by the inference
    engine's weight quantizer). Raw arrays take the plain matmul path, so
    training is untouched; quantized leaves run the W8A8 int8-MXU kernel
    (ops/quantizer.int8_linear)."""
    if isinstance(w, dict):
        from deepspeed_tpu.ops.quantizer import int8_linear

        return int8_linear(x, w["q8"], w["s"])
    return x @ w


def _qkv(h, attn_p, cfg: TransformerConfig, positions):
    """Project h -> (q, k, v) heads with positional transform applied."""
    B, S, _ = h.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    q = _linear(h, attn_p["wq"])
    k = _linear(h, attn_p["wk"])
    v = _linear(h, attn_p["wv"])
    if cfg.use_bias:
        q, k, v = q + attn_p["bq"], k + attn_p["bk"], v + attn_p["bv"]
    q = q.reshape(B, S, nh, hd)
    k = k.reshape(B, S, nkv, hd)
    v = v.reshape(B, S, nkv, hd)
    if cfg.pos_embedding == "rope":
        q = _rope(q, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
        k = _rope(k, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
    return q, k, v


def _layer_body(x, layer_params, cfg: TransformerConfig, positions, dropout_rng,
                window=None):
    """One decoder layer; shapes: x (B,S,D), layer_params leaves unstacked.

    Residual topologies: pre-LN (GPT-2/llama), post-LN (BERT / OPT-350m
    ``do_layer_norm_before=False``), and parallel residual (GPT-J / NeoX:
    x + attn(ln1 x) + mlp(ln1|ln2 x))."""
    attn_p, mlp_p = layer_params["attn"], layer_params["mlp"]
    ln1, ln2 = layer_params["ln1"], layer_params["ln2"]
    B, S, D = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim

    def maybe_quant(h):
        if cfg.act_quant_bits > 0:
            from deepspeed_tpu.compression.ops import quantize_activation_ste

            return quantize_activation_ste(h, bits=cfg.act_quant_bits)
        return h

    pre_ln = cfg.norm_position == "pre"
    h = _norm(x, ln1["scale"], ln1.get("bias"), cfg) if pre_ln else x
    h = maybe_quant(h)
    q, k, v = _qkv(h, attn_p, cfg, positions)
    attn_out = _attention(q, k, v, cfg, positions, window=window).reshape(B, S, nh * hd)
    attn_out = _linear(attn_out, attn_p["wo"])
    if cfg.use_bias:
        attn_out = attn_out + attn_p["bo"]
    if cfg.dropout > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - cfg.dropout, attn_out.shape)
        attn_out = jnp.where(keep, attn_out / (1.0 - cfg.dropout), 0.0).astype(attn_out.dtype)

    if cfg.parallel_residual:
        h2 = h if cfg.shared_ln else maybe_quant(_norm(x, ln2["scale"], ln2.get("bias"), cfg))
        mlp_out, aux = _mlp_block(h2, mlp_p, cfg, dropout_rng)
        return x + attn_out + mlp_out, aux

    if pre_ln:
        x = x + attn_out
        h = maybe_quant(_norm(x, ln2["scale"], ln2.get("bias"), cfg))
        mlp_out, aux = _mlp_block(h, mlp_p, cfg, dropout_rng)
        return x + mlp_out, aux

    # post-LN: norm is applied over residual sums (BERT ordering)
    x = _norm(x + attn_out, ln1["scale"], ln1.get("bias"), cfg)
    mlp_out, aux = _mlp_block(maybe_quant(x), mlp_p, cfg, dropout_rng)
    return _norm(x + mlp_out, ln2["scale"], ln2.get("bias"), cfg), aux


# policy registry lives in runtime/activation_checkpointing (shared with the
# engine's configure() surface; adds host-offload as policy name "offload")
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as _ckpt  # noqa: E402
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import resolve_policy as _resolve_remat_policy  # noqa: E402


def _constrain_tp(p, logical_names):
    """Pin a parameter to its tensor-parallel compute sharding (the logical
    spec WITHOUT the ZeRO fsdp dim) at its use site.

    For the embedding tables this is what makes the gradient scatter-add
    partition well: the constraint's transpose pins the table cotangent to
    the same spec, so GSPMD scatters batch-sharded updates locally and
    psums over the batch axes, instead of resharding the full (B, S, D)
    hidden-state gradient from batch sharding to the fsdp grad-accumulator
    spec — its only plan for that is a replicate-then-repartition of the
    whole tensor ("[SPMD] Involuntary full rematerialization")."""
    from deepspeed_tpu import comm
    from deepspeed_tpu.runtime.zero.sharding import logical_to_mesh_spec

    # is_initialized guard: get_mesh() would auto-create a default all-data
    # mesh, silently initializing global comm state from a bare forward()
    if not comm.is_initialized():
        return p
    mesh = comm.get_mesh()
    spec = logical_to_mesh_spec(logical_names)
    return jax.lax.with_sharding_constraint(p, jax.sharding.NamedSharding(mesh, spec))


def _constrain_batch_sharding(x):
    """Pin (B, S, ...) activations to batch sharding: dim0 over (data, fsdp),
    dim1 over sequence, trailing dims unconstrained.

    The constraint's transpose applies the same spec to the cotangent, so the
    hidden-state gradient leaving the backward layer scan stays batch-sharded.
    Without it, GSPMD propagates the (fsdp-sharded) embedding-grad-accumulator
    spec backwards onto the full (B, S, D) gradient, and its only way from
    batch-sharding to hidden-sharding there is a replicate-then-repartition of
    the whole tensor — the "[SPMD] Involuntary full rematerialization" warning
    (a full-tensor all-gather per step on the ZeRO-3 offload path)."""
    from deepspeed_tpu import comm

    if not comm.is_initialized() or x.ndim < 2:
        return x
    mesh = comm.get_mesh()
    batch_axes = tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)
    if not batch_axes:
        return x
    dp = 1
    for a in batch_axes:
        dp *= mesh.shape[a]
    if x.shape[0] % dp != 0:
        return x  # unshardable batch (e.g. odd eval shapes): leave it alone
    U = jax.sharding.PartitionSpec.UNCONSTRAINED
    sub = mesh.shape.get("sequence", 1)
    seq = "sequence" if sub > 1 and x.shape[1] % sub == 0 else U
    spec = jax.sharding.PartitionSpec(
        batch_axes if len(batch_axes) > 1 else batch_axes[0], seq, *([U] * (x.ndim - 2))
    )
    return jax.lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))


def forward(params, cfg: TransformerConfig, tokens, dropout_rng=None,
            ltd_keep_len=None, pld_theta=None, token_types=None, return_hidden=False):
    """tokens (B, S) int32 -> (logits (B, S, V), moe_aux_loss scalar).

    ``ltd_keep_len`` (static int) — random-LTD: each participating layer runs
    on that many randomly kept tokens, outputs scattered back (reference
    data_routing/basic_layer.py:113; engine advances the schedule and re-jits
    per value). ``pld_theta`` (dynamic scalar) — progressive layer drop:
    stochastic depth with keep prob 1 - (l/L)(1-theta) (reference
    progressive_layer_drop.py, consumed at engine.py:1512).
    """
    dtype = cfg.jnp_dtype
    B, S = tokens.shape
    x = jnp.take(_constrain_tp(params["embed"]["tok"], ("vocab", "embed")),
                 tokens, axis=0).astype(dtype)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    if cfg.pos_embedding == "learned":
        pos_t = _constrain_tp(params["embed"]["pos"], ("seq", "embed"))
        # explicit broadcast: the implicit (1, S, D) rank-promotion leaves a
        # keepdims reduce in the transpose whose unit dim drags the batch
        # sharding along, and GSPMD can only reshard that to the fsdp grad
        # spec by replicating ("[SPMD] Involuntary full rematerialization")
        x = x + jnp.broadcast_to(pos_t[:S].astype(dtype), x.shape)
    if cfg.type_vocab_size > 0:
        tt = token_types if token_types is not None else jnp.zeros_like(tokens)
        # same scatter-grad constraint as tok/pos (logical (None, "embed"),
        # matching logical_specs for the type table)
        type_t = _constrain_tp(params["embed"]["type"], (None, "embed"))
        x = x + jnp.take(type_t, tt, axis=0).astype(dtype)
    if cfg.embed_norm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg)
    x = _constrain_batch_sharding(x)

    ltd_on = (
        cfg.random_ltd and ltd_keep_len is not None and 0 < int(ltd_keep_len) < S
        and dropout_rng is not None
    )
    pld_on = cfg.pld_enabled and pld_theta is not None and dropout_rng is not None

    # Window staticness: uniform windows (Mistral-style sliding window, or
    # no windows at all) are baked into the layer body as a python int via
    # this closure — surviving jax.checkpoint and lax.scan untraced, so
    # _attention can take the tile-pruned flash path. Only per-layer-varying
    # windows (GPT-Neo local/global alternation under scan_layers) flow
    # through as traced scalars.
    _varying_windows = cfg.varying_windows
    _static_win = (int(cfg.local_attn_windows[0])
                   if (cfg.local_attn_windows is not None and not _varying_windows)
                   else None)

    def layer_with_routing(x_in, layer_p, rng, layer_frac, window=None):
        """One layer + data-efficiency wrappers (LTD token subset, PLD skip)."""
        if not _varying_windows:
            window = _static_win  # closure keeps it a static python int
        r_drop = r_ltd = r_pld = None
        if rng is not None:
            r_drop, r_ltd, r_pld = jax.random.split(rng, 3)
        if ltd_on:
            from deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer import (
                gather_tokens,
                random_keep_indices,
                scatter_tokens,
            )

            idx = random_keep_indices(r_ltd, B, S, int(ltd_keep_len))
            x_k = gather_tokens(x_in, idx)
            pos_k = jnp.take_along_axis(positions, idx, axis=1)
            new_k, aux = _layer_body(x_k, layer_p, cfg=cfg, positions=pos_k,
                                     dropout_rng=r_drop, window=window)
            new_x = scatter_tokens(x_in, new_k, idx)
        else:
            new_x, aux = _layer_body(x_in, layer_p, cfg=cfg, positions=positions,
                                     dropout_rng=r_drop, window=window)
        if pld_on:
            p_keep = 1.0 - layer_frac * (1.0 - jnp.float32(pld_theta))
            keep = jax.random.bernoulli(r_pld, p_keep)
            new_x = jnp.where(keep, new_x, x_in)
            aux = jnp.where(keep, aux, jnp.zeros_like(aux))
        return new_x, aux

    layer_fn = layer_with_routing
    if cfg.remat:
        # unrolled layers receive window as a static python int (per-layer
        # flash tile pruning); it must stay static THROUGH the checkpoint
        # wrapper or the tracer defeats the isinstance(int) gate in
        # _attention. The scan path passes traced windows, where
        # static_argnums would be an error.
        static_args = (4,) if (not cfg.scan_layers and _varying_windows) else ()
        layer_fn = jax.checkpoint(layer_fn, policy=_resolve_remat_policy(cfg.remat_policy),
                                  static_argnums=static_args)
    if _ckpt.partition_activations_enabled():
        # partition_activations (reference checkpointing.py:366): shard the
        # layer-boundary residual over tensor(+sequence) so the saved stash
        # is 1/TP and GSPMD swaps the layer allreduce for AG+RS
        _inner_fn = layer_fn

        def layer_fn(x_in, *rest):  # noqa: F811
            return _inner_fn(_ckpt.partition_saved_activation(x_in), *rest)
    if _ckpt.profile_enabled():
        _profiled_fn = layer_fn

        def layer_fn(x_in, *rest):  # noqa: F811
            with jax.named_scope("checkpoint_layer"):
                return _profiled_fn(x_in, *rest)

    layers = _cast_layers(params["layers"], dtype)
    needs_rng = (
        cfg.dropout > 0.0 or cfg.moe_use_rts or ltd_on or pld_on
    ) and dropout_rng is not None
    L = cfg.num_layers
    layer_fracs = jnp.arange(1, L + 1, dtype=jnp.float32) / L
    if cfg.scan_layers:
        if needs_rng:
            layer_rngs = jax.random.split(dropout_rng, L)
        else:
            layer_rngs = jnp.zeros((L, 2), jnp.uint32)

        # uniform/absent windows are baked into the layer body as a static
        # int (see layer_with_routing); the stacked array only carries
        # per-layer-VARYING windows
        windows = (jnp.asarray(cfg.local_attn_windows, jnp.int32)
                   if _varying_windows else jnp.zeros((L,), jnp.int32))

        def scan_step(carry, inp):
            layer_p, rng, frac, win = inp
            rng = rng if needs_rng else None
            win = win if _varying_windows else None
            new_x, aux = layer_fn(carry, layer_p, rng, frac, win)
            return new_x, aux

        x, auxs = jax.lax.scan(scan_step, x, (layers, layer_rngs, layer_fracs, windows))
        aux_total = jnp.sum(auxs)
    else:
        aux_total = jnp.float32(0.0)
        for i in range(L):
            layer_p = jax.tree.map(lambda p: p[i], layers)
            rng = jax.random.fold_in(dropout_rng, i) if needs_rng else None
            # unrolled layers: every window is a static python int, so
            # each local layer gets the tile-pruned flash path (uniform
            # windows are redundantly re-set by the layer-body closure)
            win = (int(cfg.local_attn_windows[i])
                   if cfg.local_attn_windows is not None else None)
            x, aux = layer_fn(x, layer_p, rng, layer_fracs[i], win)
            aux_total = aux_total + aux

    if cfg.norm_position == "pre":  # post-LN stacks end normalized already
        x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg)
    if return_hidden:
        return x, aux_total
    return _vocab_head(x, params, cfg, dtype), aux_total


def _vocab_head(x, params, cfg: TransformerConfig, dtype):
    """Hidden states -> vocab logits.

    An optional ``mlm_head`` in params (BERT ``cls.predictions.transform``
    / DistilBERT ``vocab_transform``+``vocab_layer_norm``: dense + act +
    LayerNorm, then a decoder bias) runs before the tied or untied
    projection — MLM checkpoints deviate from HF numerics without it."""
    mh = params.get("mlm_head")
    if mh is not None:
        x = _dense_act(cfg)(x @ mh["w"].astype(dtype) + mh["b"].astype(dtype))
        x = _norm(x, mh["ln_scale"], mh.get("ln_bias"), cfg)
    if cfg.tie_embeddings:
        logits = jnp.einsum("...sd,vd->...sv", x, params["embed"]["tok"].astype(dtype))
    else:
        w = params["lm_head"]["w"]
        logits = _linear(x, w if isinstance(w, dict) else w.astype(dtype))
        if "b" in params.get("lm_head", {}):
            logits = logits + params["lm_head"]["b"].astype(dtype)
    if mh is not None and "proj_bias" in mh:
        logits = logits + mh["proj_bias"].astype(dtype)
    return logits


def apply(params, cfg: TransformerConfig, tokens, dropout_rng=None, token_types=None):
    """tokens (B, S) int32 -> logits (B, S, V)."""
    return forward(params, cfg, tokens, dropout_rng=dropout_rng, token_types=token_types)[0]


def encode(params, cfg: TransformerConfig, tokens, token_types=None):
    """tokens (B, S) int32 -> final hidden states (B, S, D) (encoder use:
    the BERT-family injection policies; reference policy ABC policy.py)."""
    return forward(params, cfg, tokens, token_types=token_types, return_hidden=True)[0]


# ---------------------------------------------------------------------------
# streaming (sub-group) execution pieces — ZeRO-Infinity parameter offload
# (runtime/zero/param_offload.py). The decoder is cut at layer-group
# boundaries so host-resident weights stream through HBM one group at a
# time; the activation at each boundary is the only checkpoint kept.
# Reference analogue: stage3.py sub_group_size streaming +
# partitioned_param_swapper.py.
# ---------------------------------------------------------------------------

def embed_fwd(params, cfg: TransformerConfig, tokens):
    """tokens (..., S) -> embedded activations (..., S, D) in model dtype
    (leading dims beyond batch — e.g. a microbatch dim — broadcast through)."""
    dtype = cfg.jnp_dtype
    S = tokens.shape[-1]
    x = jnp.take(params["embed"]["tok"], tokens, axis=0).astype(dtype)
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["pos"][:S].astype(dtype)
    if cfg.type_vocab_size > 0:
        x = x + params["embed"]["type"][0].astype(dtype)
    if cfg.embed_norm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg)
    return x


def layer_slice_fwd(layers_slice, cfg: TransformerConfig, x, windows=None):
    """Run a contiguous group of decoder layers (stacked leaves, leading dim
    = group size). Returns (x', moe_aux_sum). No dropout in the streaming
    path (offload training runs at scales where dropout is off).

    ``windows`` — (group_size,) i32 per-layer local-attention windows for
    models with cfg.local_attn_windows (GPT-Neo); the caller slices the
    global tuple to this group's [lo:hi) rows. None = all-global."""
    B, S, D = x.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    layer_fn = partial(_layer_body, cfg=cfg, positions=positions, dropout_rng=None)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, policy=_resolve_remat_policy(cfg.remat_policy))
    dtype = cfg.jnp_dtype
    layers = _cast_layers(layers_slice, dtype)

    n = jax.tree.leaves(layers_slice)[0].shape[0]
    if windows is None and cfg.local_attn_windows is not None:
        raise ValueError(
            "cfg.local_attn_windows is set: layer_slice_fwd needs this "
            "group's per-layer windows (pass windows=cfg.local_attn_windows[lo:hi])"
        )
    wins = windows if windows is not None else jnp.zeros((n,), jnp.int32)

    def scan_step(carry, inp):
        layer_p, win = inp
        win = win if windows is not None else None
        new_x, aux = layer_fn(carry, layer_p, window=win)
        return new_x, aux

    x, auxs = jax.lax.scan(scan_step, x, (layers, wins))
    return x, jnp.sum(auxs)


def _ce_from_logits(logits, batch, tokens, denom=None):
    """Shift + masked token cross-entropy shared by loss_fn / head_loss_fwd.

    ``denom`` overrides the masked normalizer — callers that sum partial CE
    terms across microbatches (the 1F1B pipeline head) pass the GLOBAL mask
    token count so per-microbatch sums add up to the whole-batch mean.
    """
    from deepspeed_tpu.ops.cross_entropy import softmax_cross_entropy

    if "labels" in batch:
        labels = batch["labels"]
        logits_for_loss = logits
    else:
        labels = tokens[..., 1:]
        logits_for_loss = logits[..., :-1, :]
    nll = softmax_cross_entropy(logits_for_loss, labels)
    mask = batch.get("loss_mask")
    if mask is not None:
        mask = mask[..., : nll.shape[-1]].astype(jnp.float32)
        if denom is None:
            denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(nll * mask) / denom
    if denom is not None:
        return jnp.sum(nll) / denom
    return jnp.mean(nll)


def head_loss_fwd(params, cfg: TransformerConfig, x, batch, denom=None):
    """Final norm + logits + cross-entropy (MoE aux is added by the caller
    from the per-group aux sums)."""
    dtype = cfg.jnp_dtype
    if cfg.norm_position == "pre":
        x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg)
    logits = _vocab_head(x, params, cfg, dtype)
    return _ce_from_logits(logits, batch, batch["input_ids"], denom=denom)


# ---------------------------------------------------------------------------
# KV-cache decode path (reference: csrc/transformer/inference softmax_context
# kernels + InferenceEngine token loop, inference/engine.py:560)
# ---------------------------------------------------------------------------

def init_cache(cfg: TransformerConfig, batch_size: int, max_len: Optional[int] = None):
    """Per-layer KV cache: (L, B, T, kv_heads, head_dim) in model dtype —
    or, with ``kv_cache_dtype="int8"``, {"q8": int8, "s": f32 per-token-
    per-head scales} per component (half the decode-read bytes; the
    quantized write / dequantized read live in inference_ops)."""
    T = max_len or cfg.max_seq_len
    shape = (cfg.num_layers, batch_size, T, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        def q_component():
            return {"q8": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:-1] + (1,), jnp.float32)}

        return {"k": q_component(), "v": q_component()}
    return {
        "k": jnp.zeros(shape, cfg.jnp_dtype),
        "v": jnp.zeros(shape, cfg.jnp_dtype),
    }


def cache_alloc_len(cache) -> int:
    """Allocated time-axis length of a cache pytree (dense or int8)."""
    return jax.tree.leaves(cache)[0].shape[2]


def kv_read_bytes_per_row(cfg: TransformerConfig, read_len: int,
                          tp: int = 1) -> int:
    """HBM bytes ONE sequence row's attention streams from the KV cache
    when a decode step attends ``read_len`` slots: K and V across all
    layers, int8 payload + fp32 per-token-per-head scales when
    ``kv_cache_dtype == "int8"``. This is the deterministic host-side
    accounting behind the ``kv_bytes_read`` telemetry field and the
    bench's roofline math — it counts exactly what the compiled read
    touches, so tests can assert it.

    ``tp`` is the tensor width the cache's heads axis is ACTUALLY split
    over (parallel.partition.kv_shard_width): each chip streams only its
    head shard, so the PER-CHIP bytes — the quantity that bounds a
    bandwidth-limited decode step — divide by it. Must divide kv_heads
    (the caller resolves the replicated fallback to tp=1)."""
    assert cfg.kv_heads % tp == 0, (cfg.kv_heads, tp)
    if cfg.kv_cache_dtype == "int8":
        per_slot = cfg.kv_heads * (cfg.head_dim * 1 + 4)  # q8 payload + s
    else:
        per_slot = cfg.kv_heads * cfg.head_dim * jnp.dtype(cfg.jnp_dtype).itemsize
    return 2 * cfg.num_layers * read_len * per_slot // tp


def _layer_body_cached(x, layer_params, k_cache, v_cache, cfg: TransformerConfig, positions, pos,
                       window=None, read_len=None):
    """One decoder layer over a segment of S new tokens with KV cache.

    x: (B, S, D); k_cache/v_cache: (B, T, nkv, hd) for THIS layer; pos: the
    count of tokens already cached — a scalar (all rows aligned: plain
    prefill/decode) or an (B,) vector (rows at different depths: the
    speculative-decode verify/draft path writes each row's segment at its
    own offset). ``read_len`` (static int) tight-reads the cache: attention
    streams only slots [0, read_len) — the caller guarantees it covers
    every attended position. Returns (x, new_k_cache, new_v_cache).
    """
    attn_p, mlp_p = layer_params["attn"], layer_params["mlp"]
    ln1, ln2 = layer_params["ln1"], layer_params["ln2"]
    B, S, D = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim

    pre_ln = cfg.norm_position == "pre"
    h = _norm(x, ln1["scale"], ln1.get("bias"), cfg) if pre_ln else x
    q, k, v = _qkv(h, attn_p, cfg, positions)

    # PREFILL fast path: pos is the literal int 0 only in the prefill
    # program (compile_decode_fns traces with a Python 0), where attention
    # over the segment is exactly causal self-attention — the Pallas flash
    # kernel computes it without materializing the (B, H, S, T) logits
    # (reference: the inference softmax_context kernel family). Static
    # windows ride the kernel's tile-pruned band path; the rolling cache
    # RELIES on this (segment attention must not read the ring, whose
    # slots a long segment partially evicts).
    from deepspeed_tpu.ops.pallas.flash_attention import supports_seq_len

    use_flash_prefill = (
        isinstance(pos, int) and pos == 0 and S > 1
        and (window is None or isinstance(window, int))
        and cfg.attn_impl == "pallas" and cfg.causal
        and cfg.pos_embedding != "alibi"
        # seq lens the auto-tiler can't cover stay on the einsum path
        # rather than erroring at trace time
        and supports_seq_len(S)
    )
    ring = cfg.rolling_kv_cache

    k_cache, v_cache = update_kv_cache(k_cache, v_cache, k, v, pos, positions,
                                       ring=ring)

    if use_flash_prefill:
        w = window if isinstance(window, int) and window > 0 and window < S else None
        attn_out = _flash_sharded(q, k, v, cfg, causal=True,
                                  window=w).reshape(B, S, nh * hd)
        attn_out = _linear(attn_out, attn_p["wo"])
        if cfg.use_bias:
            attn_out = attn_out + attn_p["bo"]
        return _finish_layer_cached(x, h, attn_out, layer_params, cfg, k_cache, v_cache)

    cache_T = (k_cache["q8"] if isinstance(k_cache, dict) else k_cache).shape[1]
    assert not (ring and S > 1 and cache_T < S), (
        "rolling KV cache: a multi-token segment longer than the ring must "
        f"take the flash prefill path (S={S}, cache={cache_T}) — a segment "
        "read through the ring would see its own evictions; the engine "
        "gates cache sizing on this")
    slopes = _alibi_slopes(nh) if cfg.pos_embedding == "alibi" else None
    attn_out = softmax_context(
        q, k_cache, v_cache, pos, scale=cfg.attn_scale, positions=positions,
        alibi_slopes=slopes, local_window=window, ring=ring,
        read_len=read_len if not ring else None,
    ).reshape(B, S, nh * hd)
    attn_out = _linear(attn_out, attn_p["wo"])
    if cfg.use_bias:
        attn_out = attn_out + attn_p["bo"]
    return _finish_layer_cached(x, h, attn_out, layer_params, cfg, k_cache, v_cache)


def _finish_layer_cached(x, h, attn_out, layer_params, cfg: TransformerConfig, k_cache, v_cache):
    """Residual topology + MLP tail of a cached layer (shared by the einsum
    and flash-prefill attention paths)."""
    mlp_p = layer_params["mlp"]
    ln1, ln2 = layer_params["ln1"], layer_params["ln2"]

    if cfg.parallel_residual:
        h2 = h if cfg.shared_ln else _norm(x, ln2["scale"], ln2.get("bias"), cfg)
        mlp_out, _ = _mlp_block(h2, mlp_p, cfg, decode=True)
        return x + attn_out + mlp_out, k_cache, v_cache

    if cfg.norm_position == "pre":
        x = x + attn_out
        h = _norm(x, ln2["scale"], ln2.get("bias"), cfg)
        mlp_out, _ = _mlp_block(h, mlp_p, cfg, decode=True)
        return x + mlp_out, k_cache, v_cache

    x = _norm(x + attn_out, ln1["scale"], ln1.get("bias"), cfg)
    mlp_out, _ = _mlp_block(x, mlp_p, cfg, decode=True)
    return _norm(x + mlp_out, ln2["scale"], ln2.get("bias"), cfg), k_cache, v_cache


def forward_with_cache(params, cfg: TransformerConfig, tokens, cache, pos, positions=None,
                       read_len=None):
    """Segment forward with KV cache (prefill: S = prompt len, pos = 0;
    decode: S = 1). ``pos`` may be a scalar (all rows aligned) or an (B,)
    vector of per-row depths (speculative decoding — rows advance by their
    own accepted counts). ``positions`` (B, S) overrides the derived token
    positions for RAGGED/padded prompts: pad slots carry position >= cache
    length, so their KV writes drop out of bounds and real tokens pack
    densely per row (requires vector ``pos``). ``read_len`` (static int)
    tight-reads the cache time axis — attention streams slots
    [0, read_len) only; the caller guarantees the active extent fits.
    Returns (logits (B,S,V), updated cache)."""
    dtype = cfg.jnp_dtype
    B, S = tokens.shape
    if read_len is not None and read_len >= cache_alloc_len(cache):
        read_len = None  # degenerate slice: the allocation is already tight
    x = jnp.take(params["embed"]["tok"], tokens, axis=0).astype(dtype)
    if positions is not None:
        assert jnp.ndim(pos) == 1, "explicit positions require vector pos"
    elif jnp.ndim(pos) == 1:
        positions = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # (B, S)
    else:
        positions = pos + jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    if cfg.pos_embedding == "learned":
        pos_table = params["embed"]["pos"].astype(dtype)
        clamped = jnp.minimum(positions, pos_table.shape[0] - 1)
        x = x + (jnp.take(pos_table, clamped, axis=0) if jnp.ndim(pos) == 1
                 else jnp.take(pos_table, clamped[0], axis=0))
    if cfg.type_vocab_size > 0:
        # decode has no token-type stream; type 0 matches forward()'s default
        x = x + params["embed"]["type"][0].astype(dtype)
    if cfg.embed_norm:
        en = params["embed_norm"]
        x = _norm(x, en["scale"], en.get("bias"), cfg)

    layers = _cast_layers(params["layers"], dtype)

    # mirror forward(): a uniform window stays a STATIC int through the
    # scan (flash band prefill + the rolling cache depend on it); only
    # per-layer-varying windows ride the scan as traced scalars
    uniform_w = cfg.uniform_window
    varying = cfg.varying_windows
    windows = (
        jnp.asarray(cfg.local_attn_windows, jnp.int32)
        if varying else jnp.zeros((cfg.num_layers,), jnp.int32)
    )

    def body(carry, inp):
        h = carry
        layer_p, k_c, v_c, win = inp
        win = win if varying else uniform_w
        h, k_c, v_c = _layer_body_cached(h, layer_p, k_c, v_c, cfg, positions, pos,
                                         window=win, read_len=read_len)
        return h, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(body, x, (layers, cache["k"], cache["v"], windows))
    if cfg.norm_position == "pre":
        x = _norm(x, params["final_norm"]["scale"], params["final_norm"].get("bias"), cfg)
    return _vocab_head(x, params, cfg, dtype), {"k": new_k, "v": new_v}


def loss_fn(params, cfg: TransformerConfig, batch, rng=None, ltd_keep_len=None, pld_theta=None):
    """Next-token cross entropy. batch: {'input_ids': (B,S) int32} and
    optional 'labels' (shifted internally if absent), 'loss_mask', and
    'token_type_ids' (BERT-family segment ids)."""
    tokens = batch["input_ids"]
    logits, moe_aux = forward(
        params, cfg, tokens, dropout_rng=rng,
        ltd_keep_len=ltd_keep_len, pld_theta=pld_theta,
        token_types=batch.get("token_type_ids"),
    )
    ce = _ce_from_logits(logits, batch, tokens)
    if cfg.moe_num_experts > 0:
        ce = ce + cfg.moe_aux_loss_coef * moe_aux
    return ce


class TransformerModel:
    """Engine-protocol wrapper (see runtime/engine.py): init/loss/logical_specs."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg

    @classmethod
    def from_preset(cls, name: str, **overrides):
        return cls(get_config(name, **overrides))

    def init(self, rng):
        return init(rng, self.cfg)

    def loss(self, params, batch, rng=None, ltd_keep_len=None, pld_theta=None):
        return loss_fn(
            params, self.cfg, batch, rng=rng,
            ltd_keep_len=ltd_keep_len, pld_theta=pld_theta,
        )

    def apply(self, params, tokens, rng=None):
        return apply(params, self.cfg, tokens, dropout_rng=rng)

    def logical_specs(self, params):
        return logical_specs(params, self.cfg)

    def flops_per_token(self, seq_len: int) -> float:
        return self.cfg.flops_per_token(seq_len)

    def num_params(self) -> int:
        return self.cfg.num_params()
