"""Accelerator selection (reference: ``accelerator/real_accelerator.py``:
``get_accelerator()``/``set_accelerator()`` injection seam)."""

_accelerator = None


def get_accelerator():
    global _accelerator
    if _accelerator is None:
        from deepspeed_tpu.accelerator.tpu_accelerator import TpuAccelerator

        _accelerator = TpuAccelerator()
    return _accelerator


def set_accelerator(accel) -> None:
    """Inject a third-party accelerator implementation (must be set before the
    first get_accelerator() call to take effect everywhere)."""
    global _accelerator
    _accelerator = accel
