"""TPU (and CPU-simulated-TPU) implementation of the accelerator seam.

Counterpart of the reference's ``accelerator/cuda_accelerator.py`` — but backed
by ``jax.devices()`` / XLA memory stats / ``jax.profiler`` ranges instead of
torch.cuda streams and events.
"""

import contextlib

import jax

from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator


class TpuAccelerator(Accelerator):
    _name = "tpu"

    def __init__(self):
        self._platform = jax.default_backend()

    # --- identity -------------------------------------------------------
    def device_name(self, device_index=None) -> str:
        devices = jax.devices()
        if device_index is None:
            return self._platform
        return str(devices[device_index])

    def is_available(self) -> bool:
        return len(jax.devices()) > 0

    def device_count(self) -> int:
        return jax.device_count()

    def local_device_count(self) -> int:
        return jax.local_device_count()

    def current_device(self):
        return jax.local_devices()[0]

    def current_device_name(self) -> str:
        return str(jax.local_devices()[0])

    def communication_backend_name(self) -> str:
        return "xla"

    def on_accelerator(self, array) -> bool:
        try:
            return any(d.platform != "cpu" for d in array.devices())
        except Exception:
            return False

    # --- memory ---------------------------------------------------------
    def memory_stats(self, device_index=None) -> dict:
        dev = jax.local_devices()[device_index or 0]
        stats = dev.memory_stats()
        return dict(stats) if stats else {}

    def memory_allocated(self, device_index=None) -> int:
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))

    def total_memory(self, device_index=None) -> int:
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def reset_peak_memory_stats(self, device_index=None):
        return None  # XLA does not expose a reset; parity no-op

    # --- dtype / capability --------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is emulated on TPU MXU (bf16-native); supported for parity
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # --- RNG ------------------------------------------------------------
    def default_rng(self, seed: int):
        return jax.random.PRNGKey(seed)

    def manual_seed(self, seed: int):
        return jax.random.PRNGKey(seed)

    # --- profiler ranges (nvtx push/pop semantics: LIFO stack) ----------
    def range_push(self, msg: str):
        if not hasattr(self, "_range_stack"):
            self._range_stack = []
        annotation = jax.profiler.TraceAnnotation(msg)
        annotation.__enter__()
        self._range_stack.append(annotation)

    def range_pop(self):
        stack = getattr(self, "_range_stack", [])
        if stack:
            stack.pop().__exit__(None, None, None)

    @contextlib.contextmanager
    def range(self, msg: str):
        with jax.profiler.TraceAnnotation(msg):
            yield

    # --- op builder dispatch -------------------------------------------
    def create_op_builder(self, op_name: str):
        builder_cls = self.get_op_builder(op_name)
        return builder_cls() if builder_cls is not None else None

    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.op_builder import ALL_OPS

        return ALL_OPS.get(op_name)
