"""Hardware abstraction seam.

TPU-native re-design of the reference's ``accelerator/abstract_accelerator.py``
(``DeepSpeedAccelerator`` ABC). The reference ABC is stream/event centric
because CUDA exposes manual scheduling; under XLA the compiler owns scheduling,
so the surviving surface is: device enumeration, memory stats, RNG, dtype
support, profiler ranges, the communication backend name, and op dispatch.
"""

import abc


class Accelerator(abc.ABC):
    _name: str = "abstract"

    # --- identity -------------------------------------------------------
    def device_name(self, device_index=None) -> str:
        raise NotImplementedError

    def is_available(self) -> bool:
        raise NotImplementedError

    @abc.abstractmethod
    def device_count(self) -> int:
        """Global device count visible to this process group."""

    @abc.abstractmethod
    def local_device_count(self) -> int:
        """Devices attached to this host process."""

    def current_device(self):
        raise NotImplementedError

    def communication_backend_name(self) -> str:
        """'xla' on TPU: collectives are compiler-inserted over ICI/DCN
        (reference returns 'nccl' for CUDA, abstract_accelerator.py:177)."""
        raise NotImplementedError

    # --- memory ---------------------------------------------------------
    def memory_stats(self, device_index=None) -> dict:
        raise NotImplementedError

    def memory_allocated(self, device_index=None) -> int:
        raise NotImplementedError

    def total_memory(self, device_index=None) -> int:
        raise NotImplementedError

    def available_memory(self, device_index=None) -> int:
        raise NotImplementedError

    def empty_cache(self) -> None:
        """XLA owns allocation; provided for API parity."""
        return None

    # --- dtype / capability --------------------------------------------
    def is_bf16_supported(self) -> bool:
        raise NotImplementedError

    def is_fp16_supported(self) -> bool:
        raise NotImplementedError

    def supported_dtypes(self):
        raise NotImplementedError

    # --- RNG ------------------------------------------------------------
    def default_rng(self, seed: int):
        raise NotImplementedError

    # --- profiler ranges (nvtx analogue) --------------------------------
    def range_push(self, msg: str):
        raise NotImplementedError

    def range_pop(self):
        raise NotImplementedError

    # --- op builder dispatch -------------------------------------------
    def create_op_builder(self, op_name: str):
        raise NotImplementedError

    def get_op_builder(self, op_name: str):
        raise NotImplementedError
