"""Fleet membership primitives for the router (serving/router.py):
replica records, the engine-rid namespace partition, and the per-replica
telemetry proxies that let N serving engines share ONE hub — one trace
file, one metrics registry — with every event and metric tagged by
replica id.

Everything here is jax-free host bookkeeping, like the router itself:
the fleet layer never touches device state directly, it only drives
``ServingEngine`` public APIs.
"""

from typing import Callable, Dict, Optional

# Replica lifecycle (router-side view; the replica's own ``health()`` is
# the input, these are the router's placement decisions):
#
#   HEALTHY    — in rotation, takes placements.
#   RECOVERING — breaker open on the replica (PR 7 ladder running): no
#                placements, backed off; re-admitted when health() says ok.
#   DRAINING   — admission closed by router.drain(); in-flight work
#                finishes, then the replica retires to DRAINED.
#   FAILED     — the replica's step() raised terminally or its engine is
#                poisoned with no recovery armed: the router must evict
#                (migrate its live streams to survivors) on the next step.
#   DEAD       — evicted; live work migrated or honestly shed.
#   DRAINED    — drained to empty and retired; zero requests lost.
HEALTHY = "healthy"
RECOVERING = "recovering"
DRAINING = "draining"
FAILED = "failed"
DEAD = "dead"
DRAINED = "drained"

# States the router will place new work on (everything else is skipped
# by routing; DRAINING still *finishes* what it holds).
PLACEABLE = (HEALTHY,)
# States with a live engine the router still steps.
STEPPABLE = (HEALTHY, RECOVERING, DRAINING)

# Engine-rid namespace partition: replica slot i assigns natural engine
# rids from i * RID_STRIDE. A request migrated off a dead replica keeps
# its pinned engine rid — its RNG identity — and the stride guarantees
# no survivor ever assigned (or will naturally assign) that rid itself.
# Slot 0 starts at 0: a single-replica fleet is rid-for-rid identical to
# a bare ServingEngine.
RID_STRIDE = 1 << 20


class Replica:
    """One fleet member: the serving engine plus the router's view of it
    (placement state, shed-hint backoff, local→fleet rid map)."""

    def __init__(self, replica_id: str, serving, slot: int):
        self.replica_id = replica_id
        self.serving = serving
        self.slot = slot                    # rid-partition slot (monotonic)
        self.state = HEALTHY
        self.backoff_until = 0.0            # shed retry_after_s hints land here
        self.local_to_fleet: Dict[int, int] = {}   # local serving rid -> fleet rid
        self.admitted = 0                   # placements this router made here
        self.shed = 0                       # final fleet verdicts shed here
        self.migrated_in = 0                # requests re-admitted from dead peers
        self.migrated_out = 0               # live requests moved off at eviction

    def __repr__(self):  # pragma: no cover — debugging aid
        return (f"Replica({self.replica_id!r}, state={self.state!r}, "
                f"slot={self.slot})")


class ScopedRegistry:
    """A :class:`MetricsRegistry` view that stamps every metric with a
    ``replica`` label — replicas share the fleet's one registry, and
    per-replica series stay separable in ``/metrics`` and ``dump()``."""

    def __init__(self, base, replica_id: str):
        self._base = base
        self._replica = replica_id

    def _labels(self, labels: Optional[dict]) -> dict:
        merged = dict(labels) if labels else {}
        merged.setdefault("replica", self._replica)
        return merged

    def counter(self, name: str, labels: Optional[dict] = None):
        return self._base.counter(name, self._labels(labels))

    def gauge(self, name: str, labels: Optional[dict] = None):
        return self._base.gauge(name, self._labels(labels))

    def histogram(self, name: str, labels: Optional[dict] = None):
        return self._base.histogram(name, self._labels(labels))

    def span(self, name: str, labels: Optional[dict] = None):
        return self._base.span(name, self._labels(labels))

    def dump(self) -> dict:
        return self._base.dump()


class ReplicaTelemetry:
    """Per-replica facade over the fleet's shared telemetry hub: every
    trace event gains a ``replica`` field and every metric a ``replica``
    label, through ONE underlying trace writer and registry.

    ``close()`` is a no-op — replicas come and go (drain/add, rolling
    restart) but the hub belongs to the fleet; only ``FleetRouter.
    close()`` closes the base hub, once, after the last replica."""

    def __init__(self, base, replica_id: str):
        self._base = base
        self.replica = replica_id
        self.registry = ScopedRegistry(base.registry, replica_id)

    @property
    def enabled(self) -> bool:
        return self._base.enabled

    def emit(self, kind: str, payload: dict, **kwargs):
        tagged = dict(payload)
        tagged.setdefault("replica", self.replica)
        return self._base.emit(kind, tagged, **kwargs)

    def span(self, name: str, labels: Optional[dict] = None):
        return self.registry.span(name, labels)

    def close(self):
        """No-op by design: see class docstring."""

    def __getattr__(self, name):
        # everything else (cfg, role, summary, compile_recorder, ...)
        # answers from the shared hub
        return getattr(self._base, name)


def attach_replica_telemetry(engine, base_hub, replica_id: str):
    """Point a (telemetry-off-built) continuous-batching engine at the
    fleet's shared hub through a :class:`ReplicaTelemetry` facade. Must
    run BEFORE the engine is wrapped in ``ServingEngine`` (which caches
    the hub at construction). Returns the facade."""
    tele = ReplicaTelemetry(base_hub, replica_id)
    engine._eng.telemetry = tele
    return tele


ReplicaFactory = Callable[[str], object]
