"""Fleet router: N serving replicas behind one admission surface,
surviving replica failure (docs/serving.md "Fleet").

The :class:`FleetRouter` is jax-free — like the serving layer under it,
it is host bookkeeping over ``ServingEngine`` public APIs, so the
placement, failover, and drain logic is testable in milliseconds with a
fake engine. The load-bearing behaviors:

- **Routing** — join-shortest-committed-tokens: candidates are ranked by
  ``committed_tokens()`` and consulted via ``admission_outlook()`` (no
  side effects); the ONE real ``submit`` lands on the best replica that
  would admit, spilling over to the next-best when the first would only
  queue or shed. A shed verdict's ``retry_after_s`` hint backs the
  replica off so the router stops hammering a recovering/full replica.
- **Health-driven ejection** — ``probe()`` (inline per step, and
  optionally on a daemon thread) walks each replica's ``health()``
  ladder: ok ⇢ healthy, recovering ⇢ backed out of rotation, poisoned ⇢
  failed. A failed replica — or one whose ``step()`` raises terminally —
  is evicted: every live request is re-admitted onto survivors from the
  replica's ``RecoveryLog`` snapshot and resumes **bitwise** mid-token
  (``submit(rid=, gen_base=)`` under the fleet's partitioned engine-rid
  namespace — see ``fleet.RID_STRIDE``); what no survivor can hold is
  shed honestly. Fleet conservation holds: admitted == finished + shed
  + expired + cancelled.
- **Rolling drain/add** — ``drain()`` finishes a replica's in-flight
  work while admissions spill to peers; ``add()`` brings a factory-built
  replica into rotation under live load; ``rolling_restart()`` composes
  them over the whole fleet with zero lost requests.

The router owns the FLEET rid namespace: callers hold fleet rids,
``_routes`` maps each to its current ``(replica, local rid)`` placement
— which eviction rewrites mid-stream without the caller noticing.
"""

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.serving.fleet import (
    DEAD,
    DRAINED,
    DRAINING,
    FAILED,
    HEALTHY,
    PLACEABLE,
    RECOVERING,
    RID_STRIDE,
    STEPPABLE,
    Replica,
)
from deepspeed_tpu.serving.request import (
    ADMITTED,
    FINISHED,
    SHED,
    TERMINAL_STATES,
    Admission,
    ServeRequest,
)
from deepspeed_tpu.telemetry.spans import SpanEmitter

# tick_stats fields that are ratios/identities, recomputed (not summed)
# when aggregating across replicas
_DERIVED_TICK_FIELDS = ("pipeline_depth", "mean_emitted_per_tick",
                        "block_ms_per_token", "overlap_frac", "utilization")


class FleetStream:
    """Per-token pull iterator over a FLEET rid: replays what the current
    placement already emitted, then drives ``router.step()`` for more.
    Migration is invisible — the survivor's record is pre-seeded with
    every token the dead replica emitted, so the cursor just keeps
    walking the same logical stream."""

    def __init__(self, router: "FleetRouter", frid: int):
        self._router = router
        self._frid = frid
        self._pos = 0

    def __iter__(self) -> "FleetStream":
        return self

    def __next__(self) -> int:
        while True:
            req = self._router.request(self._frid)
            if req is not None and self._pos < len(req.tokens):
                tok = req.tokens[self._pos]
                self._pos += 1
                return int(tok)
            if req is None or req.state in TERMINAL_STATES:
                raise StopIteration
            if not self._router.has_work():
                # live request but nothing can make progress (engine gone
                # mid-eviction): never spin
                raise StopIteration
            self._router.step()


class FleetRouter:
    """Load balancer + failover layer over N ``ServingEngine`` replicas.

    ``factory(replica_id) -> ServingEngine`` builds one replica; build
    the engine with telemetry OFF and attach the fleet's shared hub via
    ``fleet.attach_replica_telemetry`` so every replica's events/metrics
    land in one trace tagged by replica id. ``telemetry`` is the base
    hub for fleet-level ``router_event``s / ``fleet_*`` metrics (when
    None, the first replica's hub is adopted).

    Drive it exactly like a single serving engine: ``submit`` /
    ``step`` / ``reap`` / ``stream`` / ``result`` / ``cancel`` — the
    returned rids are fleet-scoped and survive replica death."""

    def __init__(self, factory: Callable[[str], object], replicas: int = 1,
                 *, telemetry=None, clock=time.monotonic):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self._factory = factory
        self._clock = clock
        # Probe-thread discipline (ds-lint thread-shared-state): every
        # attribute the probe/ops threads read is read under this lock;
        # the probe thread NEVER emits trace events itself (TraceWriter
        # is main-thread-owned) — it enqueues into _pending_events, and
        # step() drains the queue on the main thread.
        self._lock = threading.RLock()
        self._pending_events: List[dict] = []
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._replicas: Dict[str, Replica] = {}
        self._routes: Dict[int, Tuple[str, int]] = {}  # fleet rid -> (replica, local rid)
        self._dead_reaped: Dict[int, ServeRequest] = {}
        self._next_frid = 0
        self._next_slot = 0
        self._tick = 0
        self._hooks: Dict[int, List[Callable]] = {}
        self._rolling: Optional[dict] = None
        self._submitted = 0
        self._admitted = 0
        self._shed = 0
        self._spillovers = 0
        self._migrated = 0
        self._lost = 0
        self._deaths = 0
        self._ops_server = None
        self._closed = False
        self._on_step_hooks: List[Callable] = []
        # Degradation-ladder knobs (driven by serving.autoscaler): cap
        # output length for no-SLO tenants, then shed batch backfill
        # before interactive — both consulted in submit() for requests
        # with no deadline, both journaled by the autoscaler.
        self.cap_new_tokens_no_slo: Optional[int] = None
        self.shed_backfill = False
        self._tele = telemetry
        for _ in range(replicas):
            self.add()
        if self._tele is None:  # adopt the first replica's (possibly
            # facade-wrapped) hub; fleet events go to the BASE hub
            first = next(iter(self._replicas.values()))
            tele = first.serving._tele
            self._tele = getattr(tele, "_base", tele)
        # migration-bridge spans go to the base hub untagged (the bridge
        # is fleet-level, between replicas); only the main thread emits
        # (the _place_entry call sites), honoring the probe discipline
        self._spans = SpanEmitter(self._tele, clock=clock)

    # -- fleet lifecycle ------------------------------------------------
    def add(self, factory: Optional[Callable[[str], object]] = None) -> str:
        """Build and enroll a fresh replica (under live load): slot ids
        are monotonic — a replacement never reuses a dead replica's
        engine-rid partition, so migrated pinned rids stay unique."""
        slot = self._next_slot
        self._next_slot += 1
        replica_id = f"r{slot}"
        serving = (factory or self._factory)(replica_id)
        serving.set_rid_base(slot * RID_STRIDE)
        rep = Replica(replica_id, serving, slot)
        with self._lock:
            self._replicas[replica_id] = rep
        self._event({"event": "replica_added", "replica": replica_id,
                     "replicas": self._placeable_count()})
        self._update_gauges()
        return replica_id

    def drain(self, replica_id: str):
        """Take a replica out of rotation gracefully: admission closes
        (new work spills to peers), in-flight streams finish intact, and
        the replica retires to ``drained`` once dry — zero requests
        lost. The rolling-restart building block."""
        rep = self._replica(replica_id)
        if rep.state in (DEAD, DRAINED):
            return
        rep.serving.drain()
        with self._lock:
            rep.state = DRAINING
        self._event({"event": "drain", "replica": replica_id})
        self._update_gauges()

    def kill(self, replica_id: str, detail: str = "killed"):
        """Chaos primitive: abrupt replica death. Recovery runs from the
        replica's ``RecoveryLog`` snapshot alone — exactly the state a
        real process loss would leave behind."""
        rep = self._replica(replica_id)
        if rep.state in (DEAD, DRAINED):
            return
        self._event({"event": "kill", "replica": replica_id,
                     "tick": self._tick})
        self._evict(rep, detail)

    def rolling_restart(self):
        """Restart the whole fleet with zero lost requests: one replica
        at a time — add the replacement first (capacity never dips), then
        drain the old one; the next pair starts when the drain retires.
        Driven forward by ``step()``; idempotent while one is running."""
        if self._rolling is not None:
            return
        pending = [r.replica_id for r in self._replicas.values()
                   if r.state in STEPPABLE]
        self._rolling = {"pending": pending, "draining": None}
        self._event({"event": "rolling_restart",
                     "replicas": len(pending)})

    def at_tick(self, tick: int, fn: Callable[["FleetRouter"], None]):
        """Register a chaos hook to run at the START of router tick
        ``tick`` (1-based, like the engine fault plans) — the replayable
        scheduling surface behind ``ds_loadgen --kill-replica`` /
        ``--rolling-restart``."""
        self._hooks.setdefault(int(tick), []).append(fn)

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def on_step(self, fn: Callable[["FleetRouter"], None]):
        """Register a recurring hook run at the END of every ``step()``
        (after replicas stepped, before gauges) — the autoscaler's
        attachment point: its policy reads/acts on the main thread, so
        its ``fleet_scale`` events hit the trace writer safely."""
        self._on_step_hooks.append(fn)

    @property
    def telemetry(self):
        """The fleet's base telemetry hub (events + metrics registry)."""
        return self._tele

    def scale_in_candidate(self) -> Optional[str]:
        """The replica an autoscaler may safely drain, or None.

        Residue-aware: never the last placeable replica, never a
        non-healthy one, and — the scale-in correctness rule — never a
        replica that holds the ONLY copy of a recovering request's
        RecoveryLog residue (breaker open or engine health not ``ok``
        while outstanding ``residue_tokens`` remain: draining it would
        strand mid-stream state no survivor has). Among the eligible,
        prefer the emptiest (least residue, then least committed KV)."""
        with self._lock:
            if self._placeable_count() <= 1:
                return None
            reps = [r for r in self._replicas.values()
                    if r.state == HEALTHY]
        eligible = []
        for rep in reps:
            st = rep.serving.statusz()
            residue = int(st.get("residue_tokens", 0))
            if residue > 0 and (st.get("breaker_open")
                                or rep.serving.health() != "ok"):
                continue  # sole copy of recovering residue: not drainable
            eligible.append((residue, rep.serving.committed_tokens(),
                             rep.slot, rep.replica_id))
        if not eligible:
            return None
        return min(eligible)[3]

    def rebalance_queued(self, max_moves: Optional[int] = None) -> int:
        """Spread host-side QUEUED (never-started) requests across the
        fleet: pop entries off the deepest healthy queue and re-admit
        them on a lighter replica until depths are within one of each
        other (or ``max_moves``). Returns the number moved.

        Why this exists: placement happens at submit time, so a burst
        that lands on a small fleet stays trapped on the old replicas'
        queues — ``add()``-ing a replica only helps FUTURE arrivals. The
        autoscaler calls this right after scale-out so new capacity
        rescues the very burst that triggered it. Only queued entries
        move (``engine_rid`` None — no KV state, no stream to resume);
        running streams stay pinned where their cache lives. A request
        is released from its source only AFTER a survivor admitted it,
        so a failed placement leaves it exactly where it was."""
        moved = 0
        while max_moves is None or moved < max_moves:
            with self._lock:
                reps = [r for r in self._replicas.values()
                        if r.state == HEALTHY]
            if len(reps) < 2:
                break
            depths = sorted((int(r.serving.statusz()["queue_depth"]),
                             r.slot, r) for r in reps)
            (lo, _, dst), (hi, _, src) = depths[0], depths[-1]
            if hi - lo <= 1:
                break  # balanced: moving more would just shuffle work
            queued = [e for e
                      in src.serving.recovery_snapshot(include_queued=True)
                      if e.get("engine_rid") is None]
            if not queued:
                break  # statusz raced a drain; nothing concrete to move
            entry = queued[-1]  # tail = least-urgent under the policy
            lrid = entry["rid"]
            frid = src.local_to_fleet.get(lrid)
            old = src.serving.request(lrid)
            if frid is None or old is None:
                break
            # target ONLY the shallowest queue: each move strictly
            # shrinks the imbalance, so the loop terminates
            if not self._place_entry(entry, src, frid, old.on_token,
                                     event="rebalanced", targets=[dst]):
                break  # the lightest replica won't admit it; keep at src
            src.serving.release(lrid)
            moved += 1
        if moved:
            self._event({"event": "rebalance", "migrated": moved})
            self._flush_events()
        return moved

    # -- routing --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               priority: int = 0, tenant: str = "default",
               deadline_ms: Optional[float] = None,
               on_token=None, prefix_id: Optional[int] = None) -> Admission:
        """Fleet admission: one honest verdict from the best replica.
        Candidates (healthy, not backed off) are ranked by committed KV
        tokens; ``admission_outlook`` picks the first that would ADMIT,
        falling back to the first that would queue, falling back to the
        least-loaded one's real shed verdict (whose ``retry_after_s``
        hint also backs that replica off). The returned rid is
        fleet-scoped.

        ``prefix_id`` requires the factory to register prefixes
        SYMMETRICALLY on every replica (same registration order -> same
        serving-level id everywhere): placement may pick any replica, and
        a migrated request's survivor resolves the same id — a replica
        missing it falls back to the full-prompt prefill rather than
        stranding the stream."""
        self._submitted += 1
        self._counter("fleet_submitted_total")
        if deadline_ms is None:   # degradation ladder: no-SLO traffic
            if self.shed_backfill:
                self._shed += 1
                self._counter("fleet_shed_total")
                self._event({"event": "shed",
                             "reason": "degraded_backfill"})
                return Admission(status=SHED, reason="degraded_backfill")
            if self.cap_new_tokens_no_slo is not None:
                max_new_tokens = min(int(max_new_tokens),
                                     int(self.cap_new_tokens_no_slo))
        need = int(np.asarray(prompt_ids, np.int32).reshape(-1).size) \
            + int(max_new_tokens)
        now = self._clock()
        cands = self._candidates(now)
        if not cands:
            return self._fleet_shed(need, now)
        chosen, verdicts = None, []
        for rep in cands:
            status, reason = rep.serving.admission_outlook(need)
            verdicts.append((rep, status))
            if status == ADMITTED:
                chosen = rep
                break
        if chosen is None:
            chosen = next((rep for rep, status in verdicts
                           if status not in (SHED,)), None)
        if chosen is None:
            chosen = cands[0]   # all would shed: least-loaded sheds honestly
        adm = chosen.serving.submit(
            prompt_ids, max_new_tokens, priority=priority, tenant=tenant,
            deadline_ms=deadline_ms, on_token=on_token,
            prefix_id=prefix_id)
        if not adm:
            chosen.shed += 1
            self._shed += 1
            self._counter("fleet_shed_total")
            if adm.retry_after_s is not None:
                with self._lock:
                    chosen.backoff_until = now + adm.retry_after_s
                self._event({
                    "event": "backoff", "replica": chosen.replica_id,
                    "retry_after_s": adm.retry_after_s})
            return adm
        frid = self._next_frid
        self._next_frid += 1
        with self._lock:
            self._routes[frid] = (chosen.replica_id, adm.rid)
        chosen.local_to_fleet[adm.rid] = frid
        chosen.admitted += 1
        self._admitted += 1
        self._counter("fleet_admitted_total")
        if chosen is not cands[0]:
            # the least-loaded replica would not take it; the fleet
            # verdict came from a peer — the spillover ISSUE's routing
            # contract promises
            self._spillovers += 1
            self._counter("fleet_spillover_total")
            self._event({
                "event": "spillover", "request": frid,
                "from_replica": cands[0].replica_id,
                "replica": chosen.replica_id})
        self._event({
            "event": "route", "request": frid,
            "replica": chosen.replica_id, "verdict": adm.status,
            "attempts": 1 + cands.index(chosen)})
        return Admission(status=adm.status, rid=frid, reason=adm.reason,
                         retry_after_s=adm.retry_after_s)

    def _candidates(self, now: float) -> List[Replica]:
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state in PLACEABLE and now >= r.backoff_until]
        reps.sort(key=lambda r: (r.serving.committed_tokens(), r.slot))
        return reps

    def _fleet_shed(self, need: int, now: float) -> Admission:
        """No replica can even be asked: the fleet-level verdict. The
        hint is the soonest any backed-off replica re-opens."""
        with self._lock:
            waits = [r.backoff_until - now for r in self._replicas.values()
                     if r.state in PLACEABLE and r.backoff_until > now]
        hint = round(min(waits), 3) if waits else None
        self._shed += 1
        self._counter("fleet_shed_total")
        payload = {"event": "shed", "reason": "no_replicas",
                   "need_tokens": need}
        if hint is not None:
            payload["retry_after_s"] = hint
        self._event(payload)
        return Admission(status=SHED, reason="no_replicas",
                         retry_after_s=hint)

    # -- the fleet tick -------------------------------------------------
    def step(self) -> Dict[int, List[int]]:
        """One fleet tick: chaos hooks, the health ladder, evictions, one
        ``step()`` per steppable replica (a raising replica is evicted —
        its streams migrate to survivors), drain retirement, and the
        rolling-restart machine. Returns {fleet rid: [tokens]} emitted
        this tick."""
        self._tick += 1
        for fn in self._hooks.pop(self._tick, []):
            fn(self)
        self.probe()
        for rep in list(self._replicas.values()):
            if rep.state == FAILED:
                self._evict(rep, "health: poisoned")
        out: Dict[int, List[int]] = {}
        for rep in list(self._replicas.values()):
            if rep.state not in STEPPABLE:
                continue
            if rep.serving.has_work():
                try:
                    emitted = rep.serving.step()
                except Exception as e:  # noqa: BLE001 — any terminal step
                    # failure ejects the replica; the fleet keeps serving
                    self._evict(rep, f"{type(e).__name__}: {e}")
                    continue
                for lrid, toks in emitted.items():
                    frid = rep.local_to_fleet.get(lrid)
                    if frid is not None:
                        out[frid] = toks
            if rep.state == DRAINING and not rep.serving.has_work():
                self._retire(rep)
        self._advance_rolling()
        for fn in list(self._on_step_hooks):
            fn(self)
        self._flush_events()
        self._update_gauges()
        return out

    def has_work(self) -> bool:
        return any(rep.serving.has_work()
                   for rep in self._replicas.values()
                   if rep.state in STEPPABLE)

    def run(self, max_ticks: Optional[int] = None) -> int:
        ticks = 0
        while self.has_work():
            if max_ticks is not None and ticks >= max_ticks:
                break
            self.step()
            ticks += 1
        return ticks

    # -- ejection + migration -------------------------------------------
    def _evict(self, rep: Replica, detail: str):
        """Replica death: re-admit its live requests onto survivors from
        the recovery snapshot (running streams resume bitwise under
        their pinned engine rids; queued ones re-enter fresh), shed the
        rest honestly, and stash its terminal records for ``reap``."""
        with self._lock:
            rep.state = DEAD
        self._deaths += 1
        self._counter("fleet_replica_deaths_total")
        migrated = 0
        for entry in rep.serving.recovery_snapshot(include_queued=True):
            lrid = entry["rid"]
            frid = rep.local_to_fleet.get(lrid)
            old = rep.serving.request(lrid)
            if frid is None or old is None:
                continue
            placed = self._place_entry(entry, rep, frid, old.on_token)
            if placed:
                rep.serving.release(lrid)
                rep.migrated_out += 1
                migrated += 1
        # whatever no survivor could hold is shed honestly on the dead
        # replica's books (serving_event reason engine_lost, tagged with
        # its replica id) and surfaces through reap below
        lost = rep.serving.abandon(f"replica {rep.replica_id} lost: "
                                   f"{detail[:120]}")
        self._lost += len(lost)
        if lost:
            self._counter("fleet_lost_total", len(lost))
        self._stash_reaped(rep)
        self._event({
            "event": "replica_dead", "replica": rep.replica_id,
            "detail": detail[:200], "migrated": migrated,
            "lost": len(lost)})
        self._flush_events()
        self._update_gauges()

    def _place_entry(self, entry: dict, dead: Replica, frid: int,
                     on_token, event: str = "migrated",
                     targets: Optional[List[Replica]] = None) -> bool:
        """Try every survivor (least-loaded first, or the explicit
        ``targets`` list in order) for one recovery entry. True when one
        admitted/queued it — the route now points there and the stream
        continues. ``event`` discriminates death migration
        (``migrated``, counted as such) from queue rebalancing
        (``rebalanced``, counted separately: nothing died)."""
        now = self._clock()
        cands = targets if targets is not None else self._candidates(now)
        # migration-bridge span id, minted BEFORE the readmit so the
        # survivor's admission span can parent on it — but EMITTED only
        # after a successful placement (a failed sweep writes nothing, so
        # the trace never holds a dangling bridge)
        mig_span = (self._spans.new_span_id()
                    if entry.get("trace_id") is not None
                    and self._spans.enabled else None)
        for surv in cands:
            if surv is dead:
                continue
            try:
                adm = surv.serving.readmit(entry, on_token=on_token,
                                           parent_span=mig_span)
            except ValueError:
                continue  # cannot ever fit here (budget/rid collision)
            if not adm:
                continue  # honest local shed: try the next survivor
            with self._lock:
                self._routes[frid] = (surv.replica_id, adm.rid)
            surv.local_to_fleet[adm.rid] = frid
            if event == "migrated":
                surv.migrated_in += 1
                self._migrated += 1
                self._counter("fleet_migrated_total")
            else:
                self._counter("fleet_rebalanced_total")
            if mig_span is not None:
                # the cross-replica stitch: parented on the request's
                # root (emitted on its birth replica), tagged with both
                # endpoints — one trace_id spans engine generations
                self._spans.emit(
                    "migration", entry["trace_id"], now, self._clock(),
                    span_id=mig_span, parent_id=entry.get("span_root"),
                    attrs={"event": event,
                           "from_replica": dead.replica_id,
                           "to_replica": surv.replica_id,
                           "gen_base": len(entry.get("emitted", []))})
            self._event({
                "event": event, "request": frid,
                "from_replica": dead.replica_id,
                "to_replica": surv.replica_id,
                "tokens_emitted": len(entry.get("emitted", [])),
                "gen_base": len(entry.get("emitted", [])),
                "verdict": adm.status})
            return True
        return False

    def _retire(self, rep: Replica):
        """A draining replica ran dry: retire it (state ``drained``) and
        stash its terminal records — nothing was lost."""
        with self._lock:
            rep.state = DRAINED
        self._stash_reaped(rep)
        self._event({"event": "replica_drained",
                     "replica": rep.replica_id})
        self._update_gauges()

    def _stash_reaped(self, rep: Replica):
        """Translate a retiring replica's terminal records into the fleet
        namespace so a later ``reap()`` still surfaces them."""
        for lrid, req in rep.serving.reap().items():
            frid = rep.local_to_fleet.pop(lrid, None)
            if frid is None:
                continue
            with self._lock:
                self._routes.pop(frid, None)
                self._dead_reaped[frid] = req

    def _advance_rolling(self):
        roll = self._rolling
        if roll is None:
            return
        if roll["draining"] is not None:
            rep = self._replicas.get(roll["draining"])
            if rep is not None and rep.state not in (DRAINED, DEAD):
                return  # still finishing in-flight work
            roll["draining"] = None
        if not roll["pending"]:
            self._rolling = None
            self._event({"event": "rolling_restart_done",
                         "replicas": self._placeable_count()})
            return
        old = roll["pending"].pop(0)
        rep = self._replicas.get(old)
        if rep is None or rep.state not in STEPPABLE:
            return  # died on its own mid-restart; next step advances
        self.add()          # replacement first: capacity never dips
        self.drain(old)
        roll["draining"] = old

    # -- request surface (fleet rid namespace) --------------------------
    def request(self, frid: int) -> Optional[ServeRequest]:
        """The request's CURRENT record — wherever migration put it."""
        with self._lock:
            route = self._routes.get(frid)
            if route is None:
                return self._dead_reaped.get(frid)
        rep = self._replicas.get(route[0])
        return rep.serving.request(route[1]) if rep is not None else None

    def status(self, frid: int) -> str:
        req = self.request(frid)
        return req.state if req is not None else "unknown"

    def stream(self, frid: int) -> FleetStream:
        if self.request(frid) is None:
            raise KeyError(f"unknown fleet request {frid}: shed or "
                           f"already reaped")
        return FleetStream(self, frid)

    def result(self, frid: int):
        """Pop a FINISHED request's full token array (prompt + generated),
        wherever it finished. KeyError (naming the state) otherwise."""
        with self._lock:
            req = self._dead_reaped.get(frid)
            if req is not None:
                if req.state != FINISHED:
                    raise KeyError(f"no result for fleet request {frid}: "
                                   f"{req.state}")
                self._dead_reaped.pop(frid)
                return req.result
            route = self._routes.get(frid)
        if route is None:
            raise KeyError(f"no result for fleet request {frid}: unknown — "
                           f"never admitted, shed, or already reaped")
        rep_id, lrid = route
        out = self._replicas[rep_id].serving.result(lrid)
        with self._lock:
            self._routes.pop(frid, None)
        self._replicas[rep_id].local_to_fleet.pop(lrid, None)
        return out

    def cancel(self, frid: int) -> bool:
        with self._lock:
            route = self._routes.get(frid)
        if route is None:
            return False
        rep = self._replicas.get(route[0])
        return rep.serving.cancel(route[1]) if rep is not None else False

    def reap(self) -> Dict[int, ServeRequest]:
        """Every terminal record across the fleet (and from dead/drained
        replicas), keyed by fleet rid."""
        with self._lock:
            out = dict(self._dead_reaped)
            self._dead_reaped.clear()
        for rep in list(self._replicas.values()):
            for lrid, req in rep.serving.reap().items():
                frid = rep.local_to_fleet.pop(lrid, None)
                if frid is None:
                    continue
                with self._lock:
                    self._routes.pop(frid, None)
                out[frid] = req
        return out

    # -- health plane ---------------------------------------------------
    def probe(self):
        """Walk every replica's ``health()`` ladder and update placement
        states. Runs inline each ``step()`` and (optionally) on the
        daemon probe thread — so the WHOLE body holds the router lock,
        and state-change trace events are only ENQUEUED here; ``step()``
        emits them from the main thread (the trace writer is not
        thread-safe)."""
        with self._lock:
            now = self._clock()
            for rep in self._replicas.values():
                if rep.state in (DEAD, DRAINED, FAILED):
                    continue
                health = rep.serving.health()
                if health == "ok" and rep.state == RECOVERING:
                    rep.state = HEALTHY
                    rep.backoff_until = now
                    self._pending_events.append({
                        "event": "replica_recovered",
                        "replica": rep.replica_id, "health": health})
                elif health == "recovering" and rep.state == HEALTHY:
                    rep.state = RECOVERING
                    self._pending_events.append({
                        "event": "replica_recovering",
                        "replica": rep.replica_id, "health": health})
                elif health == "poisoned":
                    rep.state = FAILED
                    self._pending_events.append({
                        "event": "replica_failed",
                        "replica": rep.replica_id, "health": health})
                elif health == "draining" and rep.state in (HEALTHY,
                                                            RECOVERING):
                    # drained out-of-band (operator called engine.drain):
                    # honor it — finish, then retire
                    rep.state = DRAINING
                    self._pending_events.append({
                        "event": "drain", "replica": rep.replica_id})

    def start_probe(self, interval_s: float = 0.25) -> threading.Thread:
        """Background health probe for deployments that do not call
        ``step()`` continuously. Idempotent."""
        if self._probe_thread is not None:
            return self._probe_thread
        self._probe_thread = threading.Thread(
            target=self._probe_loop, args=(float(interval_s),),
            name="fleet-probe", daemon=True)
        self._probe_thread.start()
        return self._probe_thread

    def _probe_loop(self, interval_s: float):
        while not self._probe_stop.wait(interval_s):
            self.probe()

    def stop_probe(self):
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    def health(self) -> str:
        """Fleet health for ``/healthz``: ``"ok"`` while ANY replica is
        in rotation; ``"draining"`` when the rest are only finishing
        work; ``"recovering"`` when replicas may come back; ``"dead"``
        when nothing is left."""
        with self._lock:
            states = [r.state for r in self._replicas.values()]
        if any(s == HEALTHY for s in states):
            return "ok"
        if any(s in (RECOVERING, FAILED) for s in states):
            return "recovering"
        if any(s == DRAINING for s in states):
            return "draining"
        return "dead"

    def statusz(self) -> dict:
        """Fleet ``/statusz``: per-replica placement state + engine
        snapshot, the route count, and the fleet counters."""
        with self._lock:
            reps = list(self._replicas.values())
            routes = len(self._routes)
            pending = len(self._dead_reaped)
            counters = {
                "tick": self._tick,
                "submitted": self._submitted,
                "admitted": self._admitted,
                "shed": self._shed,
                "spillovers": self._spillovers,
                "migrated": self._migrated,
                "lost": self._lost,
                "replica_deaths": self._deaths,
                "rolling_restart": self._rolling is not None,
            }
        replicas = {}
        for rep in reps:
            info = {"state": rep.state, "slot": rep.slot,
                    "admitted": rep.admitted, "shed": rep.shed,
                    "migrated_in": rep.migrated_in,
                    "migrated_out": rep.migrated_out}
            if rep.state in STEPPABLE:
                info["statusz"] = rep.serving.statusz()
            replicas[rep.replica_id] = info
        out = {
            "health": self.health(),
            "replicas": replicas,
            "placeable": self._placeable_count(),
            "routes": routes,
            "unreaped_terminal": pending,
        }
        out.update(counters)
        return out

    def start_ops_server(self, port: int = 0, host: str = "127.0.0.1"):
        """Fleet-level ``/metrics``, ``/healthz``, ``/statusz`` — the one
        scrape endpoint over the shared registry (per-replica series are
        separable by their ``replica`` label)."""
        if self._ops_server is not None:
            return self._ops_server
        from deepspeed_tpu.telemetry.ops_server import OpsServer

        self._ops_server = OpsServer(
            registry=self._tele.registry, health=self.health,
            status=self.statusz, host=host, port=port).start()
        return self._ops_server

    # -- aggregate views (ds_loadgen drives these) ----------------------
    def steppable_engines(self) -> List[Tuple[str, object]]:
        """``(replica_id, serving_engine)`` for every in-rotation replica
        — the autoscaler's actuation surface (per-engine ``kv_budget``
        tightening on the degradation ladder)."""
        with self._lock:
            return [(r.replica_id, r.serving)
                    for r in self._replicas.values()
                    if r.state in STEPPABLE]

    @property
    def vocab_size(self) -> int:
        return next(iter(self._replicas.values())).serving.vocab_size

    def committed_tokens(self) -> int:
        return sum(rep.serving.committed_tokens()
                   for rep in self._replicas.values()
                   if rep.state in STEPPABLE)

    def tick_stats(self) -> dict:
        """Summed tick accounting across live replicas, with the derived
        ratios recomputed fleet-wide."""
        out: Dict[str, float] = {}
        for rep in self._replicas.values():
            if rep.state not in STEPPABLE:
                continue
            for k, v in rep.serving.tick_stats().items():
                if k in _DERIVED_TICK_FIELDS or not isinstance(
                        v, (int, float)) or isinstance(v, bool):
                    continue
                out[k] = out.get(k, 0) + v
        ticks = out.get("ticks", 0)
        tokens = out.get("tokens", 0)
        cap = out.get("capacity_tokens", 0)
        host = out.get("dispatch_ms", 0.0) + out.get("block_ms", 0.0)
        out["mean_emitted_per_tick"] = (round(tokens / ticks, 3)
                                        if ticks else 0.0)
        out["block_ms_per_token"] = (round(out.get("block_ms", 0.0) / tokens,
                                           4) if tokens else None)
        out["overlap_frac"] = (round(1.0 - out.get("block_ms", 0.0) / host, 4)
                               if host > 0 else None)
        out["utilization"] = round(tokens / cap, 4) if cap else 0.0
        return out

    def recovery_stats(self) -> dict:
        """Summed engine recovery accounting plus the fleet's own:
        migrations, losses, deaths, spillovers."""
        out: Dict[str, float] = {}
        for rep in self._replicas.values():
            for k, v in rep.serving.recovery_stats().items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                out[k] = round(out.get(k, 0) + v, 3)
        out["fleet_migrated"] = self._migrated
        out["fleet_lost"] = self._lost
        out["fleet_replica_deaths"] = self._deaths
        out["fleet_spillovers"] = self._spillovers
        return out

    def close(self):
        """Shut the fleet down: probe thread, ops server, every replica
        (their telemetry facades are no-op closers), then the ONE base
        hub — flushed exactly once."""
        if self._closed:
            return
        self._closed = True
        self.stop_probe()
        if self._ops_server is not None:
            self._ops_server.close()
            self._ops_server = None
        self._flush_events()
        for rep in self._replicas.values():
            try:
                rep.serving.close()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                pass
        try:
            self._tele.close()
        except Exception:  # noqa: BLE001 — shutdown must not raise
            pass

    # -- internals ------------------------------------------------------
    def _replica(self, replica_id: str) -> Replica:
        rep = self._replicas.get(replica_id)
        if rep is None:
            raise KeyError(f"unknown replica {replica_id!r} "
                           f"(have {sorted(self._replicas)})")
        return rep

    def _placeable_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.state in PLACEABLE)

    def _event(self, payload: dict):
        if self._tele is not None and self._tele.enabled:
            self._tele.emit("router_event", payload)

    def _flush_events(self):
        """Emit probe-thread-enqueued state changes from the main thread
        (the trace writer is not thread-safe)."""
        with self._lock:
            pending, self._pending_events = self._pending_events, []
        for payload in pending:
            self._event(payload)

    def _counter(self, name: str, n: float = 1.0):
        if self._tele is not None and self._tele.enabled:
            self._tele.registry.counter(name).inc(n)

    def _update_gauges(self):
        if self._tele is None or not self._tele.enabled:
            return
        reg = self._tele.registry
        reg.gauge("fleet_replicas").set(self._placeable_count())
        reg.gauge("fleet_queue_depth").set(
            sum(rep.serving.queue_depth() for rep in self._replicas.values()
                if rep.state in STEPPABLE))
        reg.gauge("fleet_committed_tokens").set(self.committed_tokens())
