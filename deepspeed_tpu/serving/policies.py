"""Pluggable scheduler policies for :class:`ServingEngine`.

A policy orders the bounded admission queue each tick; the engine then
walks that order placing requests into free slots, with ONE shared
anti-starvation rule layered on top (the aging barrier, see
``ServingEngine._schedule``): a request whose queue wait exceeds
``aging_s`` may no longer be leapfrogged by later-ranked requests — the
fix for the bare FIFO-with-skip starvation mode where a long request
waiting for the big pool watches an endless stream of short ones jump
past it.

Policies are deliberately jax-free and deterministic: ordering depends
only on request fields and the injected clock, so admission-order tests
are exact.

    fifo      submission order (the pre-serving behavior, minus
              unbounded skip)
    priority  higher ``priority`` first; waiting boosts effective
              priority by 1 level per ``aging_s`` so low-priority work
              cannot starve under a steady high-priority stream
    edf       earliest absolute deadline (submit + deadline_ms) first;
              no-SLO requests sort last in submission order
    fair      per-tenant fair share: the tenant with the least committed
              service (admitted prompt+output tokens) goes first, so one
              chatty tenant cannot monopolize the slots
"""

from typing import Dict, List

from deepspeed_tpu.serving.request import ServeRequest


class SchedulerPolicy:
    """Base: FIFO. Subclasses override ``key`` (sort key over the queue,
    lower = admitted first) and, when stateful, the lifecycle hooks."""

    name = "fifo"

    def key(self, req: ServeRequest, now: float):
        return (req.rid,)

    def order(self, queue: List[ServeRequest], now: float) -> List[ServeRequest]:
        # sorted() is stable: ties always resolve in submission order
        return sorted(queue, key=lambda r: self.key(r, now))

    # lifecycle hooks (stateful policies only)
    def on_admit(self, req: ServeRequest, now: float):
        pass

    def on_finish(self, req: ServeRequest, now: float):
        pass


class FifoPolicy(SchedulerPolicy):
    name = "fifo"


class PriorityPolicy(SchedulerPolicy):
    """Strict priority with aging: effective priority = ``priority`` +
    one level per ``aging_s`` seconds waited, so a parked low-priority
    request eventually outranks freshly submitted high-priority ones."""

    name = "priority"

    def __init__(self, aging_s: float = 30.0):
        if aging_s <= 0:
            raise ValueError("aging_s must be > 0")
        self.aging_s = aging_s

    def key(self, req: ServeRequest, now: float):
        effective = req.priority + req.waited_s(now) / self.aging_s
        return (-effective, req.rid)


class EdfPolicy(SchedulerPolicy):
    """Earliest-deadline-first against per-request SLOs. Requests without
    a deadline sort last (deadline_at = +inf), in submission order."""

    name = "edf"

    def key(self, req: ServeRequest, now: float):
        return (req.deadline_at, req.rid)


class FairSharePolicy(SchedulerPolicy):
    """Per-tenant fair share by committed service: tenants are charged
    ``need_tokens`` (prompt + max_new) at admission — deterministic, known
    before decoding — and the least-served tenant's oldest request goes
    first. New tenants start at the CURRENT minimum, not zero, so a
    late-arriving tenant gets its fair turn without replaying history."""

    name = "fair"

    def __init__(self):
        self._served: Dict[str, float] = {}

    def _account(self, tenant: str) -> float:
        """The tenant's service counter, opened at the CURRENT minimum on
        first sight (recomputing the baseline per lookup would hand every
        incumbent's total to the newcomer and break the interleave)."""
        if tenant not in self._served:
            self._served[tenant] = (min(self._served.values())
                                    if self._served else 0.0)
        return self._served[tenant]

    def key(self, req: ServeRequest, now: float):
        return (self._account(req.tenant), req.rid)

    def on_admit(self, req: ServeRequest, now: float):
        self._served[req.tenant] = self._account(req.tenant) + req.need_tokens


def resolve_policy(spec, aging_s: float = None) -> SchedulerPolicy:
    """A policy instance from its name ("fifo" | "priority" | "edf" |
    "fair") or an already-constructed :class:`SchedulerPolicy` (instances
    pass through untouched — construct one to pin knobs explicitly).
    ``aging_s`` flows into aging-aware policies built by name, so
    ``ServingEngine(policy="priority", aging_s=...)`` configures the
    boost rate it documents rather than the policy default."""
    if isinstance(spec, SchedulerPolicy):
        return spec
    builders = {"fifo": FifoPolicy, "priority": PriorityPolicy,
                "edf": EdfPolicy, "fair": FairSharePolicy}
    try:
        builder = builders[spec]
    except KeyError:
        raise ValueError(
            f"unknown scheduler policy {spec!r} (choose from "
            f"{sorted(builders)} or pass a SchedulerPolicy instance)"
        ) from None
    if builder is PriorityPolicy and aging_s is not None:
        return PriorityPolicy(aging_s=aging_s)
    return builder()
