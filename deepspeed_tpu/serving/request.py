"""Serving-layer request records: lifecycle states, admission verdicts,
and the per-request bookkeeping (:class:`ServeRequest`) the scheduler
policies order and the telemetry hook reads.

Deliberately light on dependencies (numpy only, no jax): the scheduler
policies and their tier-1 tests operate on these records without paying a
jax import.
"""

import math
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

# -- request lifecycle states ------------------------------------------
# QUEUED -> RUNNING -> FINISHED is the happy path; QUEUED requests may
# instead terminate CANCELLED (caller) or EXPIRED (deadline blew while
# waiting); RUNNING ones may terminate CANCELLED (slot freed mid-flight)
# or SHED (the engine was lost and recovery could not re-admit — the
# fault-tolerance path's honest terminal state: nothing is silently
# dropped, admitted == finished + shed + expired + cancelled).
QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
CANCELLED = "cancelled"
EXPIRED = "expired"

# -- admission verdicts (ServingEngine.submit) -------------------------
# ADMITTED: handed to the batching engine immediately (a fitting slot was
#   free and nothing queued outranked it) — the next tick prefills it.
# QUEUED_STATUS: accepted into the bounded queue; the scheduler policy
#   decides its turn.
# SHED: rejected under backpressure (queue full / KV budget / recovering)
#   — nothing was enqueued, no request id exists, retry after the hint.
#   Doubles as the terminal STATE of an admitted request the fault-
#   tolerance layer could not carry through an engine loss.
ADMITTED = "admitted"
QUEUED_STATUS = "queued"
SHED = "shed"

TERMINAL_STATES = (FINISHED, CANCELLED, EXPIRED, SHED)


@dataclass
class Admission:
    """What ``ServingEngine.submit`` returns instead of growing an
    unbounded list: an explicit verdict plus backpressure context."""

    status: str                          # ADMITTED | QUEUED_STATUS | SHED
    rid: Optional[int] = None            # None iff shed
    reason: str = ""                     # shed cause ("queue_full", "kv_budget")
    retry_after_s: Optional[float] = None  # shed only: load-based ETA, None if unknown

    def __bool__(self) -> bool:          # truthy == the request is in the system
        return self.status != SHED


@dataclass
class ServeRequest:
    """One request's serving-side record. Times are clock() seconds (the
    engine's injectable clock); ``None`` until the transition happens."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    priority: int = 0                    # higher = more urgent
    tenant: str = "default"
    deadline_ms: Optional[float] = None  # SLO: relative to submit time
    on_token: Optional[Callable[[int, int], None]] = None  # (rid, token)

    state: str = QUEUED
    submit_t: float = 0.0
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    # the ONE SLO verdict every reporting surface shares (trace event,
    # serve_deadline_* counters, loadgen records): set by whichever
    # observer judges first, never recomputed from a later clock read
    deadline_met: Optional[bool] = None
    tokens: List[int] = field(default_factory=list)
    result: Optional[np.ndarray] = None  # prompt + generated, set at FINISHED
    engine_rid: Optional[int] = None     # ContinuousBatchingEngine rid once RUNNING
    # serving-level prefix id when admission splices a registered prefix
    # (ServingEngine.register_prefix); the RecoveryLog records it so a
    # rebuilt engine re-registers before re-admitting
    prefix_id: Optional[int] = None
    # times this request was re-admitted onto a rebuilt engine (fault
    # tolerance; 0 = never touched by a recovery)
    recoveries: int = 0
    # request-scoped tracing (telemetry/spans.py): trace_id is the span
    # layer's request identity (None = sampled out, no spans emitted);
    # span_root is the root queue span's id and span_parent the span the
    # NEXT tick-window spans hang off (the latest admission /
    # recovery_replay span). The recovery snapshot carries all three, so
    # a migrated request's survivor-side spans stitch onto the same
    # trace_id across replicas.
    trace_id: Optional[str] = None
    span_root: Optional[str] = None
    span_parent: Optional[str] = None

    @property
    def need_tokens(self) -> int:
        """KV-budget footprint: the slot extent this request commits to."""
        return int(self.prompt.size) + self.max_new_tokens

    @property
    def deadline_at(self) -> float:
        """Absolute deadline in clock() seconds (+inf when no SLO): the
        EDF sort key and the queued-work expiry threshold."""
        if self.deadline_ms is None:
            return math.inf
        return self.submit_t + self.deadline_ms / 1000.0

    def waited_s(self, now: float) -> float:
        return max(0.0, now - self.submit_t)

    def queue_ms(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return (self.admit_t - self.submit_t) * 1000.0

    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submit_t) * 1000.0
