"""SLO-aware serving front-end over :class:`ContinuousBatchingEngine`.

The batching engine (inference/continuous.py) is a fast decode loop with
an UNBOUNDED pending list drained FIFO-with-skip: fine for a script, not
a server. :class:`ServingEngine` adds the layer a server needs, without
touching the hot path:

- **Bounded admission + backpressure**: ``submit`` returns an
  :class:`Admission` verdict — ``admitted`` (handed to the engine now),
  ``queued`` (bounded queue), or ``shed`` (queue full / KV token budget
  exceeded; nothing enqueued, retry-after hint attached) — instead of
  growing a list without bound.
- **Pluggable scheduling**: FIFO, strict priority, earliest-deadline-
  first, per-tenant fair share (serving/policies.py), all subject to one
  anti-starvation aging rule: a request whose queue wait exceeds
  ``aging_s`` can no longer be leapfrogged, replacing bare FIFO-with-skip.
- **Request lifecycle**: cancellation frees the pool slot mid-flight,
  per-token streaming (callback or pull iterator), and queued work whose
  deadline has blown is shed instead of decoded uselessly.
- **Pipelined drive**: the serving loop drives the engine's
  dispatch-ahead tick pipeline (``pipeline_depth``, default one tick in
  flight — the engine overlaps device compute with this layer's
  scheduling/admission work; ``pipeline_depth=0`` restores the fully
  synchronous loop, token streams bitwise identical). ``tick_stats()``
  reports the dispatch/block/overlap accounting.
- **Telemetry**: every lifecycle transition counts
  (``serve_admitted/shed/expired/cancelled/finished_total``,
  ``serve_deadline_met/missed_total``, ``serve_queue_depth`` /
  ``serve_committed_tokens`` gauges); finished requests' per-request
  ``inference_request`` events are enriched in place (via the engine's
  ``request_event_hook``) with ``path:"serving"``, ``queue_ms``,
  ``ttft_ms``, ``priority``, ``tenant``, ``deadline_ms``/``deadline_met``
  so ``ds_trace_report --serve`` can summarize a run.

Single-threaded by design, like the engine it wraps: the caller (or
``tools/ds_loadgen.py``) drives ``step()``; everything is deterministic
given the injected ``clock``, which is what makes the scheduler-policy
tests exact.

    cb = ContinuousBatchingEngine(model, config=..., cache_buckets=...)
    srv = ServingEngine(cb, policy="edf", max_queue_depth=32)
    adm = srv.submit(prompt, max_new_tokens=64, deadline_ms=500)
    if adm:                       # admitted or queued (falsy == shed)
        for tok in srv.stream(adm.rid):
            ...                   # pulls srv.step() under the hood
"""

import time
from typing import Dict, List, Optional

from deepspeed_tpu.serving.policies import SchedulerPolicy, resolve_policy
from deepspeed_tpu.serving.request import (
    ADMITTED,
    CANCELLED,
    EXPIRED,
    FINISHED,
    QUEUED,
    QUEUED_STATUS,
    RUNNING,
    SHED,
    TERMINAL_STATES,
    Admission,
    ServeRequest,
)


class TokenStream:
    """Pull-based per-token iterator over one request's output. Each
    ``next()`` returns the next generated token, driving
    ``ServingEngine.step()`` as needed; iteration ends when the request
    reaches a terminal state (check ``request.state`` to tell a finished
    stream from a cancelled/expired one)."""

    def __init__(self, serving: "ServingEngine", request: ServeRequest):
        self._serving = serving
        self._request = request
        self._i = 0

    @property
    def request(self) -> ServeRequest:
        return self._request

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        while self._i >= len(self._request.tokens):
            if self._request.state in TERMINAL_STATES:
                raise StopIteration
            if not self._serving.has_work():
                raise StopIteration
            self._serving.step()
        tok = self._request.tokens[self._i]
        self._i += 1
        return tok


class ServingEngine:
    """Admission control + scheduling + lifecycle over a
    :class:`ContinuousBatchingEngine` (which this object then owns: it
    installs the request-event hook and expects to be the only caller of
    ``engine.submit``/``step``)."""

    def __init__(self, engine, policy="fifo", max_queue_depth: int = 64,
                 kv_budget_tokens: Optional[int] = None,
                 aging_s: float = 30.0, clock=time.monotonic,
                 pipeline_depth: Optional[int] = None):
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if aging_s <= 0:
            raise ValueError("aging_s must be > 0")
        if pipeline_depth is not None:
            if pipeline_depth < 0:
                raise ValueError("pipeline_depth must be >= 0")
            # the serving layer drives the engine's dispatch-pipelined tick
            # loop; None keeps whatever the engine was constructed with
            # (default: 1 tick in flight — docs/serving.md "Tick pipeline")
            engine.pipeline_depth = pipeline_depth
        self._cb = engine
        self.policy: SchedulerPolicy = resolve_policy(policy, aging_s=aging_s)
        self.max_queue_depth = max_queue_depth
        # KV token budget: total prompt+output tokens committed across
        # RUNNING + QUEUED requests. Default 2x the slot-pool capacity —
        # one poolful decoding plus one poolful staged behind it; more
        # than that is queue wait the client should see as backpressure.
        cap = sum(p["slots"] * p["length"] for p in engine.pool_state())
        self.kv_budget_tokens = (kv_budget_tokens if kv_budget_tokens is not None
                                 else 2 * cap)
        if self.kv_budget_tokens < 1:
            raise ValueError("kv_budget_tokens must be >= 1")
        self.aging_s = aging_s
        self._clock = clock
        self._tele = engine._eng.telemetry
        self._queue: List[ServeRequest] = []
        self._running: Dict[int, ServeRequest] = {}   # engine rid -> request
        self._requests: Dict[int, ServeRequest] = {}  # serving rid -> request
        # handed to the engine but not yet admitted by an engine tick: the
        # engine queues them in _pending, so pool_state() still reports
        # their slots free — admission math must reserve them explicitly
        self._staged: Dict[int, int] = {}             # engine rid -> need_tokens
        self._next_rid = 0
        self._t_start: Optional[float] = None  # first submit: rate clock zero
        self._tokens_done = 0                  # finished requests' tokens
        engine.request_event_hook = self._event_hook

    # -- public API -----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               priority: int = 0, tenant: str = "default",
               deadline_ms: Optional[float] = None,
               on_token=None) -> Admission:
        """Admission-controlled submit. Malformed arguments raise
        ValueError (an oversized request can NEVER run — that is an
        error, not load); a well-formed one is admitted, queued, or shed
        with explicit backpressure. Shed requests get no id and leave no
        state behind."""
        prompt = self._cb.validate_request(prompt_ids, max_new_tokens)
        need = int(prompt.size) + max_new_tokens
        if need > self.kv_budget_tokens:
            # structurally inadmissible: no amount of draining frees
            # enough budget, so a shed-with-retry-hint would loop forever
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds kv_budget_tokens {self.kv_budget_tokens}: this "
                f"request can never be admitted under the configured budget")
        now = self._clock()
        if self._t_start is None:
            self._t_start = now
        if len(self._queue) >= self.max_queue_depth:
            return self._shed("queue_full", prompt, need, now)
        committed = self.committed_tokens()
        if committed + need > self.kv_budget_tokens:
            return self._shed("kv_budget", prompt, need, now,
                              excess=committed + need - self.kv_budget_tokens)
        rid = self._next_rid
        self._next_rid += 1
        req = ServeRequest(rid=rid, prompt=prompt,
                           max_new_tokens=max_new_tokens, priority=priority,
                           tenant=tenant, deadline_ms=deadline_ms,
                           on_token=on_token, submit_t=now)
        self._requests[rid] = req
        # empty queue + a fitting free slot: hand straight to the engine —
        # the strongest statement submit can truthfully make (with a
        # non-empty queue the policy decides, so the verdict is "queued")
        if not self._queue and self._fits_now(need):
            self._handover(req, now)
            status = ADMITTED
        else:
            self._queue.append(req)
            status = QUEUED_STATUS
        self._update_gauges()
        return Admission(status=status, rid=rid)

    def step(self) -> Dict[int, List[int]]:
        """One serving tick: expire deadline-blown queued work, place
        queued requests into free slots in policy order (bounded by the
        aging barrier), then one engine tick. Returns {rid: [tokens]}
        emitted this tick, keyed by SERVING rid."""
        now = self._clock()
        self._expire(now)
        self._schedule(now)
        out: Dict[int, List[int]] = {}
        if self._cb.has_work():
            emitted = self._cb.step()
            # the engine admits every placeable pending request at the top
            # of its tick, and we only hand over what fits — so after the
            # tick the staged reservations are real slots (pool_state now
            # counts them) or already finished-and-freed
            self._staged.clear()
            tnow = self._clock()
            for erid, toks in emitted.items():
                req = self._running.get(erid)
                if req is None:
                    continue  # not ours (direct engine.submit user)
                if req.first_token_t is None and toks:
                    req.first_token_t = tnow
                req.tokens.extend(toks)
                out[req.rid] = list(toks)
                if req.on_token is not None:
                    for tok in toks:
                        req.on_token(req.rid, tok)
            for erid, result in self._cb.finished().items():
                req = self._running.pop(erid, None)
                if req is None:
                    continue
                req.state = FINISHED
                req.finish_t = tnow
                req.result = result
                if req.deadline_ms is not None and req.deadline_met is None:
                    # telemetry off: the event hook didn't judge it first
                    req.deadline_met = tnow <= req.deadline_at
                self._tokens_done += len(req.tokens)
                self.policy.on_finish(req, tnow)
                if self._tele.enabled:
                    reg = self._tele.registry
                    reg.counter("serve_finished_total").inc()
                    if req.deadline_met is not None:
                        reg.counter("serve_deadline_met_total"
                                    if req.deadline_met
                                    else "serve_deadline_missed_total").inc()
        self._update_gauges()
        return out

    def run(self, max_ticks: Optional[int] = None) -> int:
        """Step until idle (or ``max_ticks``); returns ticks taken."""
        ticks = 0
        while self.has_work():
            if max_ticks is not None and ticks >= max_ticks:
                break
            self.step()
            ticks += 1
        return ticks

    def has_work(self) -> bool:
        return bool(self._queue) or self._cb.has_work()

    def queue_depth(self) -> int:
        return len(self._queue)

    def committed_tokens(self) -> int:
        """Prompt+output tokens committed by queued + running requests —
        what admission weighs against ``kv_budget_tokens``."""
        return (sum(r.need_tokens for r in self._queue)
                + sum(r.need_tokens for r in self._running.values()))

    def tick_stats(self) -> dict:
        """Tick-utilization accounting for the serving loop: the engine's
        dispatch/block/overlap numbers (``ContinuousBatchingEngine.
        tick_stats``) plus ``utilization`` — fraction of the dispatched
        emission capacity actually emitted (tokens / capacity_tokens,
        where each ticked pool contributes slots × burst). This is the
        in-process view of what ``ds_trace_report --serve`` computes from
        ``serving_tick`` trace events, and what ``ds_loadgen``'s
        ``--pipeline-depth`` A/B compares."""
        s = self._cb.tick_stats()
        cap = s.get("capacity_tokens", 0)
        s["utilization"] = round(s["tokens"] / cap, 4) if cap else 0.0
        return s

    def status(self, rid: int) -> str:
        req = self._requests.get(rid)
        return req.state if req is not None else "unknown"

    def request(self, rid: int) -> Optional[ServeRequest]:
        """The live request record (None once reaped or never admitted)."""
        return self._requests.get(rid)

    def result(self, rid: int):
        """Pop a FINISHED request's full token array (prompt + generated).
        Raises KeyError naming the actual state otherwise — mirrors
        ``ContinuousBatchingEngine.result`` semantics."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(f"no result for request {rid}: unknown — never "
                           f"admitted, shed, or already reaped")
        if req.state != FINISHED:
            raise KeyError(f"no result for request {rid}: {req.state}")
        self._requests.pop(rid)
        return req.result

    def reap(self) -> Dict[int, ServeRequest]:
        """Remove and return every terminal-state request record —
        finished (``.result`` holds the tokens), cancelled, and expired.
        A long-running server calls this (or ``result``) to keep the
        record table bounded; the load generator uses it for reporting."""
        done = {rid: r for rid, r in self._requests.items()
                if r.state in TERMINAL_STATES}
        for rid in done:
            self._requests.pop(rid)
        return done

    def close(self):
        """Flush/close the telemetry trace (the engines share one hub);
        the load generator and servers call this at shutdown."""
        self._tele.close()

    def stream(self, rid: int) -> TokenStream:
        """Per-token pull iterator for an admitted/queued request; tokens
        already emitted are replayed first, then each ``next()`` drives
        ``step()`` until the next token or a terminal state."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(f"unknown request {rid}: shed or already reaped")
        return TokenStream(self, req)

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request. A running one frees its
        pool slot immediately — the next ``step()`` can admit into it.
        False when already terminal/unknown (nothing left to cancel)."""
        req = self._requests.get(rid)
        if req is None or req.state in TERMINAL_STATES:
            return False
        now = self._clock()
        if req.state == QUEUED:
            self._queue = [r for r in self._queue if r.rid != rid]
        else:  # RUNNING
            self._cb.cancel(req.engine_rid)
            self._running.pop(req.engine_rid, None)
            self._staged.pop(req.engine_rid, None)
        req.state = CANCELLED
        req.finish_t = now
        if self._tele.enabled:
            self._tele.registry.counter("serve_cancelled_total").inc()
            self._tele.emit("serving_event", {
                "event": "cancelled", "request": rid,
                "queue_ms": round(req.waited_s(now) * 1000.0, 3),
                "tokens_emitted": len(req.tokens),
            })
        self._update_gauges()
        return True

    # -- internals ------------------------------------------------------
    def _shed(self, reason: str, prompt, need: int, now: float,
              excess: Optional[int] = None) -> Admission:
        hint = self._retry_after(need if excess is None else excess, now)
        if self._tele.enabled:
            self._tele.registry.counter("serve_shed_total").inc()
            event = {"event": "shed", "reason": reason,
                     "prompt_tokens": int(prompt.size), "need_tokens": need,
                     "queue_depth": len(self._queue),
                     "committed_tokens": self.committed_tokens()}
            if hint is not None:
                event["retry_after_s"] = hint
            self._tele.emit("serving_event", event)
        return Admission(status=SHED, reason=reason, retry_after_s=hint)

    def _retry_after(self, excess_tokens: int, now: float) -> Optional[float]:
        """Coarse backpressure hint: how long until ``excess_tokens`` of
        committed work drains at the observed completion rate. None until
        any request has finished (no rate to extrapolate from)."""
        if self._tokens_done <= 0 or self._t_start is None:
            return None
        elapsed = now - self._t_start
        if elapsed <= 0:
            return None
        rate = self._tokens_done / elapsed
        return round(max(1, excess_tokens) / rate, 3)

    def _effective_pool_state(self) -> List[dict]:
        """pool_state() with staged handovers already subtracted, placed
        the way the engine's ``_place`` will (smallest fitting pool)."""
        pools = [dict(p) for p in self._cb.pool_state()]
        for need in self._staged.values():
            pool = next((p for p in pools
                         if p["length"] >= need and p["free"] > 0), None)
            if pool is not None:
                pool["free"] -= 1
        return pools

    def _fits_now(self, need: int) -> bool:
        return any(p["length"] >= need and p["free"] > 0
                   for p in self._effective_pool_state())

    def _handover(self, req: ServeRequest, now: float):
        req.engine_rid = self._cb.submit(req.prompt, req.max_new_tokens)
        req.state = RUNNING
        req.admit_t = now
        self._staged[req.engine_rid] = req.need_tokens
        self._running[req.engine_rid] = req
        self.policy.on_admit(req, now)
        if self._tele.enabled:
            self._tele.registry.counter("serve_admitted_total").inc()

    def _schedule(self, now: float):
        """Place queued requests into free slots in policy order, bounded
        by the anti-starvation aging rule: a request that has waited
        ``aging_s`` (a) moves to the head of the order, oldest first —
        so a request the policy keeps outranking (no-deadline work under
        EDF, low priority under a high-priority stream) still gets the
        next slot it fits — and (b) becomes a barrier when it does NOT
        fit: nothing ranked behind it may leapfrog (the fix for the bare
        FIFO-with-skip mode where a long request waiting for the big pool
        starves behind an endless stream of short ones)."""
        if not self._queue:
            return
        free = self._effective_pool_state()
        placed = set()
        order = self.policy.order(self._queue, now)
        aged = [r for r in order if r.waited_s(now) >= self.aging_s]
        if aged:
            aged.sort(key=lambda r: r.rid)  # oldest aged request first
            fresh = [r for r in order if r.waited_s(now) < self.aging_s]
            order = aged + fresh
        for req in order:
            pool = next((p for p in free
                         if p["length"] >= req.need_tokens and p["free"] > 0),
                        None)
            if pool is None:
                if req.waited_s(now) >= self.aging_s:
                    break  # aging barrier: nobody leapfrogs an aged request
                continue
            pool["free"] -= 1
            self._handover(req, now)
            placed.add(req.rid)
        if placed:
            self._queue = [r for r in self._queue if r.rid not in placed]

    def _expire(self, now: float):
        """Shed queued work whose deadline already blew: decoding it would
        burn slot time on a response the client stopped waiting for."""
        expired = [r for r in self._queue if now > r.deadline_at]
        if not expired:
            return
        for req in expired:
            req.state = EXPIRED
            req.finish_t = now
            if self._tele.enabled:
                self._tele.registry.counter("serve_expired_total").inc()
                self._tele.emit("serving_event", {
                    "event": "expired", "request": req.rid,
                    "queue_ms": round(req.waited_s(now) * 1000.0, 3),
                    "deadline_ms": req.deadline_ms,
                })
        self._queue = [r for r in self._queue if r.state == QUEUED]

    def _update_gauges(self):
        if not self._tele.enabled:
            return
        reg = self._tele.registry
        reg.gauge("serve_queue_depth").set(len(self._queue))
        reg.gauge("serve_committed_tokens").set(self.committed_tokens())

    def _event_hook(self, engine_rid: int, event: dict) -> Optional[dict]:
        """Installed as the batching engine's ``request_event_hook``:
        enrich the per-request ``inference_request`` event with the
        serving-side lifecycle fields (and retag it as ours)."""
        req = self._running.get(engine_rid)
        if req is None:
            return None  # a direct engine.submit request: leave it alone
        now = self._clock()
        event["path"] = "serving"
        event["request"] = req.rid
        q = req.queue_ms()
        if q is not None:
            event["queue_ms"] = round(q, 3)
        # finishing tick: first_token_t for a one-tick request is not
        # recorded yet, so fall back to "now" (same tick that emitted it)
        ttft = req.ttft_ms()
        event["ttft_ms"] = round(
            ttft if ttft is not None else (now - req.submit_t) * 1000.0, 3)
        event["priority"] = req.priority
        event["tenant"] = req.tenant
        if req.deadline_ms is not None:
            # this is the request's single SLO verdict: the counters and
            # loadgen records reuse it rather than re-reading the clock
            req.deadline_met = bool(now <= req.deadline_at)
            event["deadline_ms"] = req.deadline_ms
            event["deadline_met"] = req.deadline_met
        return event
