"""SLO-aware serving front-end over :class:`ContinuousBatchingEngine`.

The batching engine (inference/continuous.py) is a fast decode loop with
an UNBOUNDED pending list drained FIFO-with-skip: fine for a script, not
a server. :class:`ServingEngine` adds the layer a server needs, without
touching the hot path:

- **Bounded admission + backpressure**: ``submit`` returns an
  :class:`Admission` verdict — ``admitted`` (handed to the engine now),
  ``queued`` (bounded queue), or ``shed`` (queue full / KV token budget
  exceeded; nothing enqueued, retry-after hint attached) — instead of
  growing a list without bound.
- **Pluggable scheduling**: FIFO, strict priority, earliest-deadline-
  first, per-tenant fair share (serving/policies.py), all subject to one
  anti-starvation aging rule: a request whose queue wait exceeds
  ``aging_s`` can no longer be leapfrogged, replacing bare FIFO-with-skip.
- **Request lifecycle**: cancellation frees the pool slot mid-flight,
  per-token streaming (callback or pull iterator), and queued work whose
  deadline has blown is shed instead of decoded uselessly.
- **Pipelined drive**: the serving loop drives the engine's
  dispatch-ahead tick pipeline (``pipeline_depth``, default one tick in
  flight — the engine overlaps device compute with this layer's
  scheduling/admission work; ``pipeline_depth=0`` restores the fully
  synchronous loop, token streams bitwise identical). ``tick_stats()``
  reports the dispatch/block/overlap accounting.
- **Fault tolerance** (armed by ``engine_factory=``/``recovery=``; see
  docs/serving.md "Fault tolerance"): a failed engine tick enters an
  escalation ladder — bounded retry-with-backoff for clean (pre-mutation)
  failures, then engine rebuild with every running request re-admitted
  mid-stream (``prompt + emitted``, same engine rid,
  ``gen_base=len(emitted)``) so recovered token streams are BITWISE
  identical to the fault-free run; rebuilds optionally degrade to
  smaller ``degrade_mesh_shapes`` when capacity was lost. While the
  circuit breaker is open, new admissions shed with reason
  ``"recovering"`` and an honest ``retry_after_s``; requests recovery
  cannot re-admit terminate ``shed`` — never a silent drop. Terminal
  failure (every level exhausted) raises :class:`RecoveryFailed`.
- **Telemetry**: every lifecycle transition counts
  (``serve_admitted/shed/expired/cancelled/finished_total``,
  ``serve_deadline_met/missed_total``, ``serve_queue_depth`` /
  ``serve_committed_tokens`` gauges); finished requests' per-request
  ``inference_request`` events are enriched in place (via the engine's
  ``request_event_hook``) with ``path:"serving"``, ``queue_ms``,
  ``ttft_ms``, ``priority``, ``tenant``, ``deadline_ms``/``deadline_met``
  so ``ds_trace_report --serve`` can summarize a run.
- **Request tracing** (docs/telemetry.md "Request tracing"): every
  admitted request carries a ``trace_id`` (optionally sampled via
  ``span_sampler=``) and the lifecycle emits causally-linked ``span``
  events — queue/admission here, tick windows via the engine's
  ``span_hook``, recovery_replay on rebuild, migration bridges from the
  fleet router — that ``telemetry/timeline.py`` reconstructs into one
  per-request timeline with critical-path attribution and Perfetto
  export (``ds_trace_report --request`` / ``ds_trace_timeline``).

Single-threaded by design, like the engine it wraps: the caller (or
``tools/ds_loadgen.py``) drives ``step()``; everything is deterministic
given the injected ``clock``, which is what makes the scheduler-policy
tests exact.

    cb = ContinuousBatchingEngine(model, config=..., cache_buckets=...)
    srv = ServingEngine(cb, policy="edf", max_queue_depth=32)
    adm = srv.submit(prompt, max_new_tokens=64, deadline_ms=500)
    if adm:                       # admitted or queued (falsy == shed)
        for tok in srv.stream(adm.rid):
            ...                   # pulls srv.step() under the hood
"""

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.serving.faults import EnginePreempted
from deepspeed_tpu.serving.policies import SchedulerPolicy, resolve_policy
from deepspeed_tpu.serving.recovery import (
    RecoveryConfig,
    RecoveryFailed,
    RecoveryLog,
    snapshot_request,
)
from deepspeed_tpu.serving.request import (
    ADMITTED,
    CANCELLED,
    EXPIRED,
    FINISHED,
    QUEUED,
    QUEUED_STATUS,
    RUNNING,
    SHED,
    TERMINAL_STATES,
    Admission,
    ServeRequest,
)
from deepspeed_tpu.telemetry.spans import SpanEmitter
from deepspeed_tpu.utils.logging import logger


class TokenStream:
    """Pull-based per-token iterator over one request's output. Each
    ``next()`` returns the next generated token, driving
    ``ServingEngine.step()`` as needed; iteration ends when the request
    reaches a terminal state (check ``request.state`` to tell a finished
    stream from a cancelled/expired one)."""

    def __init__(self, serving: "ServingEngine", request: ServeRequest):
        self._serving = serving
        self._request = request
        self._i = 0

    @property
    def request(self) -> ServeRequest:
        return self._request

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        while self._i >= len(self._request.tokens):
            req = self._request
            if req.state in TERMINAL_STATES:
                raise StopIteration
            if not self._serving.has_work():
                raise StopIteration
            if not self._serving._tracks(req):
                # orphaned: the request claims to be live but the serving
                # layer no longer holds it anywhere work could reach it
                # (e.g. someone cancelled its engine rid directly) —
                # stepping an engine that will never emit for this rid
                # again would spin forever. Terminate with the full lost-
                # request bookkeeping (counters, serving_event, recovery-
                # log retirement), never a silent state flip.
                self._serving._mark_lost(req, "orphaned mid-stream: the "
                                              "engine no longer tracks it")
                raise StopIteration
            self._serving.step()
        tok = self._request.tokens[self._i]
        self._i += 1
        return tok


class ServingEngine:
    """Admission control + scheduling + lifecycle over a
    :class:`ContinuousBatchingEngine` (which this object then owns: it
    installs the request-event hook and expects to be the only caller of
    ``engine.submit``/``step``)."""

    def __init__(self, engine, policy="fifo", max_queue_depth: int = 64,
                 kv_budget_tokens: Optional[int] = None,
                 aging_s: float = 30.0, clock=time.monotonic,
                 pipeline_depth: Optional[int] = None,
                 engine_factory: Optional[Callable] = None,
                 degrade_mesh_shapes: Optional[List[dict]] = None,
                 recovery=None, sleep=time.sleep,
                 span_sampler: Optional[Callable[[int], bool]] = None):
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if aging_s <= 0:
            raise ValueError("aging_s must be > 0")
        if pipeline_depth is not None:
            if pipeline_depth < 0:
                raise ValueError("pipeline_depth must be >= 0")
            # the serving layer drives the engine's dispatch-pipelined tick
            # loop; None keeps whatever the engine was constructed with
            # (default: 1 tick in flight — docs/serving.md "Tick pipeline")
            engine.pipeline_depth = pipeline_depth
        self._cb = engine
        # -- fault tolerance (docs/serving.md "Fault tolerance") --------
        # Recovery is armed when a rebuild factory or an explicit
        # RecoveryConfig is given; otherwise tick exceptions propagate
        # raw, exactly as before this layer existed.
        #   engine_factory(mesh_shape=None) -> ContinuousBatchingEngine
        # builds a replacement engine after a preemption/poisoned tick
        # (build with telemetry OFF: the serving layer re-injects its own
        # hub so counters and the trace file stay continuous);
        # degrade_mesh_shapes lists successively smaller mesh shapes to
        # fall back to when the full-size rebuild fails or a preemption
        # took capacity with it (graceful degradation).
        self.engine_factory = engine_factory
        self.degrade_mesh_shapes = list(degrade_mesh_shapes or [])
        self.recovery_cfg = RecoveryConfig.parse(recovery)
        self._recovery_enabled = (engine_factory is not None
                                  or recovery is not None)
        if self.recovery_cfg.fetch_timeout_s is not None:
            engine.fetch_timeout_s = self.recovery_cfg.fetch_timeout_s
        self._pipeline_depth = pipeline_depth
        self._sleep = sleep
        self._recovery_log = RecoveryLog()
        # highest engine rid ever assigned (+1): a rebuilt engine's rid
        # counter resumes here, so a new request after a recovery gets
        # the same engine rid — hence the same per-request RNG stream —
        # it would have gotten in the fault-free run
        self._rid_watermark = 0
        self._breaker_open = False
        self._outage_start: Optional[float] = None
        self._consecutive_failures = 0
        self._fault_count = 0
        self._retry_count = 0
        self._rebuild_count = 0
        self._lost_ticks = 0
        self._lost_requests = 0
        self._degrade_level = 0          # 0 = full mesh, i = degrade_mesh_shapes[i-1]
        self._recovery_ms: List[float] = []
        self._outage_ms_total = 0.0
        self._closed = False
        # serving-level prefix registry: stable ids that survive engine
        # rebuilds (tokens kept host-side, re-registered on the new engine)
        self._prefixes: Dict[int, np.ndarray] = {}
        self._prefix_pids: Dict[int, int] = {}   # serving pid -> engine pid
        self._next_prefix_id = 0
        self.policy: SchedulerPolicy = resolve_policy(policy, aging_s=aging_s)
        self.max_queue_depth = max_queue_depth
        # KV token budget: total prompt+output tokens committed across
        # RUNNING + QUEUED requests. Default 2x the slot-pool capacity —
        # one poolful decoding plus one poolful staged behind it; more
        # than that is queue wait the client should see as backpressure.
        cap = sum(p["slots"] * p["length"] for p in engine.pool_state())
        self.kv_budget_tokens = (kv_budget_tokens if kv_budget_tokens is not None
                                 else 2 * cap)
        if self.kv_budget_tokens < 1:
            raise ValueError("kv_budget_tokens must be >= 1")
        self.aging_s = aging_s
        self._clock = clock
        self._created = clock()   # uptime zero for /statusz
        self._draining = False    # drain(): admission closed, work finishes
        self._ops_server = None   # live ops plane (start_ops_server)
        # Ops-plane read lock (docs/static_analysis.md "Interprocedural
        # passes", docs/telemetry.md "Live ops plane"): the exporter's
        # handler threads call health()/statusz()/tick_stats() while the
        # tick loop runs. The ONE discipline: those readers hold this
        # RLock; the tick loop takes it only around the engine swap in
        # _restore_onto (the single multi-step mutation whose
        # intermediate states — half-restored engine, cleared _running —
        # must never be scraped). Everything else the readers touch is
        # either read under the lock as an atomic copy (list/dict of a
        # container the main thread mutates in place) or a single
        # attribute load. step() itself never takes the lock: a scrape
        # can never block the hot path on device work.
        self._ops_lock = threading.RLock()
        self._tele = engine._eng.telemetry
        self._queue: List[ServeRequest] = []
        self._running: Dict[int, ServeRequest] = {}   # engine rid -> request
        self._requests: Dict[int, ServeRequest] = {}  # serving rid -> request
        # handed to the engine but not yet admitted by an engine tick: the
        # engine queues them in _pending, so pool_state() still reports
        # their slots free — admission math must reserve them explicitly
        self._staged: Dict[int, int] = {}             # engine rid -> need_tokens
        self._next_rid = 0
        self._t_start: Optional[float] = None  # first submit: rate clock zero
        self._tokens_done = 0                  # finished requests' tokens
        # committed (finished-request) tokens per tenant — the /statusz
        # fair-share view and serve_tenant_committed_tokens gauges
        self._tenant_tokens: Dict[str, int] = {}
        engine.request_event_hook = self._event_hook
        # -- request-scoped tracing (docs/telemetry.md "Request tracing") --
        # One SpanEmitter per serving engine; span ids are scope-unique so
        # several replicas sharing one trace file never collide. The
        # sampler (None = trace everything) decides per ORIGINAL serving
        # rid at submit; sampled-out requests get trace_id None and emit
        # no spans (their counters/events are untouched). The engine-side
        # span hook is installed only when the hub is live, so a disabled
        # build never pays the per-tick window bookkeeping.
        self._span_sampler = span_sampler
        self._spans = SpanEmitter(self._tele, clock=clock)
        self._drain_t0: Optional[float] = None  # drain() start, for drain_wait
        if self._tele.enabled:
            engine.span_hook = self._span_hook

    # -- public API -----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               priority: int = 0, tenant: str = "default",
               deadline_ms: Optional[float] = None,
               on_token=None, prefix_id: Optional[int] = None) -> Admission:
        """Admission-controlled submit. Malformed arguments raise
        ValueError (an oversized request can NEVER run — that is an
        error, not load); a well-formed one is admitted, queued, or shed
        with explicit backpressure. Shed requests get no id and leave no
        state behind. With ``prefix_id`` (``register_prefix``),
        ``prompt_ids`` is the per-request SUFFIX; admission splices the
        registered prefix KV and only the suffix is prefilled. While the
        circuit breaker is open (engine lost, recovery in progress) new
        work is shed with reason ``"recovering"`` and an honest
        ``retry_after_s`` covering the expected outage."""
        if prefix_id is not None:
            if prefix_id not in self._prefixes:
                raise KeyError(f"unknown prefix id {prefix_id}: never "
                               f"registered with this serving engine")
            suffix = np.asarray(prompt_ids, np.int32).reshape(-1)
            if suffix.size == 0:
                raise ValueError("empty suffix (use submit without "
                                 "prefix_id for prefix-only prompts)")
            prompt_ids = np.concatenate([self._prefixes[prefix_id], suffix])
        prompt = self._cb.validate_request(prompt_ids, max_new_tokens)
        need = int(prompt.size) + max_new_tokens
        if need > self.kv_budget_tokens:
            # structurally inadmissible: no amount of draining frees
            # enough budget, so a shed-with-retry-hint would loop forever
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds kv_budget_tokens {self.kv_budget_tokens}: this "
                f"request can never be admitted under the configured budget")
        now = self._clock()
        if self._t_start is None:
            self._t_start = now
        if self._draining:
            # the replica is being removed from the fleet: no retry hint —
            # the client must go to another replica, not wait for this one
            return self._shed("draining", prompt, need, now, no_hint=True)
        if self._breaker_open:
            # honest degradation: during an outage admission answers
            # immediately with a load-shed verdict + recovery ETA rather
            # than queueing work behind an engine that may never return
            return self._shed("recovering", prompt, need, now)
        if len(self._queue) >= self.max_queue_depth:
            return self._shed("queue_full", prompt, need, now)
        committed = self.committed_tokens()
        if committed + need > self.kv_budget_tokens:
            return self._shed("kv_budget", prompt, need, now,
                              excess=committed + need - self.kv_budget_tokens)
        rid = self._next_rid
        self._next_rid += 1
        req = ServeRequest(rid=rid, prompt=prompt,
                           max_new_tokens=max_new_tokens, priority=priority,
                           tenant=tenant, deadline_ms=deadline_ms,
                           on_token=on_token, submit_t=now,
                           prefix_id=prefix_id)
        if self._tele.enabled and (self._span_sampler is None
                                   or self._span_sampler(rid)):
            # trace identity = birth replica + original serving rid; it
            # rides the recovery entry unchanged, so spans emitted after a
            # migration still land on the SAME trace
            req.trace_id = f"{self._trace_scope()}{rid}"
        self._requests[rid] = req
        # empty queue + a fitting free slot: hand straight to the engine —
        # the strongest statement submit can truthfully make (with a
        # non-empty queue the policy decides, so the verdict is "queued")
        if not self._queue and self._fits_now(need):
            self._handover(req, now)
            status = ADMITTED
        else:
            self._queue.append(req)
            status = QUEUED_STATUS
        self._update_gauges()
        return Admission(status=status, rid=rid)

    def step(self) -> Dict[int, List[int]]:
        """One serving tick: expire deadline-blown queued work, place
        queued requests into free slots in policy order (bounded by the
        aging barrier), then one engine tick. Returns {rid: [tokens]}
        emitted this tick, keyed by SERVING rid."""
        now = self._clock()
        self._expire(now)
        self._schedule(now)
        out: Dict[int, List[int]] = {}
        if self._cb.has_work():
            emitted, ticked = self._guarded_tick()
            if ticked:
                # the engine admits every placeable pending request at the
                # top of its tick, and we only hand over what fits — so
                # after the tick the staged reservations are real slots
                # (pool_state now counts them) or already finished-and-
                # freed. A recovered (re-admitted) tick keeps its staged
                # reservations: the rebuilt engine has not ticked yet.
                self._staged.clear()
            tnow = self._clock()
            for erid, toks in emitted.items():
                req = self._running.get(erid)
                if req is None:
                    continue  # not ours (direct engine.submit user)
                if req.first_token_t is None and toks:
                    req.first_token_t = tnow
                req.tokens.extend(toks)
                self._recovery_log.extend(req.rid, toks)
                out[req.rid] = list(toks)
                if req.on_token is not None:
                    for tok in toks:
                        req.on_token(req.rid, tok)
            for erid, result in self._cb.finished().items():
                req = self._running.pop(erid, None)
                if req is None:
                    continue
                self._finish_request(req, result, tnow)
            if ticked and self._tele.enabled:
                s = self._cb.tick_stats()
                if s.get("spec_drafted"):
                    # live acceptance rate for /metrics + /statusz: the
                    # one number that says whether speculation is earning
                    # its verify FLOPs right now
                    self._tele.registry.gauge("serve_spec_acceptance").set(
                        round(s["spec_accepted"] / s["spec_drafted"], 4))
        if (self._drain_t0 is not None and self._draining
                and not self.has_work()):
            # the drain completed this tick: close the ops-scoped
            # drain_wait span (how long removal-from-rotation stalled on
            # in-flight work)
            self._spans.emit("drain_wait", f"{self._trace_scope()}ops",
                             self._drain_t0, self._clock())
            self._drain_t0 = None
        self._update_gauges()
        return out

    def _finish_request(self, req: ServeRequest, result, now: float):
        """The ONE FINISHED transition (normal retirement and recovered-
        complete synthesis both land here): record/result state, recovery
        log retirement, the deadline fallback verdict, rate accounting,
        policy hook, and the finished/deadline counters."""
        req.state = FINISHED
        req.finish_t = now
        req.result = result
        self._recovery_log.retire(req.rid)
        if req.deadline_ms is not None and req.deadline_met is None:
            # telemetry off: the event hook didn't judge it first
            req.deadline_met = now <= req.deadline_at
        self._tokens_done += len(req.tokens)
        self._tenant_tokens[req.tenant] = (
            self._tenant_tokens.get(req.tenant, 0) + len(req.tokens))
        self.policy.on_finish(req, now)
        if self._tele.enabled:
            reg = self._tele.registry
            reg.counter("serve_finished_total").inc()
            reg.gauge("serve_tenant_committed_tokens",
                      {"tenant": req.tenant}).set(
                self._tenant_tokens[req.tenant])
            if req.deadline_met is not None:
                reg.counter("serve_deadline_met_total" if req.deadline_met
                            else "serve_deadline_missed_total").inc()

    # -- fault tolerance ------------------------------------------------
    def _guarded_tick(self):
        """One engine tick under the recovery policy. Returns
        ``(emitted, ticked)`` — ``ticked`` False when the tick was lost
        to a fault and the engine was rebuilt (the re-admitted requests'
        staged reservations must survive until the NEW engine ticks).
        With recovery disarmed (no factory, no RecoveryConfig) this is a
        bare ``engine.step()`` — exceptions propagate unchanged."""
        if not self._recovery_enabled:
            return self._cb.step(), True
        try:
            emitted = self._cb.step()
        except Exception as e:  # noqa: BLE001 — any tick failure enters recovery
            return self._on_tick_failure(e)
        self._consecutive_failures = 0
        if self._breaker_open:
            self._close_breaker()
        return emitted, True

    def _on_tick_failure(self, exc: Exception):
        """The escalation ladder: bounded retry-with-backoff for a CLEAN
        failure (raised before the engine mutated state), then engine
        rebuild — on the full mesh first, then each configured degraded
        mesh. Ticks in flight on the lost engine are discarded, never
        fetched; the resume RNG design regenerates their tokens bitwise."""
        cfg = self.recovery_cfg
        now = self._clock()
        self._open_breaker(now)
        self._consecutive_failures += 1
        self._fault_count += 1
        self._fault_event("fault", error=type(exc).__name__,
                          detail=str(exc)[:200],
                          poisoned=bool(self._cb.poisoned),
                          consecutive=self._consecutive_failures)
        if self._tele.enabled:
            self._tele.registry.counter("serve_fault_total").inc()
        # a poisoned engine (exception past the dispatch barrier: results
        # lost mid-pipeline) or an explicit preemption must NOT be
        # retried — a retried tick would leave a hole in every stream
        retryable = not self._cb.poisoned and not isinstance(exc, EnginePreempted)
        if retryable:
            for attempt in range(cfg.max_tick_retries):
                self._sleep(cfg.backoff_s * (2 ** attempt))
                self._retry_count += 1
                if self._tele.enabled:
                    self._tele.registry.counter("serve_tick_retry_total").inc()
                try:
                    emitted = self._cb.step()
                except Exception as e2:  # noqa: BLE001 — retry outcome feeds escalation
                    self._consecutive_failures += 1
                    self._fault_count += 1
                    if self._tele.enabled:
                        # a failed retry IS another fault: the counter,
                        # recovery_stats()["faults"] and the trace-report
                        # recovery section must all agree on the total
                        self._tele.registry.counter("serve_fault_total").inc()
                    self._fault_event("retry_failed", attempt=attempt + 1,
                                      error=type(e2).__name__,
                                      consecutive=self._consecutive_failures)
                    exc = e2
                    if self._cb.poisoned or isinstance(e2, EnginePreempted):
                        break  # state lost mid-retry: straight to rebuild
                else:
                    # a real completed tick: tokens flow through the
                    # normal attribution path, staged slots are consumed
                    self._fault_event("retried", attempt=attempt + 1)
                    self._consecutive_failures = 0
                    self._close_breaker()
                    return emitted, True
        self._rebuild(exc)
        return {}, False

    def _rebuild(self, exc: Exception):
        """Abandon the engine and build a replacement, re-admitting every
        running request mid-stream (prompt + emitted, same engine rid,
        ``gen_base=len(emitted)`` — bitwise resume). Escalates through
        ``degrade_mesh_shapes`` when a build fails or the preemption took
        capacity; raises :class:`RecoveryFailed` (after marking every
        live request shed) when nothing can be built."""
        cfg = self.recovery_cfg
        t0 = self._clock()
        if self.engine_factory is None:
            self._fail_terminally(exc, "no engine_factory configured — "
                                       "cannot rebuild the lost engine")
        if self._rebuild_count >= cfg.max_rebuilds:
            self._fail_terminally(exc, f"max_rebuilds={cfg.max_rebuilds} "
                                       f"exhausted")
        lost = self._cb.abort_inflight()
        self._lost_ticks += lost
        old_hook = self._cb.fault_hook
        # degradation ladder: level 0 = the factory's full-size build,
        # level i = degrade_mesh_shapes[i-1]. A degrading preemption
        # advances the ladder before building; a failed build advances it
        # and tries again.
        shapes: List[Optional[dict]] = [None] + self.degrade_mesh_shapes
        if isinstance(exc, EnginePreempted) and exc.degrade:
            self._degrade_level = min(self._degrade_level + 1,
                                      len(shapes) - 1)
            if self._degrade_level == 0 or shapes[self._degrade_level] is None:
                logger.warning("preemption demanded degradation but no "
                               "degrade_mesh_shapes are configured — "
                               "rebuilding at full size")
        new = None
        while new is None:
            shape = shapes[self._degrade_level]
            try:
                new = self.engine_factory(mesh_shape=shape)
            except Exception as build_err:  # noqa: BLE001 — feeds the degradation ladder
                self._fault_event("rebuild_failed", mesh=shape,
                                  error=type(build_err).__name__,
                                  detail=str(build_err)[:200])
                if self._degrade_level + 1 < len(shapes):
                    self._degrade_level += 1
                else:
                    self._fail_terminally(
                        build_err, "engine_factory failed at every "
                                   "degradation level")
        try:
            # device-heavy restore (prefix re-prefill + re-admission) runs
            # against the replacement OFF the ops lock — a /healthz probe
            # must answer 503 "recovering" instantly, not block for the
            # whole rebuild; only the final multi-reference swap inside
            # _restore_onto takes _ops_lock (see the commit block there)
            readmitted = self._restore_onto(new, old_hook)
        except Exception as restore_err:  # noqa: BLE001 — restore failure is terminal
            # a replacement that cannot be restored (prefix prefill or
            # re-admission raised something other than a size rejection)
            # must still honour the contract: mark every live request
            # shed and SURFACE RecoveryFailed — never a raw escape that
            # leaves requests RUNNING against a half-restored engine
            self._fail_terminally(restore_err,
                                  "replacement engine could not be restored")
        recovery_ms = (self._clock() - t0) * 1000.0
        self._recovery_ms.append(recovery_ms)
        shape = shapes[self._degrade_level]
        self._fault_event("rebuild", recovery_ms=round(recovery_ms, 3),
                          readmitted=readmitted, lost_ticks=lost,
                          degraded=shape is not None, mesh=shape,
                          rebuilds=self._rebuild_count)
        if self._tele.enabled:
            reg = self._tele.registry
            reg.counter("serve_rebuild_total").inc()
            if lost:
                reg.counter("serve_lost_tick_total").inc(lost)
            reg.histogram("recovery_ms").observe(recovery_ms)
        logger.warning(
            f"serving engine rebuilt after {type(exc).__name__} "
            f"(#{self._rebuild_count}, {recovery_ms:.1f} ms, "
            f"{readmitted} re-admitted, {lost} in-flight ticks lost"
            + (f", degraded to mesh {shape}" if shape is not None else "")
            + ")")

    def _restore_onto(self, new, old_hook) -> int:
        """Make the replacement engine serve where the lost one stopped:
        adopt the telemetry hub and hooks, restore rid continuity and
        serving-level prefixes, and re-admit every running request
        mid-stream. Returns the re-admission count. Raises only when the
        replacement itself is unusable (the caller converts that into
        the terminal-failure path).

        Lock discipline: the device-heavy work (prefix re-prefill,
        re-admission prefills) targets only the replacement engine and
        LOCAL tables, off ``_ops_lock`` — a concurrent scrape keeps
        answering from the lost engine's last state (breaker open, so
        ``/healthz`` says 503 "recovering" instantly instead of blocking
        for the whole rebuild). Only the final multi-reference commit —
        engine swap + prefix/running/staged tables + the generation
        bump — runs under the lock, so ``statusz()``/``health()``/
        ``tick_stats()`` see the old engine or the fully restored one,
        never the in-between."""
        cfg = self.recovery_cfg
        # adopt the serving hub on the replacement: ONE trace writer and
        # metrics registry across engine generations (factories build
        # with telemetry off; a factory-created hub would re-open the
        # trace file and fork the counters)
        new._eng.telemetry = self._tele
        new.request_event_hook = self._event_hook
        new.fault_hook = old_hook
        if self._tele.enabled:
            new.span_hook = self._span_hook
        # the replacement's HBM attribution, through the adopted hub (its
        # own build snapshot went to the factory's disabled telemetry):
        # a degraded-mesh rebuild's changed per-chip footprint is visible
        new.memory_snapshot("rebuild")
        if self._pipeline_depth is not None:
            new.pipeline_depth = self._pipeline_depth
        if cfg.fetch_timeout_s is not None:
            new.fetch_timeout_s = cfg.fetch_timeout_s
        # rid continuity: new requests continue the rid sequence the lost
        # engine was on, so their RNG streams match the fault-free run
        new._next_rid = max(new._next_rid, self._rid_watermark)
        # serving-level prefixes survive: re-register on the new engine
        prefix_pids = {spid: new.register_prefix(toks)
                       for spid, toks in self._prefixes.items()}
        # re-admit every running request mid-stream, in the lost engine's
        # submission order (deterministic). The RecoveryLog — not the
        # live records — is the source of truth here: it is exactly the
        # jax-free state a cross-process recovery would have.
        readmitted = 0
        running: Dict[int, ServeRequest] = {}
        staged: Dict[int, int] = {}
        for entry in self._recovery_log.entries():
            req = self._requests.get(entry["rid"])
            if req is None or req.state != RUNNING:
                self._recovery_log.retire(entry["rid"])
                continue
            emitted = entry["emitted"]
            remaining = entry["max_new_tokens"] - len(emitted)
            if remaining < 1:
                # every token surfaced but the finish never retired: the
                # stream is complete, finish it host-side
                self._finish_recovered(req, entry)
                continue
            full = np.concatenate([
                np.asarray(entry["prompt"], np.int32),
                np.asarray(emitted, np.int32)]) if emitted else req.prompt
            t0_replay = self._clock()
            try:
                erid = new.submit(full, remaining, rid=entry["engine_rid"],
                                  gen_base=len(emitted))
            except ValueError as e:
                # the degraded engine cannot hold it — shed honestly
                self._mark_lost(req, f"readmit_failed: {e}")
                continue
            running[erid] = req
            staged[erid] = req.need_tokens
            req.recoveries += 1
            readmitted += 1
            if req.trace_id is not None and self._spans.enabled:
                # in-process recovery: the replay span parents on the
                # request's root and becomes the parent of its post-
                # recovery tick windows — the timeline shows recovery
                # time as recovery, not mystery gap
                sid = self._spans.emit(
                    "recovery_replay", req.trace_id, t0_replay, self._clock(),
                    parent_id=req.span_root,
                    attrs={"gen_base": len(emitted),
                           "engine_rid": int(erid)})
                req.span_parent = sid
        # commit: the one multi-step mutation a scrape must never observe
        # half-done (the _ops_lock read/swap discipline)
        with self._ops_lock:
            self._cb = new
            self._prefix_pids = prefix_pids
            self._running = running
            self._staged.clear()
            self._staged.update(staged)
            self._rebuild_count += 1
        return readmitted

    def _finish_recovered(self, req: ServeRequest, entry: dict):
        """A lost request whose stream was already complete host-side:
        synthesize the result (and the ``inference_request`` event the
        lost engine never got to retire — trace-derived finished counts
        must match the registry counters), then run the one shared
        FINISHED transition."""
        if self._tele.enabled:
            event = {"request": int(req.rid), "path": "continuous",
                     "batch": 1, "prompt_tokens": len(entry["prompt"]),
                     "new_tokens": len(entry["emitted"]),
                     "recovered_finish": True}
            # enrich through the one enrichment path (queue_ms/ttft/
            # priority/tenant + the single SLO verdict) with the request
            # in hand — never a transient write to the live _running
            # table (this runs off _ops_lock during restore; a scrape
            # could observe the intermediate entry)
            event = self._enrich_event(req, event) or event
            self._tele.emit("inference_request", event)
        self._finish_request(req, np.concatenate([
            np.asarray(entry["prompt"], np.int32),
            np.asarray(entry["emitted"], np.int32)]), self._clock())

    def _mark_lost(self, req: ServeRequest, reason: str):
        """Terminal shed for a request recovery could not re-admit: the
        honest outcome — never a silent drop (the conservation invariant
        admitted == finished + shed + expired + cancelled holds)."""
        now = self._clock()
        req.state = SHED
        req.finish_t = now
        self._running = {erid: r for erid, r in self._running.items()
                         if r.rid != req.rid}
        self._queue = [r for r in self._queue if r.rid != req.rid]
        self._recovery_log.retire(req.rid)
        self._lost_requests += 1
        if self._tele.enabled:
            self._tele.registry.counter("serve_lost_request_total").inc()
            self._tele.emit("serving_event", {
                "event": "shed", "reason": "engine_lost", "request": req.rid,
                "detail": reason[:200], "tokens_emitted": len(req.tokens),
            })

    def _fail_terminally(self, exc: Exception, detail: str):
        """Recovery exhausted: mark every live request shed (streams
        terminate, accounting stays conservative), emit the terminal
        fault event, and raise :class:`RecoveryFailed` — ``run()`` and
        ``step()`` SURFACE this; nothing swallows it."""
        # gather from the record table, not _queue/_running: a failure
        # mid-restore leaves _running only partially rebuilt, and every
        # live request must still be accounted for
        live = [r for r in self._requests.values()
                if r.state not in TERMINAL_STATES]
        for req in live:
            self._mark_lost(req, f"unrecoverable: {detail}")
        self._fault_event("unrecoverable", error=type(exc).__name__,
                          detail=detail, requests_lost=len(live))
        self._update_gauges()
        raise RecoveryFailed(
            f"serving recovery failed ({detail}); last engine fault: "
            f"{type(exc).__name__}: {exc}. {len(live)} in-flight "
            f"request(s) marked shed.") from exc

    def _open_breaker(self, now: float):
        if self._breaker_open:
            return
        with self._ops_lock:  # serialize with statusz(): its health/
            # breaker_open fields must come from one consistent state
            self._breaker_open = True
            self._outage_start = now
        self._fault_event("breaker", state="open")

    def _close_breaker(self):
        if not self._breaker_open:
            return
        now = self._clock()
        outage_ms = ((now - self._outage_start) * 1000.0
                     if self._outage_start is not None else 0.0)
        with self._ops_lock:
            self._outage_ms_total += outage_ms
            self._breaker_open = False
            self._outage_start = None
        self._fault_event("breaker", state="closed",
                          outage_ms=round(outage_ms, 3))

    def _fault_event(self, event: str, **fields):
        if self._tele.enabled:
            payload = {"event": event}
            payload.update(fields)
            self._tele.emit("serving_fault", payload)

    def recovery_stats(self) -> dict:
        """In-process view of the fault/recovery accounting (what
        ``ds_loadgen --chaos`` reports and ``ds_trace_report --serve``
        recomputes from ``serving_fault`` trace events)."""
        out = {
            "faults": self._fault_count,
            "retries": self._retry_count,
            "rebuilds": self._rebuild_count,
            "lost_ticks": self._lost_ticks,
            "lost_requests": self._lost_requests,
            "degrade_level": self._degrade_level,
            "outage_ms_total": round(self._outage_ms_total, 3),
            "breaker_open": self._breaker_open,
        }
        if self._recovery_ms:
            # the same interpolated percentile ds_trace_report computes
            # from the serving_fault journal — the two tools must agree
            from deepspeed_tpu.telemetry.registry import percentile

            rs = sorted(self._recovery_ms)
            out["recovery_ms"] = {
                "count": len(rs),
                "p50": round(percentile(rs, 50.0), 3),
                "max": round(rs[-1], 3),
            }
        return out

    def register_prefix(self, prefix_ids) -> int:
        """Serving-level prefix registration: like the engine's
        ``register_prefix`` but with an id that stays valid across
        engine rebuilds (the tokens are kept host-side and re-registered
        on every replacement engine)."""
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        epid = self._cb.register_prefix(prefix)  # validates + prefills
        spid = self._next_prefix_id
        self._next_prefix_id += 1
        self._prefixes[spid] = prefix
        self._prefix_pids[spid] = epid
        return spid

    def unregister_prefix(self, prefix_id: int):
        if prefix_id not in self._prefixes:
            raise KeyError(f"unknown prefix id {prefix_id}")
        self._prefixes.pop(prefix_id)
        epid = self._prefix_pids.pop(prefix_id)
        self._cb.unregister_prefix(epid)

    def _tracks(self, req: ServeRequest) -> bool:
        """Whether serving still holds ``req`` somewhere a ``step()`` can
        make progress on it — the TokenStream spin guard."""
        if req.state == QUEUED:
            return any(r.rid == req.rid for r in self._queue)
        if req.state == RUNNING:
            return any(r.rid == req.rid for r in self._running.values())
        return False

    def run(self, max_ticks: Optional[int] = None) -> int:
        """Step until idle (or ``max_ticks``); returns ticks taken.
        A terminal recovery failure (:class:`RecoveryFailed` — retries
        and every rebuild level exhausted) propagates to the caller; it
        is never swallowed into a normal-looking return."""
        ticks = 0
        while self.has_work():
            if max_ticks is not None and ticks >= max_ticks:
                break
            self.step()
            ticks += 1
        return ticks

    def has_work(self) -> bool:
        return bool(self._queue) or self._cb.has_work()

    def queue_depth(self) -> int:
        return len(self._queue)

    # -- live ops plane (docs/telemetry.md "Live ops plane") -------------
    def drain(self):
        """Stop admission while queued + running work runs to completion
        — the fleet-router precondition for removing a replica: after
        ``drain()``, ``submit`` sheds with reason ``"draining"`` (no
        retry hint: clients must go elsewhere), ``/healthz`` answers 503,
        and ``step()`` keeps serving until ``has_work()`` is False —
        in-flight streams finish bitwise-intact. Idempotent; ``resume()``
        reopens admission."""
        if self._draining:
            return
        with self._ops_lock:  # consistent with a concurrent statusz()
            self._draining = True
        # drain_wait span clock zero: step() closes the span (under the
        # replica's ops trace id) once the last in-flight stream retires
        self._drain_t0 = self._clock() if self.has_work() else None
        if self._tele.enabled:
            self._tele.emit("serving_event", {
                "event": "drain", "queue_depth": len(self._queue),
                "running": len(self._running)})

    def resume(self):
        """Reopen admission after :meth:`drain` (replica back in rotation)."""
        if not self._draining:
            return
        with self._ops_lock:
            self._draining = False
        self._drain_t0 = None  # drain aborted: no drain_wait span
        if self._tele.enabled:
            self._tele.emit("serving_event", {"event": "resume"})

    @property
    def draining(self) -> bool:
        return self._draining

    def health(self) -> str:
        """One-word replica health for ``/healthz``:

        - ``"recovering"`` — the circuit breaker is open (engine lost,
          the PR 7 recovery ladder is running); closes on the first
          healthy tick of a replacement.
        - ``"poisoned"`` — the engine marked its state untrustworthy and
          NO recovery is armed to replace it: operator intervention.
        - ``"draining"`` — admission closed, in-flight work finishing.
        - ``"ok"`` — take traffic.

        Only ``"ok"`` answers HTTP 200 on ``/healthz``."""
        with self._ops_lock:  # exporter-thread read discipline
            if self._breaker_open:
                return "recovering"
            if getattr(self._cb, "poisoned", False):
                return "poisoned"
            if self._draining:
                return "draining"
            return "ok"

    def statusz(self) -> dict:
        """One JSON-shaped snapshot for ``/statusz``: health, uptime,
        pool occupancy, queue depth, committed KV tokens, in-flight tick
        depth, tick overlap accounting, recovery generation, and the
        per-chip HBM attribution. Read-only and safe to call from the
        ops-server thread: the whole read runs under ``_ops_lock`` (the
        shared read/swap discipline — a recovery rebuild can therefore
        never swap ``_cb`` out from under a half-built snapshot), with
        shared containers additionally copied atomically before
        iteration so a concurrent ``step()`` can never torn-read them."""
        with self._ops_lock:
            now = self._clock()
            queue = list(self._queue)
            running = list(dict(self._running).values())
            requests = list(dict(self._requests).values())
            counts: Dict[str, int] = {}
            for r in requests:
                counts[r.state] = counts.get(r.state, 0) + 1
            stats = self.tick_stats()
            out = {
                "health": self.health(),
                "uptime_s": round(now - self._created, 3),
                "draining": self._draining,
                "pools": self._cb.pool_state(),
                "queue_depth": len(queue),
                "running": len(running),
                "requests": counts,
                "committed_kv_tokens": (sum(r.need_tokens for r in queue)
                                        + sum(r.need_tokens for r in running)),
                "kv_budget_tokens": self.kv_budget_tokens,
                "inflight_depth": len(self._cb._inflight),
                "pipeline_depth": self._cb.pipeline_depth,
                "ticks": stats.get("ticks", 0),
                "overlap_frac": stats.get("overlap_frac"),
                "block_ms_per_token": stats.get("block_ms_per_token"),
                "recovery_generation": self._rebuild_count,
                "breaker_open": self._breaker_open,
                # speculative decode health: lifetime acceptance rate
                # (accepted drafts / proposed drafts; None = speculation
                # never ran) — mirrors the serve_spec_acceptance gauge
                "spec_acceptance": stats.get("spec_acceptance"),
                # committed (finished-request) tokens per tenant — the
                # fair-share ledger behind the per-tenant
                # serve_tenant_committed_tokens gauges
                "tenant_committed_tokens": dict(self._tenant_tokens),
                # queue residue: how much admitted-but-unfinished work
                # this replica still owes. "draining with residue" means
                # don't place here, but the work WILL finish; "breaker
                # open" means don't place here, the work may die — a
                # fleet router (or any external probe) must not conflate
                # the two when deciding whether to wait or migrate.
                "residue_queued": len(queue),
                "residue_running": len(running),
                "residue_tokens": (
                    sum(max(0, r.max_new_tokens - len(r.tokens))
                        for r in queue)
                    + sum(max(0, r.max_new_tokens - len(r.tokens))
                          for r in running)),
            }
            try:
                from deepspeed_tpu.telemetry import memory as hbm

                comps = self._cb.hbm_components()
                out["hbm_bytes"] = comps
                headroom = hbm.headroom_bytes(self._tele, comps)
                if headroom is not None:
                    out["hbm_headroom_bytes"] = headroom
            except Exception:  # noqa: BLE001 — status must render even mid-rebuild
                pass
            return out

    def hbm_headroom_bytes(self) -> Optional[int]:
        """Per-chip HBM headroom (configured/backend limit minus the live
        attribution) — the number an admission policy or the fleet router
        consults before placing more KV on this replica. None when no
        limit is known (the CPU virtual mesh without an override)."""
        from deepspeed_tpu.telemetry import memory as hbm

        return hbm.headroom_bytes(self._tele, self._cb.hbm_components())

    def start_ops_server(self, port: int = 0, host: str = "127.0.0.1"):
        """Serve ``/metrics`` (Prometheus), ``/healthz`` and ``/statusz``
        for this replica on a daemon thread (telemetry/ops_server.py).
        ``port=0`` binds an ephemeral port — read it from the returned
        server's ``.port``/``.url``. Idempotent (returns the live
        server); ``close()`` shuts it down."""
        if self._ops_server is not None:
            return self._ops_server
        from deepspeed_tpu.telemetry.ops_server import OpsServer

        self._ops_server = OpsServer(
            registry=self._tele.registry, health=self.health,
            status=self.statusz, host=host, port=port).start()
        return self._ops_server

    def committed_tokens(self) -> int:
        """Prompt+output tokens committed by queued + running requests —
        what admission weighs against ``kv_budget_tokens``."""
        return (sum(r.need_tokens for r in self._queue)
                + sum(r.need_tokens for r in self._running.values()))

    def tick_stats(self) -> dict:
        """Tick-utilization accounting for the serving loop: the engine's
        dispatch/block/overlap numbers (``ContinuousBatchingEngine.
        tick_stats``) plus ``utilization`` — fraction of the dispatched
        emission capacity actually emitted (tokens / capacity_tokens,
        where each ticked pool contributes slots × burst). This is the
        in-process view of what ``ds_trace_report --serve`` computes from
        ``serving_tick`` trace events, and what ``ds_loadgen``'s
        ``--pipeline-depth`` A/B compares."""
        with self._ops_lock:  # exporter-thread read discipline
            s = self._cb.tick_stats()
        cap = s.get("capacity_tokens", 0)
        s["utilization"] = round(s["tokens"] / cap, 4) if cap else 0.0
        return s

    def status(self, rid: int) -> str:
        req = self._requests.get(rid)
        return req.state if req is not None else "unknown"

    def request(self, rid: int) -> Optional[ServeRequest]:
        """The live request record (None once reaped or never admitted)."""
        return self._requests.get(rid)

    def result(self, rid: int):
        """Pop a FINISHED request's full token array (prompt + generated).
        Raises KeyError naming the actual state otherwise — mirrors
        ``ContinuousBatchingEngine.result`` semantics."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(f"no result for request {rid}: unknown — never "
                           f"admitted, shed, or already reaped")
        if req.state != FINISHED:
            raise KeyError(f"no result for request {rid}: {req.state}")
        self._requests.pop(rid)
        return req.result

    def reap(self) -> Dict[int, ServeRequest]:
        """Remove and return every terminal-state request record —
        finished (``.result`` holds the tokens), cancelled, and expired.
        A long-running server calls this (or ``result``) to keep the
        record table bounded; the load generator uses it for reporting."""
        done = {rid: r for rid, r in self._requests.items()
                if r.state in TERMINAL_STATES}
        for rid in done:
            self._requests.pop(rid)
        return done

    def close(self):
        """Flush/close the telemetry trace (the engines share one hub,
        including across rebuilds); the load generator and servers call
        this at shutdown. Idempotent and fault-safe: double close and
        close during/after a (possibly failed) recovery are no-ops —
        shutdown paths run from exception handlers and must never raise."""
        if self._closed:
            return
        self._closed = True
        if self._ops_server is not None:
            self._ops_server.close()  # never raises
            self._ops_server = None
        try:
            self._tele.close()
        except Exception as e:  # noqa: BLE001 — shutdown must not raise
            logger.warning(f"serving close: telemetry close failed ({e})")

    def stream(self, rid: int) -> TokenStream:
        """Per-token pull iterator for an admitted/queued request; tokens
        already emitted are replayed first, then each ``next()`` drives
        ``step()`` until the next token or a terminal state."""
        req = self._requests.get(rid)
        if req is None:
            raise KeyError(f"unknown request {rid}: shed or already reaped")
        return TokenStream(self, req)

    def cancel(self, rid: int) -> bool:
        """Cancel a queued or running request. A running one frees its
        pool slot immediately — the next ``step()`` can admit into it.
        False when already terminal/unknown (nothing left to cancel)."""
        req = self._requests.get(rid)
        if req is None or req.state in TERMINAL_STATES:
            return False
        now = self._clock()
        if req.state == QUEUED:
            self._queue = [r for r in self._queue if r.rid != rid]
        else:  # RUNNING
            self._cb.cancel(req.engine_rid)
            self._running.pop(req.engine_rid, None)
            self._staged.pop(req.engine_rid, None)
            self._recovery_log.retire(rid)
        req.state = CANCELLED
        req.finish_t = now
        if self._tele.enabled:
            self._tele.registry.counter("serve_cancelled_total").inc()
            self._tele.emit("serving_event", {
                "event": "cancelled", "request": rid,
                "queue_ms": round(req.waited_s(now) * 1000.0, 3),
                "tokens_emitted": len(req.tokens),
            })
        self._update_gauges()
        return True

    # -- fleet membership (serving/router.py) ---------------------------
    @property
    def vocab_size(self) -> int:
        """The engine's vocabulary size — surfaced so fleet-level callers
        (router, load generator) never reach into ``_cb.cfg``."""
        return self._cb.cfg.vocab_size

    def set_rid_base(self, base: int):
        """Partition the engine-rid namespace for fleet membership: every
        rid this replica assigns naturally from now on is ``>= base``.
        The fleet router gives each replica slot a disjoint stride so a
        migrated request's pinned engine rid (its RNG identity, hence its
        bitwise token stream) can never collide with a rid the survivor
        hands out on its own. Slot 0 keeps base 0 — a single-replica
        fleet is rid-for-rid identical to a bare serving engine."""
        self._rid_watermark = max(self._rid_watermark, int(base))
        self._cb._next_rid = max(self._cb._next_rid, int(base))

    def admission_outlook(self, need_tokens: int):
        """What :meth:`submit` would answer RIGHT NOW for a well-formed
        request committing ``need_tokens`` — ``(status, reason)`` with no
        side effects: nothing is admitted, queued, or counted, and no
        ``serving_event`` is emitted. The fleet router uses this to rank
        candidate replicas before spending the one real ``submit`` (whose
        verdict — and shed event — is the honest, final one)."""
        with self._ops_lock:
            if self._draining:
                return SHED, "draining"
            if self._breaker_open:
                return SHED, "recovering"
            if len(self._queue) >= self.max_queue_depth:
                return SHED, "queue_full"
            if self.committed_tokens() + need_tokens > self.kv_budget_tokens:
                return SHED, "kv_budget"
            if not self._queue and self._fits_now(need_tokens):
                return ADMITTED, ""
            return QUEUED_STATUS, ""

    def recovery_snapshot(self, include_queued: bool = False) -> List[dict]:
        """Plain-data copy of every RUNNING request's recovery entry
        (prompt, emitted tokens, remaining quota, engine rid — see
        ``RecoveryLog``). This is what the fleet router reads off a dead
        replica to re-admit its streams onto survivors. With
        ``include_queued`` the host-side queue is appended too (entries
        with ``engine_rid`` None, in queue order) — queued requests have
        no device state but a dead replica's queue still holds work the
        fleet must not lose."""
        with self._ops_lock:
            out = self._recovery_log.snapshot()
            if include_queued:
                out.extend(snapshot_request(r) for r in list(self._queue))
        return out

    def readmit(self, entry: dict, *, on_token=None,
                parent_span: Optional[str] = None) -> Admission:
        """Re-admit a (possibly foreign) ``RecoveryLog`` entry onto THIS
        serving engine, resuming its stream mid-token: the handover
        re-prefills ``prompt + emitted`` and continues at
        ``gen_base=len(emitted)`` under the entry's pinned engine rid, so
        the tokens that follow are bitwise the ones the lost replica
        would have produced (``entry["engine_rid"]`` None — the request
        never reached that engine — gets a natural rid and a fresh
        stream). Admission-controlled exactly like :meth:`submit`: the
        verdict is honest, and a shed leaves no state behind. A pinned
        rid this engine already holds raises ValueError (namespace
        collision — see :meth:`set_rid_base`)."""
        prompt = np.asarray(entry["prompt"], np.int32).reshape(-1)
        emitted = [int(t) for t in entry.get("emitted", [])]
        max_new = int(entry["max_new_tokens"])
        need = int(prompt.size) + max_new
        now = self._clock()
        if self._t_start is None:
            self._t_start = now
        if max_new - len(emitted) < 1:
            # every token already surfaced host-side: synthesize the
            # finish — nothing left for an engine to generate
            rid = self._next_rid
            self._next_rid += 1
            req = self._entry_request(rid, entry, prompt, on_token, emitted)
            self._requests[rid] = req
            self._finish_request(req, np.concatenate(
                [prompt, np.asarray(emitted, np.int32)]), now)
            return Admission(status=ADMITTED, rid=rid)
        if need > self.kv_budget_tokens:
            raise ValueError(
                f"recovery entry needs {need} tokens, over this replica's "
                f"kv_budget_tokens {self.kv_budget_tokens}: it can never "
                f"be admitted here")
        if self._draining:
            return self._shed("draining", prompt, need, now, no_hint=True)
        if self._breaker_open:
            return self._shed("recovering", prompt, need, now)
        if len(self._queue) >= self.max_queue_depth:
            return self._shed("queue_full", prompt, need, now)
        committed = self.committed_tokens()
        if committed + need > self.kv_budget_tokens:
            return self._shed("kv_budget", prompt, need, now,
                              excess=committed + need - self.kv_budget_tokens)
        rid = self._next_rid
        self._next_rid += 1
        req = self._entry_request(rid, entry, prompt, on_token, emitted)
        if parent_span is not None:
            # the router's migration span: the survivor-side admission
            # span parents on it, bridging the replicas in one timeline
            req.span_parent = parent_span
        self._requests[rid] = req
        try:
            if not self._queue and self._fits_now(need):
                self._handover(req, now)
                status = ADMITTED
            else:
                self._queue.append(req)
                status = QUEUED_STATUS
        except ValueError:
            # engine refused the resume (rid collision, degraded cache):
            # leave no state behind — the router tries the next survivor
            self._requests.pop(rid, None)
            raise
        self._update_gauges()
        return Admission(status=status, rid=rid)

    def _entry_request(self, rid: int, entry: dict, prompt, on_token,
                       emitted: List[int]) -> ServeRequest:
        """A live ``ServeRequest`` rebuilt from a recovery entry: original
        submit time (queue-wait and deadline clocks keep running across
        the migration), emitted tokens pre-seeded (streams replay them,
        then continue), pinned engine rid carried until handover."""
        req = ServeRequest(rid=rid, prompt=prompt,
                           max_new_tokens=int(entry["max_new_tokens"]),
                           priority=int(entry.get("priority", 0)),
                           tenant=str(entry.get("tenant", "default")),
                           deadline_ms=entry.get("deadline_ms"),
                           on_token=on_token,
                           submit_t=float(entry["submit_t"]))
        req.tokens.extend(emitted)
        req.engine_rid = entry.get("engine_rid")
        req.recoveries = 1
        # trace identity rides the entry: survivor-side spans land on the
        # ORIGINAL trace_id under the original root (None = sampled out)
        req.trace_id = entry.get("trace_id")
        req.span_root = entry.get("span_root")
        req.span_parent = entry.get("span_parent")
        return req

    def release(self, rid: int) -> Optional[ServeRequest]:
        """Detach a live request WITHOUT terminal accounting: no state
        change, no counter, no event — the request is not lost, it
        continues on another replica (the fleet router calls this after
        a successful cross-replica ``readmit``). Frees the local slot
        best-effort (the engine may already be gone). Returns the record,
        or None if unknown/terminal (nothing to release)."""
        req = self._requests.get(rid)
        if req is None or req.state in TERMINAL_STATES:
            return None
        self._requests.pop(rid)
        self._queue = [r for r in self._queue if r.rid != rid]
        if req.engine_rid is not None:
            self._running.pop(req.engine_rid, None)
            self._staged.pop(req.engine_rid, None)
            try:
                self._cb.cancel(req.engine_rid)
            except Exception:  # noqa: BLE001 — engine may be lost/poisoned
                pass
        self._recovery_log.retire(rid)
        self._update_gauges()
        return req

    def abandon(self, detail: str) -> Dict[int, ServeRequest]:
        """Mark every live request shed (reason ``engine_lost``) — the
        honest terminal outcome for work that could not be migrated off a
        dead replica. Same accounting as the in-engine terminal-failure
        path (:meth:`_fail_terminally`) but without raising: the fleet
        keeps serving on the survivors. Returns the abandoned records."""
        live = [r for r in self._requests.values()
                if r.state not in TERMINAL_STATES]
        for req in live:
            self._mark_lost(req, detail)
        self._update_gauges()
        return {r.rid: r for r in live}

    # -- internals ------------------------------------------------------
    def _shed(self, reason: str, prompt, need: int, now: float,
              excess: Optional[int] = None, no_hint: bool = False) -> Admission:
        hint = (None if no_hint
                else self._retry_after(need if excess is None else excess, now))
        if self._tele.enabled:
            self._tele.registry.counter("serve_shed_total").inc()
            event = {"event": "shed", "reason": reason,
                     "prompt_tokens": int(prompt.size), "need_tokens": need,
                     "queue_depth": len(self._queue),
                     "committed_tokens": self.committed_tokens()}
            if hint is not None:
                event["retry_after_s"] = hint
            self._tele.emit("serving_event", event)
        return Admission(status=SHED, reason=reason, retry_after_s=hint)

    def _completion_rate(self, now: float) -> Optional[float]:
        """Observed completion rate (tokens/s), or None when it is not
        yet observable — zero requests finished, or no time has elapsed
        since the first submit. Callers must treat None as "no rate",
        never divide by it."""
        if self._tokens_done <= 0 or self._t_start is None:
            return None
        elapsed = now - self._t_start
        if elapsed <= 0:
            return None
        rate = self._tokens_done / elapsed
        return rate if rate > 0 else None

    def _recovery_eta_s(self, now: float) -> float:
        """Expected seconds until the current outage ends: the last
        measured recovery time (or the configured estimate before any
        has been observed) minus the outage time already elapsed. While
        the breaker is STILL open past that estimate (the rebuilt engine
        is unproven, or recovery is slower than last time) the honest
        assumption is another full recovery cycle — the hint never decays
        to zero mid-outage. 0.0 while healthy."""
        if not self._breaker_open or self._outage_start is None:
            return 0.0
        est = (self._recovery_ms[-1] / 1000.0 if self._recovery_ms
               else self.recovery_cfg.est_recovery_s)
        est = max(est, self.recovery_cfg.backoff_s)
        remaining = est - (now - self._outage_start)
        return remaining if remaining > 0 else est

    def _retry_after(self, excess_tokens: int, now: float) -> Optional[float]:
        """Coarse backpressure hint: how long until ``excess_tokens`` of
        committed work drains at the observed completion rate, PLUS the
        expected remaining outage when the circuit breaker is open.
        Well-defined in every regime — in particular, with ZERO
        completions in the observation window (cold start, or an outage
        before anything finished) there is no rate to divide by: the
        hint is the recovery ETA alone, or None when healthy with
        nothing to extrapolate from."""
        outage = self._recovery_eta_s(now)
        rate = self._completion_rate(now)
        if rate is None:
            return round(outage, 3) if outage > 0 else None
        return round(max(1, excess_tokens) / rate + outage, 3)

    def _effective_pool_state(self) -> List[dict]:
        """pool_state() with staged handovers already subtracted, placed
        the way the engine's ``_place`` will (smallest fitting pool)."""
        pools = [dict(p) for p in self._cb.pool_state()]
        for need in self._staged.values():
            pool = next((p for p in pools
                         if p["length"] >= need and p["free"] > 0), None)
            if pool is not None:
                pool["free"] -= 1
        return pools

    def _fits_now(self, need: int) -> bool:
        return any(p["length"] >= need and p["free"] > 0
                   for p in self._effective_pool_state())

    def _handover(self, req: ServeRequest, now: float):
        if req.engine_rid is not None or req.tokens:
            # migrated resume (readmit): re-prefill prompt + everything
            # already emitted and continue at gen_base, pinning the
            # foreign engine rid — the RNG identity — so the stream is
            # bitwise the one the lost replica would have produced.
            # rid None means the request never reached the dead
            # replica's engine (still queued there): a natural rid is
            # correct, the stream starts fresh.
            full = (np.concatenate([req.prompt,
                                    np.asarray(req.tokens, np.int32)])
                    if req.tokens else req.prompt)
            req.engine_rid = self._cb.submit(
                full, req.max_new_tokens - len(req.tokens),
                rid=req.engine_rid, gen_base=len(req.tokens))
        elif req.prefix_id is not None and req.prefix_id in self._prefixes:
            # splice the registered prefix KV; only the suffix prefills
            suffix = req.prompt[self._prefixes[req.prefix_id].size:]
            req.engine_rid = self._cb.submit_with_prefix(
                self._prefix_pids[req.prefix_id], suffix, req.max_new_tokens)
        else:
            # no prefix — or it was unregistered while this request sat
            # in the queue: req.prompt already holds the FULL token
            # sequence, so pay the full prefill instead of stranding the
            # request (stream bitwise identical either way)
            req.engine_rid = self._cb.submit(req.prompt, req.max_new_tokens)
        req.state = RUNNING
        req.admit_t = now
        self._rid_watermark = max(self._rid_watermark, req.engine_rid + 1)
        self._staged[req.engine_rid] = req.need_tokens
        self._running[req.engine_rid] = req
        # spans BEFORE the recovery-log snapshot: the entry must carry
        # span_root, or a migrated re-admission would mint a second root
        # and the cross-replica timeline would fork
        self._emit_admit_spans(req, now)
        self._recovery_log.admit(req)
        self.policy.on_admit(req, now)
        if self._tele.enabled:
            self._tele.registry.counter("serve_admitted_total").inc()

    def _schedule(self, now: float):
        """Place queued requests into free slots in policy order, bounded
        by the anti-starvation aging rule: a request that has waited
        ``aging_s`` (a) moves to the head of the order, oldest first —
        so a request the policy keeps outranking (no-deadline work under
        EDF, low priority under a high-priority stream) still gets the
        next slot it fits — and (b) becomes a barrier when it does NOT
        fit: nothing ranked behind it may leapfrog (the fix for the bare
        FIFO-with-skip mode where a long request waiting for the big pool
        starves behind an endless stream of short ones)."""
        if not self._queue:
            return
        free = self._effective_pool_state()
        placed = set()
        order = self.policy.order(self._queue, now)
        aged = [r for r in order if r.waited_s(now) >= self.aging_s]
        if aged:
            aged.sort(key=lambda r: r.rid)  # oldest aged request first
            fresh = [r for r in order if r.waited_s(now) < self.aging_s]
            order = aged + fresh
        for req in order:
            pool = next((p for p in free
                         if p["length"] >= req.need_tokens and p["free"] > 0),
                        None)
            if pool is None:
                if req.waited_s(now) >= self.aging_s:
                    break  # aging barrier: nobody leapfrogs an aged request
                continue
            pool["free"] -= 1
            self._handover(req, now)
            placed.add(req.rid)
        if placed:
            self._queue = [r for r in self._queue if r.rid not in placed]

    def _expire(self, now: float):
        """Shed queued work whose deadline already blew: decoding it would
        burn slot time on a response the client stopped waiting for."""
        expired = [r for r in self._queue if now > r.deadline_at]
        if not expired:
            return
        for req in expired:
            req.state = EXPIRED
            req.finish_t = now
            if self._tele.enabled:
                self._tele.registry.counter("serve_expired_total").inc()
                self._tele.emit("serving_event", {
                    "event": "expired", "request": req.rid,
                    "queue_ms": round(req.waited_s(now) * 1000.0, 3),
                    "deadline_ms": req.deadline_ms,
                })
        self._queue = [r for r in self._queue if r.state == QUEUED]

    def _update_gauges(self):
        if not self._tele.enabled:
            return
        reg = self._tele.registry
        reg.gauge("serve_queue_depth").set(len(self._queue))
        reg.gauge("serve_committed_tokens").set(self.committed_tokens())

    # -- request-scoped tracing (docs/telemetry.md "Request tracing") ----
    def _trace_scope(self) -> str:
        """Trace-id prefix: the hub's replica tag when this engine serves
        inside a fleet (``ReplicaTelemetry``), else empty. Serving rids
        are per-replica counters, so the birth-replica prefix is what
        keeps trace ids distinct in a shared fleet trace file."""
        rep = getattr(self._tele, "replica", None)
        return f"{rep}/" if rep is not None else ""

    def _emit_admit_spans(self, req: ServeRequest, now: float):
        """Queue + admission spans at handover. The queue (root) span is
        emitted once per trace — original submit to FIRST handover, even
        when that handover happens on a survivor replica after a
        migration — and every handover adds an admission span that
        becomes the parent the request's subsequent tick-window spans
        hang off. A migrated re-admission's admission span parents on the
        router's migration span (``req.span_parent`` pre-seeded by
        ``readmit``), stitching the cross-replica bridge."""
        if req.trace_id is None or not self._spans.enabled:
            return
        if req.span_root is None:
            req.span_root = self._spans.emit(
                "queue", req.trace_id, req.submit_t, now,
                attrs={"request": req.rid, "priority": req.priority,
                       "tenant": req.tenant})
        parent = req.span_parent if req.span_parent is not None else req.span_root
        sid = self._spans.emit(
            "admission", req.trace_id, now, self._clock(), parent_id=parent,
            attrs={"engine_rid": int(req.engine_rid),
                   "gen_base": len(req.tokens),
                   "prefix": req.prefix_id is not None})
        req.span_parent = sid

    def _span_hook(self, engine_rid: int, kind: str, t0: float, t1: float,
                   attrs: Optional[dict] = None):
        """Installed as the batching engine's ``span_hook`` (only when the
        hub is live): attribute a retired tick window (prefill_chunk /
        decode_window / spec_verify_round) to the owning request's trace,
        parented on its latest admission/recovery_replay span."""
        req = self._running.get(engine_rid)
        if req is None or req.trace_id is None:
            return
        self._spans.emit(kind, req.trace_id, t0, t1,
                         parent_id=req.span_parent, attrs=attrs)

    def _event_hook(self, engine_rid: int, event: dict) -> Optional[dict]:
        """Installed as the batching engine's ``request_event_hook``:
        enrich the per-request ``inference_request`` event with the
        serving-side lifecycle fields (and retag it as ours)."""
        req = self._running.get(engine_rid)
        if req is None:
            return None  # a direct engine.submit request: leave it alone
        return self._enrich_event(req, event)

    def _enrich_event(self, req: ServeRequest, event: dict) -> dict:
        """The enrichment body, callable with the request in hand —
        `_finish_recovered` uses this directly so it never has to
        transiently register the request in the live `_running` table
        (an off-lock write a concurrent scrape could observe)."""
        now = self._clock()
        event["path"] = "serving"
        event["request"] = req.rid
        q = req.queue_ms()
        if q is not None:
            event["queue_ms"] = round(q, 3)
        # finishing tick: first_token_t for a one-tick request is not
        # recorded yet, so fall back to "now" (same tick that emitted it)
        ttft = req.ttft_ms()
        event["ttft_ms"] = round(
            ttft if ttft is not None else (now - req.submit_t) * 1000.0, 3)
        event["priority"] = req.priority
        event["tenant"] = req.tenant
        if req.trace_id is not None:
            # joins the request summary to its span timeline: slo_blame
            # and ds_trace_report --request pivot on this
            event["trace_id"] = req.trace_id
        if req.recoveries:
            # the rebuilt engine only generated the post-outage remainder;
            # the client's stream is the full accumulated one — report
            # THAT, and flag the request so SLO analysis can segment
            event["new_tokens"] = len(req.tokens)
            event["recoveries"] = req.recoveries
        if req.deadline_ms is not None:
            # this is the request's single SLO verdict: the counters and
            # loadgen records reuse it rather than re-reading the clock
            req.deadline_met = bool(now <= req.deadline_at)
            event["deadline_ms"] = req.deadline_ms
            event["deadline_met"] = req.deadline_met
        return event
