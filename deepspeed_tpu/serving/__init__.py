"""SLO-aware request serving over continuous batching: admission control
with explicit backpressure, pluggable scheduler policies (FIFO /
priority / EDF / fair share) with anti-starvation aging, request
lifecycle (cancel, stream, deadline shedding), fault injection +
preemption-safe recovery (serving/faults.py, serving/recovery.py), and
the load-test harness behind ``tools/ds_loadgen.py``. See
docs/serving.md."""

from deepspeed_tpu.serving.engine import ServingEngine, TokenStream
from deepspeed_tpu.serving.fleet import (
    RID_STRIDE,
    Replica,
    ReplicaTelemetry,
    attach_replica_telemetry,
)
from deepspeed_tpu.serving.router import FleetRouter, FleetStream
from deepspeed_tpu.serving.autoscaler import AutoscalerConfig, FleetAutoscaler
from deepspeed_tpu.serving.scenarios import (
    ChaosAction,
    Scenario,
    TenantMix,
    builtin_matrix,
    scenario_scorecard,
)
from deepspeed_tpu.serving.faults import (
    EnginePreempted,
    Fault,
    FaultInjector,
    FaultPlan,
    FetchHang,
    InjectedFault,
    TickDispatchError,
)
from deepspeed_tpu.serving.policies import (
    EdfPolicy,
    FairSharePolicy,
    FifoPolicy,
    PriorityPolicy,
    SchedulerPolicy,
    resolve_policy,
)
from deepspeed_tpu.serving.recovery import (
    RecoveryConfig,
    RecoveryFailed,
    RecoveryLog,
)
from deepspeed_tpu.serving.request import (
    ADMITTED,
    CANCELLED,
    EXPIRED,
    FINISHED,
    QUEUED,
    QUEUED_STATUS,
    RUNNING,
    SHED,
    TERMINAL_STATES,
    Admission,
    ServeRequest,
)

__all__ = [
    "ServingEngine", "TokenStream",
    "FleetRouter", "FleetStream", "Replica", "ReplicaTelemetry",
    "attach_replica_telemetry", "RID_STRIDE",
    "AutoscalerConfig", "FleetAutoscaler",
    "Scenario", "TenantMix", "ChaosAction", "builtin_matrix",
    "scenario_scorecard",
    "SchedulerPolicy", "FifoPolicy", "PriorityPolicy", "EdfPolicy",
    "FairSharePolicy", "resolve_policy",
    "Admission", "ServeRequest",
    "Fault", "FaultPlan", "FaultInjector",
    "InjectedFault", "TickDispatchError", "FetchHang", "EnginePreempted",
    "RecoveryConfig", "RecoveryFailed", "RecoveryLog",
    "ADMITTED", "QUEUED_STATUS", "SHED",
    "QUEUED", "RUNNING", "FINISHED", "CANCELLED", "EXPIRED",
    "TERMINAL_STATES",
]
