"""Preemption-safe request recovery: the jax-free state the serving
layer needs to rebuild an engine mid-run.

The insight that makes recovery *bitwise testable* (docs/serving.md
"Fault tolerance"): every sampled token draws from
``fold_in(fold_in(base_key, rid), token_index)`` on device
(decoding.request_keys), so a request rebuilt on a FRESH engine by
re-prefilling ``prompt + emitted_tokens`` and resuming at
``gen_base = len(emitted)`` with the same engine rid continues with the
exact token stream the fault-free run would have produced. The
:class:`RecoveryLog` holds everything that resume needs — plain host
data, no jax arrays, JSONL-serializable so a later fleet layer can
recover across processes:

- per running request: prompt ids, emitted tokens, remaining quota,
  tenant / priority / deadline, the engine rid (the RNG identity), and
  the serving-level prefix id if admission spliced one.

:class:`RecoveryConfig` is the watchdog/retry/rebuild knob block the
:class:`~deepspeed_tpu.serving.engine.ServingEngine` reads;
:class:`RecoveryFailed` is the terminal error ``run()`` surfaces when
every escalation level (retry -> rebuild -> degraded-mesh rebuild) is
exhausted.
"""

import json
from dataclasses import dataclass
from typing import Dict, List, Optional


class RecoveryFailed(RuntimeError):
    """Terminal serving failure: the tick failed, retries were exhausted,
    and no engine rebuild (at any degradation level) succeeded. Every
    in-flight request has been marked shed before this is raised — no
    request is silently lost."""


@dataclass
class RecoveryConfig:
    """Watchdog + recovery knobs (``ServingEngine(recovery=...)``).

    - ``fetch_timeout_s``: per-tick watchdog on the engine's packed-result
      fetch (``ContinuousBatchingEngine.fetch_timeout_s``); a fetch
      exceeding it poisons the engine and triggers a rebuild. None = off.
    - ``max_tick_retries``: bounded retry-with-backoff budget for a
      CLEAN tick failure (raised before the engine mutated state);
      exhausting it — or any poisoned/preemption failure — escalates to
      engine rebuild.
    - ``backoff_s``: base retry backoff, doubled per attempt.
    - ``max_rebuilds``: total engine rebuilds allowed for the serving
      engine's lifetime before recovery is declared failed.
    - ``est_recovery_s``: the ``retry_after_s`` hint for shed-while-
      recovering admissions before any rebuild has been observed (after
      one, the last measured recovery time is used instead).
    """

    fetch_timeout_s: Optional[float] = None
    max_tick_retries: int = 2
    backoff_s: float = 0.05
    max_rebuilds: int = 8
    est_recovery_s: float = 1.0

    def __post_init__(self):
        if self.max_tick_retries < 0:
            raise ValueError("max_tick_retries must be >= 0")
        if self.backoff_s < 0:
            raise ValueError("backoff_s must be >= 0")
        if self.max_rebuilds < 1:
            raise ValueError("max_rebuilds must be >= 1")
        if self.fetch_timeout_s is not None and self.fetch_timeout_s <= 0:
            raise ValueError("fetch_timeout_s must be > 0 (None = off)")

    @classmethod
    def parse(cls, spec) -> "RecoveryConfig":
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(**spec)
        raise TypeError(f"recovery must be a RecoveryConfig or dict, "
                        f"got {type(spec).__name__}")


def snapshot_request(req) -> dict:
    """One request as a plain-data recovery entry (``ServeRequest``
    shape: rid/engine_rid/prompt/tokens/max_new_tokens/priority/tenant/
    deadline_ms/prefix_id). ``engine_rid`` may be None for a request
    that never reached an engine (still queued host-side) — the fleet
    router snapshots those too when it migrates a dead replica's work,
    and re-admission simply assigns a natural rid."""
    return {
        "rid": int(req.rid),
        "engine_rid": (int(req.engine_rid)
                       if req.engine_rid is not None else None),
        "prompt": [int(t) for t in req.prompt],
        "emitted": [int(t) for t in req.tokens],
        "max_new_tokens": int(req.max_new_tokens),
        "priority": int(req.priority),
        "tenant": str(req.tenant),
        "deadline_ms": (float(req.deadline_ms)
                        if req.deadline_ms is not None else None),
        "submit_t": float(req.submit_t),
        "prefix_id": (int(req.prefix_id)
                      if req.prefix_id is not None else None),
        # request-scoped tracing identity (telemetry/spans.py): carried in
        # the entry so a migrated request's survivor-side spans land on the
        # SAME trace_id and stitch under the same root — one timeline
        # across engine generations and replicas. All None when the
        # request was sampled out (no spans anywhere).
        "trace_id": (str(req.trace_id)
                     if getattr(req, "trace_id", None) is not None else None),
        "span_root": (str(req.span_root)
                      if getattr(req, "span_root", None) is not None else None),
        "span_parent": (str(req.span_parent)
                        if getattr(req, "span_parent", None) is not None
                        else None),
    }


class RecoveryLog:
    """Scheduler-visible snapshots of every RUNNING request, keyed by
    serving rid — exactly what engine loss would otherwise destroy.
    Queued requests need no entry (they live host-side in the serving
    queue and survive an engine loss untouched).

    Entries are plain dicts (ints/strs/lists only) so ``snapshot()`` /
    ``to_jsonl()`` round-trip without jax or numpy."""

    def __init__(self):
        self._entries: Dict[int, dict] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, rid: int) -> bool:
        return rid in self._entries

    def admit(self, req):
        """Record a request at engine handover (see
        :func:`snapshot_request` for the entry shape)."""
        self._entries[req.rid] = snapshot_request(req)

    def extend(self, rid: int, tokens: List[int]):
        """Append tokens that surfaced for ``rid`` this tick (no-op for
        requests the log does not track — direct engine submitters)."""
        entry = self._entries.get(rid)
        if entry is not None and tokens:
            entry["emitted"].extend(int(t) for t in tokens)

    def retire(self, rid: int):
        """Drop a request that reached a terminal state (finished,
        cancelled, shed): nothing left to recover."""
        self._entries.pop(rid, None)

    def entries(self) -> List[dict]:
        """Live entries in deterministic re-admission order (by engine
        rid — the submission order of the lost engine; queued-request
        entries with no engine rid sort last, by serving rid)."""
        return sorted(self._entries.values(),
                      key=lambda e: ((0, e["engine_rid"])
                                     if e["engine_rid"] is not None
                                     else (1, e["rid"])))

    def snapshot(self) -> List[dict]:
        """Deep-copied plain-data view (safe to serialize/mutate)."""
        return [json.loads(json.dumps(e)) for e in self.entries()]

    def clear(self):
        self._entries.clear()

    def to_jsonl(self, path: str):
        """Durable form: one entry per line, the cross-process recovery
        seed a fleet router would replay onto a replacement replica."""
        with open(path, "w") as fh:
            for entry in self.entries():
                fh.write(json.dumps(entry) + "\n")

    @classmethod
    def from_jsonl(cls, path: str) -> "RecoveryLog":
        log = cls()
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                entry = json.loads(line)
                log._entries[int(entry["rid"])] = entry
        return log
