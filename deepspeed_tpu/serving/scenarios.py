"""Declarative serving scenarios: production-shaped load as one
replayable artifact (docs/serving.md "Autoscaling & scenarios").

A scenario composes the three things a serving stack is actually judged
under — an arrival-rate curve (diurnal sinusoid, step change, burst
train), a tenant/priority/deadline mix (interactive vs batch backfill,
long-doc RAG prompt ranges, shared-prefix tenants), and an embedded
chaos schedule (replica kill/restore/rolling-restart riding the
FleetRouter's replayable ``at_tick`` hooks) — into ONE seeded JSONL
file, dump/loadable exactly like the fault plans (``faults.FaultPlan``):
one record per line, fully determined by the header's seed, so
``ds_loadgen --scenario diurnal.jsonl`` replays the same 10k-request
run anyone else got from the same file.

Like the router under it, this module is jax-free: compiling a scenario
is pure host bookkeeping (stdlib ``random`` + the loadgen arrival
generators), tested in milliseconds in the pre-tier-1 jax-free CI stage.

Record shapes (JSONL, ``record`` discriminated):

- ``{"record": "scenario", "name", "seed", "requests", "rate",
  "curve", "process", "burst_size", "vocab"}`` — the header (exactly
  one, first line). ``curve`` is a ``--rate-curve`` spec
  (``diurnal:PERIOD:PEAK`` / ``step:T:RATE`` / ``burst_train:GAP:SIZE``)
  or null for a flat-rate ``process`` schedule.
- ``{"record": "mix", "tenant", "weight", "prompt_range", "new_range",
  "priority", "deadline_ms", "shared_prefix"}`` — one tenant class.
  ``deadline_ms`` null marks no-SLO batch backfill (what the degrade
  ladder sheds first); ``shared_prefix`` > 0 gives every request of the
  tenant the same seeded prompt prefix (the prefix-cache shape).
- ``{"record": "chaos", "tick", "action"}`` — ``kill`` (lowest-slot
  healthy replica), ``restore`` (factory-add a fresh replica), or
  ``rolling_restart``, at 1-based router tick ``tick``.
"""

import json
import random
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from deepspeed_tpu.serving.loadgen import gen_arrivals, gen_curve_arrivals

CHAOS_ACTIONS = ("kill", "restore", "rolling_restart")


@dataclass
class TenantMix:
    """One tenant class in a scenario's request mix."""

    tenant: str = "default"
    weight: float = 1.0
    prompt_range: Tuple[int, int] = (4, 16)
    new_range: Tuple[int, int] = (4, 16)
    priority: int = 0
    deadline_ms: Optional[float] = None   # None = no-SLO batch backfill
    shared_prefix: int = 0                # shared prompt-prefix tokens

    def __post_init__(self):
        self.prompt_range = (int(self.prompt_range[0]),
                             int(self.prompt_range[1]))
        self.new_range = (int(self.new_range[0]), int(self.new_range[1]))
        if self.weight <= 0:
            raise ValueError(f"mix {self.tenant!r}: weight must be > 0")
        for lo, hi, what in (self.prompt_range + ("prompt_range",),
                             self.new_range + ("new_range",)):
            if lo < 1 or hi < lo:
                raise ValueError(f"mix {self.tenant!r}: bad {what} "
                                 f"({lo}, {hi})")
        if self.shared_prefix < 0:
            raise ValueError(f"mix {self.tenant!r}: shared_prefix < 0")

    def to_record(self) -> dict:
        return {"record": "mix", "tenant": self.tenant,
                "weight": self.weight,
                "prompt_range": list(self.prompt_range),
                "new_range": list(self.new_range),
                "priority": self.priority, "deadline_ms": self.deadline_ms,
                "shared_prefix": self.shared_prefix}


@dataclass
class ChaosAction:
    """One replica-level chaos step, scheduled on a router tick."""

    tick: int
    action: str

    def __post_init__(self):
        self.tick = int(self.tick)
        if self.tick < 1:
            raise ValueError(f"chaos tick must be >= 1 (got {self.tick})")
        if self.action not in CHAOS_ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} "
                             f"(choose from {CHAOS_ACTIONS})")

    def to_record(self) -> dict:
        return {"record": "chaos", "tick": self.tick, "action": self.action}


def _kill_lowest_healthy(router):
    """The replayable chaos kill: the lowest-slot healthy replica dies
    abruptly (same victim rule as ``ds_loadgen --kill-replica``)."""
    for rid in router.replica_ids():
        if router.statusz()["replicas"][rid]["state"] == "healthy":
            router.kill(rid, detail="scenario chaos kill")
            return


@dataclass
class Scenario:
    """A named, seeded, replayable serving scenario."""

    name: str
    seed: int = 0
    requests: int = 64
    rate: float = 8.0
    curve: Optional[str] = None     # a --rate-curve spec, or None
    process: str = "poisson"        # flat-rate process when curve is None
    burst_size: int = 8
    vocab: int = 128                # id range for explicit (prefix) prompts
    mixes: List[TenantMix] = field(default_factory=list)
    chaos: List[ChaosAction] = field(default_factory=list)

    def __post_init__(self):
        if not self.name:
            raise ValueError("scenario needs a name")
        if self.requests < 1:
            raise ValueError("requests must be >= 1")
        if self.rate <= 0:
            raise ValueError("rate must be > 0 req/s")
        self.chaos = sorted(self.chaos, key=lambda c: c.tick)

    # -- compilation ---------------------------------------------------
    def arrivals(self) -> List[float]:
        """The arrival schedule, delegated to the loadgen generators the
        CLI exposes standalone (``--rate-curve`` / ``--process``)."""
        if self.curve is not None:
            return gen_curve_arrivals(self.requests, self.rate, self.curve,
                                      seed=self.seed, process=self.process)
        return gen_arrivals(self.requests, self.rate, self.process,
                            seed=self.seed, burst_size=self.burst_size)

    def workload(self) -> List[dict]:
        """The request mix as loadgen workload items, fully determined by
        the header seed: per-request tenant class by weighted draw, then
        prompt/output lengths uniform in the class ranges. Shared-prefix
        tenants get explicit prompt ids — one seeded prefix per tenant,
        fresh suffix per request — so the prefix cache sees real reuse."""
        mixes = self.mixes or [TenantMix()]
        rng = random.Random(self.seed)
        weights = [m.weight for m in mixes]
        prefixes = {}
        out = []
        for _ in range(self.requests):
            m = rng.choices(mixes, weights=weights)[0]
            plen = rng.randint(*m.prompt_range)
            item = {"max_new_tokens": rng.randint(*m.new_range),
                    "tenant": m.tenant, "priority": int(m.priority)}
            if m.shared_prefix > 0:
                if m.tenant not in prefixes:
                    prefixes[m.tenant] = [rng.randrange(self.vocab)
                                          for _ in range(m.shared_prefix)]
                suffix = [rng.randrange(self.vocab)
                          for _ in range(max(1, plen - m.shared_prefix))]
                item["prompt"] = prefixes[m.tenant] + suffix
            else:
                item["prompt_tokens"] = plen
            if m.deadline_ms is not None:
                item["deadline_ms"] = float(m.deadline_ms)
            out.append(item)
        return out

    def compile(self) -> Tuple[List[dict], List[float]]:
        """``(workload, arrivals)`` ready for ``loadgen.run_load``."""
        return self.workload(), self.arrivals()

    def arm(self, router) -> int:
        """Register the chaos schedule on a FleetRouter's replayable
        ``at_tick`` hooks and journal the scenario marker (the
        ``fleet_scale`` event ``ds_trace_report --serve`` keys its
        per-scenario section on). Returns the number of chaos actions
        armed."""
        for act in self.chaos:
            if act.action == "kill":
                router.at_tick(act.tick, _kill_lowest_healthy)
            elif act.action == "restore":
                router.at_tick(act.tick, lambda r: r.add())
            else:
                router.at_tick(act.tick, lambda r: r.rolling_restart())
        tele = router.telemetry
        if tele is not None and tele.enabled:
            tele.emit("fleet_scale", {
                "event": "scenario", "scenario": self.name,
                "requests": self.requests, "seed": self.seed})
        return len(self.chaos)

    def without_chaos(self) -> "Scenario":
        """The quiet twin: identical workload + arrivals, no chaos — the
        baseline the bitwise-parity check compares migrated streams
        against."""
        return Scenario(name=f"{self.name}~quiet", seed=self.seed,
                        requests=self.requests, rate=self.rate,
                        curve=self.curve, process=self.process,
                        burst_size=self.burst_size, vocab=self.vocab,
                        mixes=list(self.mixes), chaos=[])

    # -- persistence (FaultPlan-style JSONL) ---------------------------
    def dump(self, path: str):
        with open(path, "w") as fh:
            fh.write(json.dumps({
                "record": "scenario", "name": self.name, "seed": self.seed,
                "requests": self.requests, "rate": self.rate,
                "curve": self.curve, "process": self.process,
                "burst_size": self.burst_size, "vocab": self.vocab}) + "\n")
            for m in self.mixes:
                fh.write(json.dumps(m.to_record()) + "\n")
            for c in self.chaos:
                fh.write(json.dumps(c.to_record()) + "\n")

    @classmethod
    def load(cls, path: str) -> "Scenario":
        header, mixes, chaos = None, [], []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.pop("record", None)
                if kind == "scenario":
                    if header is not None:
                        raise ValueError(f"{path}: duplicate scenario header")
                    header = rec
                elif kind == "mix":
                    mixes.append(TenantMix(
                        tenant=rec["tenant"], weight=rec.get("weight", 1.0),
                        prompt_range=tuple(rec.get("prompt_range", (4, 16))),
                        new_range=tuple(rec.get("new_range", (4, 16))),
                        priority=rec.get("priority", 0),
                        deadline_ms=rec.get("deadline_ms"),
                        shared_prefix=rec.get("shared_prefix", 0)))
                elif kind == "chaos":
                    chaos.append(ChaosAction(tick=rec["tick"],
                                             action=rec["action"]))
                else:
                    raise ValueError(f"{path}: unknown record {kind!r}")
        if header is None:
            raise ValueError(f"no scenario header in {path}")
        return cls(name=header["name"], seed=header.get("seed", 0),
                   requests=header.get("requests", 64),
                   rate=header.get("rate", 8.0), curve=header.get("curve"),
                   process=header.get("process", "poisson"),
                   burst_size=header.get("burst_size", 8),
                   vocab=header.get("vocab", 128),
                   mixes=mixes, chaos=chaos)


def scenario_scorecard(scenario: Scenario, summary: dict) -> dict:
    """The per-scenario SLO verdict over one run's loadgen summary: the
    numbers the acceptance criteria compare fleets on, tagged with the
    scenario identity so a matrix of runs stays self-describing."""
    fleet = summary.get("fleet") or {}
    return {
        "scenario": scenario.name,
        "seed": scenario.seed,
        "requests": scenario.requests,
        "curve": scenario.curve,
        "chaos_actions": len(scenario.chaos),
        "goodput_tok_s": summary.get("goodput_tok_s"),
        "throughput_tok_s": summary.get("throughput_tok_s"),
        "shed_rate": summary.get("shed_rate"),
        "deadline_met_frac": summary.get("deadline_met_frac"),
        "lost": fleet.get("lost"),
        "replica_deaths": fleet.get("replica_deaths"),
        "conservation_ok": fleet.get("conservation_ok"),
    }


def builtin_matrix() -> List[Scenario]:
    """The checked-in scenario matrix (``scenarios/*.jsonl`` is this
    list dumped; ``tools/ci_scenario_smoke.py`` keeps the two in sync).
    Every entry is production-shaped: mixed SLO tenants over a hostile
    rate curve, three of them with embedded replica chaos."""
    interactive = TenantMix(tenant="interactive", weight=0.6,
                            prompt_range=(4, 12), new_range=(6, 12),
                            priority=1, deadline_ms=1500.0)
    backfill = TenantMix(tenant="backfill", weight=0.4,
                         prompt_range=(8, 24), new_range=(8, 16),
                         priority=0, deadline_ms=None)
    return [
        Scenario(
            name="diurnal_interactive", seed=13, requests=120, rate=3.0,
            curve="diurnal:8:20",
            mixes=[interactive, backfill]),
        Scenario(
            name="burst_frontend", seed=13, requests=96, rate=8.0,
            curve="burst_train:1.5:16",
            mixes=[TenantMix(tenant="frontend", weight=0.7,
                             prompt_range=(4, 10), new_range=(4, 10),
                             priority=1, deadline_ms=1200.0),
                   backfill]),
        Scenario(
            name="step_rampup", seed=13, requests=96, rate=4.0,
            curve="step:4:18",
            mixes=[interactive, backfill]),
        Scenario(
            name="ragdoc_longprompts", seed=13, requests=48, rate=4.0,
            curve="diurnal:8:10",
            mixes=[TenantMix(tenant="rag", weight=0.5,
                             prompt_range=(24, 40), new_range=(8, 16),
                             priority=1, deadline_ms=3000.0,
                             shared_prefix=16),
                   interactive]),
        Scenario(
            name="multi_tenant_fairshare", seed=13, requests=96, rate=10.0,
            mixes=[TenantMix(tenant=f"tenant{i}", weight=w,
                             prompt_range=(4, 12), new_range=(4, 12),
                             priority=p, deadline_ms=d)
                   for i, (w, p, d) in enumerate(
                       [(0.4, 2, 900.0), (0.3, 1, 1800.0),
                        (0.2, 0, None), (0.1, 0, None)])]),
        Scenario(
            name="kill_during_peak", seed=13, requests=120, rate=3.0,
            curve="diurnal:8:20",
            mixes=[interactive, backfill],
            chaos=[ChaosAction(tick=80, action="kill"),
                   ChaosAction(tick=140, action="restore")]),
        Scenario(
            name="rolling_under_load", seed=13, requests=96, rate=8.0,
            mixes=[interactive, backfill],
            chaos=[ChaosAction(tick=30, action="rolling_restart")]),
    ]


def write_matrix(dirpath: str) -> List[str]:
    """Dump the builtin matrix into ``dirpath`` as one JSONL per
    scenario; returns the written paths (regeneration entry point:
    ``python -m deepspeed_tpu.serving.scenarios scenarios/``)."""
    import os

    paths = []
    for sc in builtin_matrix():
        path = os.path.join(dirpath, f"{sc.name}.jsonl")
        sc.dump(path)
        paths.append(path)
    return paths


if __name__ == "__main__":  # pragma: no cover - regeneration helper
    import sys

    out_dir = sys.argv[1] if len(sys.argv) > 1 else "scenarios"
    for p in write_matrix(out_dir):
        print(p)
