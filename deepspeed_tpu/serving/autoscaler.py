"""Fleet autoscaler: the closed loop that keeps SLOs under sustained
overload (docs/serving.md "Autoscaling & scenarios").

A :class:`FleetAutoscaler` attaches to a :class:`FleetRouter` via
``router.on_step`` and, once per fleet tick, reads the router's own
health-plane signals — queue depth, recent shed count, committed-token
occupancy against the KV budgets, breaker state — and drives exactly one
of three actuators:

- **scale out** through the replica factory (``router.add()``:
  add-then-warm, the same primitive rolling restart uses), never above
  ``max_replicas``;
- **scale in** through graceful drain (``router.drain()``), never below
  ``min_replicas``, and only on the replica the residue-aware
  ``router.scale_in_candidate()`` deems safe — a replica holding the
  only copy of a recovering request's RecoveryLog residue is never
  picked;
- when scale-out is capped, the **degradation ladder**: (1) tighten
  every replica's admission ``kv_budget_tokens``, (2) cap
  ``max_new_tokens`` for no-SLO tenants, (3) shed batch backfill before
  interactive. Entry and exit walk the same rungs in opposite order, so
  recovery is symmetric.

Hysteresis is structural, not tuned: every decision (including a
skipped scale-in) starts a ``cooldown_s`` window in which no further
decision fires, and scale-in/undegrade additionally require
``down_stable_ticks`` consecutive underloaded ticks — a diurnal curve
breathes 1→4→1 without thrash, a sawtooth gets at most one decision per
cooldown window (proved in tests/unit/serving/test_autoscaler.py).

Every transition is journaled as a ``fleet_scale`` trace event (see
docs/telemetry.md) plus counters/gauges: ``fleet_scale_up_total``,
``fleet_scale_down_total``, ``fleet_degrade_level`` alongside the
router's ``fleet_replicas``. Jax-free, like everything else at this
layer.
"""

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class AutoscalerConfig:
    """Policy knobs. The defaults suit the loadgen scenarios: scale out
    eagerly (queue or shed pressure), scale in lazily (sustained calm)."""

    min_replicas: int = 1
    max_replicas: int = 4
    cooldown_s: float = 2.0          # min wall-clock between decisions
    up_queue_depth: float = 4.0      # avg queued reqs/replica => overload
    up_occupancy: float = 0.85       # committed/budget => overload
    up_shed: int = 1                 # sheds in window => overload
    down_occupancy: float = 0.30     # occupancy below => underload
    down_stable_ticks: int = 8       # consecutive calm ticks before down
    shed_window_ticks: int = 16      # window for "recent" sheds
    degrade_kv_frac: float = 0.5     # rung 1: budget tightening factor
    degrade_new_tokens_cap: int = 16  # rung 2: no-SLO output cap
    max_degrade_level: int = 3

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if not 0 < self.degrade_kv_frac <= 1:
            raise ValueError("degrade_kv_frac must be in (0, 1]")
        if not 0 <= self.max_degrade_level <= 3:
            raise ValueError("max_degrade_level must be in [0, 3]")


class FleetAutoscaler:
    """The policy loop. Construct it over a live router and it runs
    itself from ``router.step()`` — no thread, no timer: decisions land
    on the main thread where the trace writer lives."""

    def __init__(self, router, config: Optional[AutoscalerConfig] = None,
                 *, clock=None):
        self._router = router
        self.config = config or AutoscalerConfig()
        self._clock = clock if clock is not None else time.monotonic
        self._last_decision_t = float("-inf")
        self._down_streak = 0
        self._shed_hist = deque()        # (tick, cumulative fleet sheds)
        self._orig_kv: Dict[str, Optional[int]] = {}
        self.degrade_level = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_down_skips = 0
        self._ticks = 0
        self._replica_ticks = 0
        router.on_step(self._on_step)
        self._gauge("fleet_degrade_level", 0)
        self._emit({"event": "autoscaler",
                    "min_replicas": self.config.min_replicas,
                    "max_replicas": self.config.max_replicas,
                    "cooldown_s": self.config.cooldown_s,
                    "replicas": router.statusz()["placeable"]})

    # -- the policy tick ------------------------------------------------
    def _on_step(self, router):
        cfg = self.config
        st = router.statusz()
        tick = int(st["tick"])
        placeable = int(st["placeable"])
        self._ticks += 1
        self._replica_ticks += placeable

        queue_total, committed, budget, breakers = 0, 0, 0, 0
        for info in st["replicas"].values():
            es = info.get("statusz")
            if not es:
                continue
            queue_total += int(es.get("queue_depth", 0))
            committed += int(es.get("committed_kv_tokens", 0))
            b = es.get("kv_budget_tokens")
            if b:
                budget += int(b)
            breakers += 1 if es.get("breaker_open") else 0
        occupancy = committed / budget if budget else 0.0
        avg_queue = queue_total / max(1, placeable)

        self._shed_hist.append((tick, int(st["shed"])))
        while (len(self._shed_hist) > 1
               and self._shed_hist[0][0] < tick - cfg.shed_window_ticks):
            self._shed_hist.popleft()
        shed_recent = int(st["shed"]) - self._shed_hist[0][1]

        if self.degrade_level >= 1:
            self._tighten_budgets(router)  # covers replicas added later

        overload = (avg_queue >= cfg.up_queue_depth
                    or occupancy >= cfg.up_occupancy
                    or shed_recent >= cfg.up_shed
                    or breakers > 0)
        underload = (not overload and shed_recent == 0
                     and queue_total == 0
                     and occupancy <= cfg.down_occupancy)
        self._down_streak = self._down_streak + 1 if underload else 0

        now = self._clock()
        if now - self._last_decision_t < cfg.cooldown_s:
            return
        ctx = {"queue_depth": queue_total, "shed_recent": shed_recent,
               "committed_frac": round(occupancy, 4),
               "breakers_open": breakers, "tick": tick}

        if overload:
            if placeable < cfg.max_replicas:
                rid = router.add()
                # rescue the trapped backlog: placement is at submit
                # time, so the queue that TRIGGERED this scale-out sits
                # on the old replicas — spread it onto the new one
                rebalanced = router.rebalance_queued()
                self.scale_ups += 1
                self._counter("fleet_scale_up_total")
                self._emit({"event": "scale_up", "replica": rid,
                            "replicas": placeable + 1,
                            "rebalanced": rebalanced, **ctx})
            elif self.degrade_level < cfg.max_degrade_level:
                self._set_degrade(router, self.degrade_level + 1,
                                  "scale_out_capped", ctx)
            else:
                return  # fully degraded at max scale: nothing left to do
            self._last_decision_t = now
        elif underload and self._down_streak >= cfg.down_stable_ticks:
            if self.degrade_level > 0:
                self._set_degrade(router, self.degrade_level - 1,
                                  "load_subsided", ctx)
            elif placeable > cfg.min_replicas:
                cand = router.scale_in_candidate()
                if cand is None:
                    self.scale_down_skips += 1
                    self._emit({"event": "scale_down_skipped",
                                "reason": "residue", **ctx})
                else:
                    router.drain(cand)
                    self.scale_downs += 1
                    self._counter("fleet_scale_down_total")
                    self._emit({"event": "scale_down", "replica": cand,
                                "replicas": placeable - 1, **ctx})
            else:
                return  # already at the floor, fully undegraded
            self._last_decision_t = now
            self._down_streak = 0

    # -- the degradation ladder -----------------------------------------
    def _set_degrade(self, router, level: int, reason: str, ctx: dict):
        """Walk the ladder one rung: 1 = tighten kv budgets, 2 = cap
        no-SLO output length, 3 = shed batch backfill. Exit reverses the
        same rung — entry/exit are symmetric by construction."""
        prev, self.degrade_level = self.degrade_level, level
        if level >= 1 and prev < 1:
            self._tighten_budgets(router)
        elif level < 1 <= prev:
            self._restore_budgets(router)
        if level >= 2 and prev < 2:
            router.cap_new_tokens_no_slo = self.config.degrade_new_tokens_cap
        elif level < 2 <= prev:
            router.cap_new_tokens_no_slo = None
        if level >= 3 and prev < 3:
            router.shed_backfill = True
        elif level < 3 <= prev:
            router.shed_backfill = False
        self._gauge("fleet_degrade_level", level)
        self._emit({"event": "degrade", "from_level": prev,
                    "to_level": level, "reason": reason, **ctx})

    def _tighten_budgets(self, router):
        for rid, eng in router.steppable_engines():
            if rid in self._orig_kv:
                continue
            orig = eng.kv_budget_tokens
            self._orig_kv[rid] = orig
            if orig is not None:
                eng.kv_budget_tokens = max(
                    1, int(orig * self.config.degrade_kv_frac))

    def _restore_budgets(self, router):
        engines = dict(router.steppable_engines())
        for rid, orig in self._orig_kv.items():
            eng = engines.get(rid)
            if eng is not None and orig is not None:
                eng.kv_budget_tokens = orig
        self._orig_kv.clear()

    # -- reporting -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "scale_down_skips": self.scale_down_skips,
            "degrade_level": self.degrade_level,
            "mean_replicas": (round(self._replica_ticks / self._ticks, 3)
                              if self._ticks else None),
        }

    def _emit(self, payload: dict):
        tele = self._router.telemetry
        if tele is not None and tele.enabled:
            tele.emit("fleet_scale", payload)

    def _counter(self, name: str, n: float = 1.0):
        tele = self._router.telemetry
        if tele is not None and tele.enabled:
            tele.registry.counter(name).inc(n)

    def _gauge(self, name: str, value: float):
        tele = self._router.telemetry
        if tele is not None and tele.enabled:
            tele.registry.gauge(name).set(value)
