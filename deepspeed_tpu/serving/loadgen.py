"""Open-loop load generator + trace-replay harness for the serving layer
(the engine room of ``tools/ds_loadgen.py``).

Open-loop means arrivals follow a schedule that does NOT wait for the
server — the regime that exposes tail latency and shedding (a closed
loop self-throttles and hides both; see the Gemma-on-TPU serving writeup
in PAPERS.md). The harness:

1. generates (or replays) a workload: arrival times from a Poisson /
   uniform / bursty process plus per-request prompt/output-length,
   priority, tenant, and deadline mixes;
2. drives a :class:`ServingEngine` in-process — submit when due, step
   while there is work;
3. reports what serving stacks are judged on: TTFT / TBT / queue-wait
   percentiles, goodput vs offered load, and shed rate.

With ``telemetry.trace_file`` set on the wrapped engine, the run also
leaves a JSONL trace that ``tools/ds_trace_report.py --serve``
summarizes — the same numbers computed from the event stream instead of
in-process records.

Workload items are plain dicts (JSONL-serializable for replay):
``{"arrival_s", "prompt_tokens" | "prompt", "max_new_tokens",
"priority", "tenant", "deadline_ms"}`` — ``prompt`` is explicit token
ids (recorded mixes); ``prompt_tokens`` a length the harness fills with
deterministic synthetic ids.
"""

import argparse
import json
import random
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.serving.faults import FaultInjector, FaultPlan
from deepspeed_tpu.serving.recovery import RecoveryConfig
from deepspeed_tpu.telemetry.registry import percentile

_PROCESSES = ("poisson", "uniform", "burst")


# -- workload synthesis ------------------------------------------------
def gen_arrivals(n: int, rate: float, process: str = "poisson",
                 seed: int = 0, burst_size: int = 8) -> List[float]:
    """``n`` arrival offsets (seconds, ascending) at ``rate`` req/s.

    poisson: exponential inter-arrivals — the memoryless open-loop
    baseline. uniform: fixed spacing (the gentlest schedule at a given
    rate). burst: groups of ``burst_size`` arriving together, bursts
    spaced to preserve the average rate — the admission-control stressor.
    """
    if n < 1:
        raise ValueError("n must be >= 1")
    if rate <= 0:
        raise ValueError("rate must be > 0 req/s")
    if process not in _PROCESSES:
        raise ValueError(f"unknown arrival process {process!r} "
                         f"(choose from {_PROCESSES})")
    rng = random.Random(seed)
    out, t = [], 0.0
    if process == "poisson":
        for _ in range(n):
            t += rng.expovariate(rate)
            out.append(t)
    elif process == "uniform":
        out = [i / rate for i in range(n)]
    else:  # burst
        if burst_size < 1:
            raise ValueError("burst_size must be >= 1")
        while len(out) < n:
            out.extend([t] * min(burst_size, n - len(out)))
            t += burst_size / rate
    return out


def synth_workload(n: int, seed: int = 0, prompt_range=(4, 16),
                   new_range=(4, 16), tenants: int = 1, priorities: int = 1,
                   deadline_ms: Optional[float] = None) -> List[dict]:
    """``n`` request dicts with uniformly mixed prompt/output lengths,
    round-robin-free random tenant/priority assignment, and an optional
    uniform deadline. Fully determined by ``seed``."""
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        item = {
            "prompt_tokens": int(rs.randint(prompt_range[0], prompt_range[1] + 1)),
            "max_new_tokens": int(rs.randint(new_range[0], new_range[1] + 1)),
        }
        if priorities > 1:
            item["priority"] = int(rs.randint(0, priorities))
        if tenants > 1:
            item["tenant"] = f"tenant{int(rs.randint(0, tenants))}"
        if deadline_ms is not None:
            item["deadline_ms"] = float(deadline_ms)
        out.append(item)
    return out


def dump_workload(path: str, workload: List[dict],
                  arrivals: Optional[List[float]] = None):
    """Write a workload (+ arrival offsets) as replayable JSONL."""
    with open(path, "w") as fh:
        for i, item in enumerate(workload):
            rec = dict(item)
            if arrivals is not None:
                rec["arrival_s"] = arrivals[i]
            fh.write(json.dumps(rec) + "\n")


def load_workload(path: str):
    """(workload, arrivals) from a JSONL trace written by
    :func:`dump_workload` (or recorded elsewhere in the same shape).
    Arrivals is None when no line carries ``arrival_s``."""
    workload, arrivals = [], []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            arrivals.append(rec.pop("arrival_s", None))
            workload.append(rec)
    if not workload:
        raise ValueError(f"no workload records in {path}")
    if any(a is None for a in arrivals):
        return workload, None
    return workload, arrivals


# -- driving the engine ------------------------------------------------
def _item_prompt(item: dict, index: int, seed: int, vocab: int) -> np.ndarray:
    if "prompt" in item:
        return np.asarray(item["prompt"], np.int32)
    n = int(item["prompt_tokens"])
    # per-item stream: prompts don't shift when the mix is resliced
    return np.random.RandomState(seed + index).randint(0, vocab, (n,)).astype(np.int32)


def run_load(serving, workload: List[dict], arrivals: List[float],
             seed: int = 0, clock=time.monotonic, sleep=time.sleep):
    """Drive ``serving`` open-loop: submit each workload item at its
    arrival offset (never waiting for the server), stepping whenever
    there is work. Returns ``(records, wall_s)`` — one record per item
    with the admission verdict and, for admitted requests, the final
    lifecycle numbers (queue/ttft/tbt ms, tokens, deadline_met)."""
    if len(arrivals) != len(workload):
        raise ValueError(f"{len(workload)} workload items but "
                         f"{len(arrivals)} arrival times")
    # works for a single ServingEngine AND a FleetRouter — both expose
    # the same submit/step/reap/vocab_size surface
    vocab = serving.vocab_size
    n = len(workload)
    records: List[dict] = [{} for _ in range(n)]
    rid_to_index: Dict[int, int] = {}
    t0 = clock()
    i = 0
    while i < n or serving.has_work():
        now = clock() - t0
        while i < n and arrivals[i] <= now:
            item = workload[i]
            adm = serving.submit(
                _item_prompt(item, i, seed, vocab),
                int(item.get("max_new_tokens", 32)),
                priority=int(item.get("priority", 0)),
                tenant=str(item.get("tenant", "default")),
                deadline_ms=item.get("deadline_ms"),
            )
            rec = records[i]
            rec["status"] = adm.status
            rec["arrival_s"] = arrivals[i]
            if adm:
                rid_to_index[adm.rid] = i
                rec["rid"] = adm.rid
            else:
                rec["state"] = "shed"
                rec["reason"] = adm.reason
                if adm.retry_after_s is not None:
                    rec["retry_after_s"] = adm.retry_after_s
            i += 1
        if serving.has_work():
            serving.step()
        elif i < n:
            # idle before the next arrival: don't spin the host
            sleep(min(max(arrivals[i] - (clock() - t0), 0.0), 0.002))
    wall_s = clock() - t0
    for rid, req in serving.reap().items():
        rec = records[rid_to_index[rid]]
        rec["state"] = req.state
        rec["tokens"] = len(req.tokens)
        rec["generated"] = list(req.tokens)  # parity checks / replay diffing
        if req.recoveries:
            rec["recoveries"] = req.recoveries
        if req.finish_t is not None:
            # completion timeline (same clock as arrivals): the chaos
            # scorecard bins these to measure the goodput dip
            rec["finish_s"] = req.finish_t - t0
        q = req.queue_ms()
        if q is not None:
            rec["queue_ms"] = q
        t = req.ttft_ms()
        if t is not None:
            rec["ttft_ms"] = t
        if (req.first_token_t is not None and req.finish_t is not None
                and len(req.tokens) > 1):
            rec["tbt_ms"] = ((req.finish_t - req.first_token_t) * 1000.0
                             / (len(req.tokens) - 1))
        if req.deadline_met is not None:  # the shared per-request verdict
            rec["deadline_met"] = req.deadline_met
    return records, wall_s


# -- reporting ---------------------------------------------------------
def _pcts(vals: List[float]) -> dict:
    return {"p50": percentile(vals, 50.0), "p99": percentile(vals, 99.0)}


def host_overhead(tick_stats: dict) -> dict:
    """Host-overhead columns from a ``ServingEngine.tick_stats()`` (or the
    bare engine's) snapshot: mean dispatch vs blocked ms per scheduler
    step, the overlap fraction (host tick-loop time NOT spent blocked on
    device results), and the A/B headline — host-blocked ms per decoded
    token."""
    steps = tick_stats.get("steps", 0)
    out = {
        "pipeline_depth": tick_stats.get("pipeline_depth"),
        "ticks": tick_stats.get("ticks", 0),
        "tick_dispatch_ms_mean": (round(tick_stats["dispatch_ms"] / steps, 4)
                                  if steps else None),
        "tick_block_ms_mean": (round(tick_stats["block_ms"] / steps, 4)
                               if steps else None),
        "overlap_frac": tick_stats.get("overlap_frac"),
        "block_ms_per_token": tick_stats.get("block_ms_per_token"),
        "wasted_tokens": tick_stats.get("wasted_tokens", 0),
    }
    if "utilization" in tick_stats:
        out["tick_utilization"] = tick_stats["utilization"]
    return out


def goodput_dip(records: List[dict], wall_s: float, bins: int = 10) -> Optional[dict]:
    """The chaos-scorecard headline: bin finished requests' output tokens
    by completion time (``finish_s``) and compare the worst bin inside
    the active window (first completion .. last completion — zeros in
    between are genuine outage, not warmup/tail) against the median bin.
    Returns ``{bin_s, baseline_tok_s, floor_tok_s, dip_frac}`` or None
    when there are not enough completions to observe a rate."""
    pts = [(float(r["finish_s"]), int(r.get("tokens", 0))) for r in records
           if r.get("state") == "finished" and "finish_s" in r]
    if not pts or wall_s <= 0 or bins < 1:
        return None
    width = wall_s / bins
    if width <= 0:
        return None
    binned = [0.0] * bins
    for t, tok in pts:
        binned[min(bins - 1, max(0, int(t / width)))] += tok
    hot = [i for i, v in enumerate(binned) if v > 0]
    window = binned[hot[0]:hot[-1] + 1]
    if len(window) < 2:
        return None  # one active bin: no dip is observable
    # baseline = the healthy completion rate (median of the BUSY bins —
    # an outage long enough to dominate the window must read as a deep
    # dip, not drag the baseline to zero); floor = the worst bin inside
    # the window, zeros included
    busy = sorted(v / width for v in window if v > 0)
    baseline = busy[len(busy) // 2]
    floor = min(v / width for v in window)
    if baseline <= 0:
        return None
    return {"bin_s": round(width, 3),
            "baseline_tok_s": round(baseline, 3),
            "floor_tok_s": round(floor, 3),
            "dip_frac": round(1.0 - floor / baseline, 4)}


def chaos_scorecard(records: List[dict], wall_s: float, recovery: dict,
                    injected: Optional[List[dict]] = None) -> dict:
    """The ``--chaos`` section: the serving engine's recovery accounting
    (``ServingEngine.recovery_stats()``) + the goodput dip measured from
    the completion timeline + the injector's fired-fault log."""
    out = dict(recovery)
    if injected is not None:
        out["injected"] = len(injected)
    recovered = sum(1 for r in records if r.get("recoveries"))
    out["recovered_requests"] = recovered
    dip = goodput_dip(records, wall_s)
    if dip is not None:
        out["goodput_dip"] = dip
    return out


def fleet_scorecard(router, records: List[dict]) -> dict:
    """The ``fleet`` summary section for a :class:`FleetRouter` run:
    per-replica placement outcomes (from the fleet ``statusz``) plus the
    conservation check the failover contract promises — every admitted
    request ends terminal (finished / shed / expired / cancelled);
    replica death loses none silently."""
    st = router.statusz()
    placed = [r for r in records if "rid" in r]
    terminal = sum(1 for r in placed if "state" in r)
    return {
        "replicas": {
            rid: {"state": info["state"], "admitted": info["admitted"],
                  "shed": info["shed"],
                  "migrated_in": info["migrated_in"],
                  "migrated_out": info["migrated_out"]}
            for rid, info in sorted(st["replicas"].items())
        },
        "submitted": st["submitted"],
        "admitted": st["admitted"],
        "shed": st["shed"],
        "spillovers": st["spillovers"],
        "migrated": st["migrated"],
        "lost": st["lost"],
        "replica_deaths": st["replica_deaths"],
        "conservation_ok": (terminal == len(placed)
                            and len(placed) == st["admitted"]),
    }


def format_fleet_sweep(results: "Dict[str, dict]") -> str:
    """``--replicas 1,2,4``: one scorecard per fleet size plus the
    goodput / SLO-met curve table — the scaling headline the ISSUE's
    acceptance criteria cite."""
    lines = []
    for n in sorted(results, key=int):
        lines += [f"== fleet: {n} replica(s) ==",
                  format_summary(results[n]).rstrip(), ""]
    lines.append("replicas  throughput  goodput   shed     deadline-met")
    for n in sorted(results, key=int):
        s = results[n]
        dm = s.get("deadline_met_frac")
        lines.append(f"{n:<9} {s['throughput_tok_s']:<11} "
                     f"{s['goodput_tok_s']:<9} {s['shed_rate']:<8.2%} "
                     f"{f'{dm:.2%}' if dm is not None else '-'}")
    return "\n".join(lines) + "\n"


def fleet_record(results: "Dict[str, dict]", workload_args: dict) -> dict:
    """FLEET_*-style JSON record for a ``--replicas`` sweep: the
    goodput/SLO curve per fleet size plus the full summaries, in the
    shape the repo's committed perf records use."""
    import jax

    curves = {
        n: {
            "throughput_tok_s": s.get("throughput_tok_s"),
            "goodput_tok_s": s.get("goodput_tok_s"),
            "shed_rate": s.get("shed_rate"),
            "deadline_met_frac": s.get("deadline_met_frac"),
            "ttft_ms": s.get("ttft_ms"),
            "replica_deaths": (s.get("fleet") or {}).get("replica_deaths"),
            "migrated": (s.get("fleet") or {}).get("migrated"),
            "lost": (s.get("fleet") or {}).get("lost"),
            "conservation_ok": (s.get("fleet") or {}).get("conservation_ok"),
        }
        for n, s in results.items()
    }
    return {
        "kind": "serving_fleet_sweep",
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": jax.device_count(),
        "replicas": sorted(int(n) for n in results),
        "curves": curves,
        "workload": workload_args,
        "summaries": results,
    }


def summarize(records: List[dict], wall_s: float,
              tick_stats: Optional[dict] = None) -> dict:
    """The serving scorecard over one run's records: counts per outcome,
    TTFT/TBT/queue-wait p50/p99, offered load, throughput, goodput
    (deadline-met output tokens per second — all finished tokens when the
    workload carries no deadlines), shed rate, deadline-met fraction.
    ``tick_stats`` (ServingEngine.tick_stats()) adds the ``host`` section:
    dispatch/blocked ms, overlap fraction, blocked ms per token."""
    by_state: Dict[str, int] = {}
    for r in records:
        state = r.get("state", r.get("status", "?"))
        by_state[state] = by_state.get(state, 0) + 1
    finished = [r for r in records if r.get("state") == "finished"]
    shed = [r for r in records if r.get("state") in ("shed", "expired")]
    arrivals = [r["arrival_s"] for r in records if "arrival_s" in r]
    span = max(arrivals) if arrivals else 0.0
    out = {
        "requests": len(records),
        "outcomes": dict(sorted(by_state.items())),
        "wall_s": round(wall_s, 3),
        "offered_rps": round(len(records) / span, 3) if span > 0 else None,
        "shed_rate": round(len(shed) / len(records), 4) if records else 0.0,
    }
    # honest-retry accounting per shed reason: how many verdicts carried
    # a retry_after_s hint and the mean hint. ds_trace_report --serve
    # computes the SAME table from the serving_event stream — the two
    # must agree (tests/unit/serving/test_shed_hints.py)
    by_reason: Dict[str, dict] = {}
    for r in records:
        if r.get("state") != "shed":
            continue
        # reaped sheds (admitted, then shed during failover) carry no
        # admission reason — bucket them separately, they are the fleet's
        # post-admission losses, not admission-control verdicts
        d = by_reason.setdefault(r.get("reason", "post_admission"),
                                 {"count": 0, "with_hint": 0, "hints": []})
        d["count"] += 1
        if r.get("retry_after_s") is not None:
            d["with_hint"] += 1
            d["hints"].append(float(r["retry_after_s"]))
    if by_reason:
        out["shed_by_reason"] = {
            reason: {
                "count": d["count"],
                "with_hint": d["with_hint"],
                "retry_after_s_mean": (round(sum(d["hints"]) / len(d["hints"]),
                                             4) if d["hints"] else None),
            }
            for reason, d in sorted(by_reason.items())
        }
    for field in ("ttft_ms", "tbt_ms", "queue_ms"):
        vals = [r[field] for r in finished if field in r]
        if vals:
            out[field] = {k: round(v, 3) for k, v in _pcts(vals).items()}
    total_tokens = sum(r.get("tokens", 0) for r in finished)
    out["throughput_tok_s"] = round(total_tokens / wall_s, 3) if wall_s > 0 else 0.0
    with_deadline = [r for r in finished if "deadline_met" in r]
    good_tokens = sum(r.get("tokens", 0) for r in finished
                      if r.get("deadline_met", True))
    out["goodput_tok_s"] = round(good_tokens / wall_s, 3) if wall_s > 0 else 0.0
    if with_deadline:
        out["deadline_met_frac"] = round(
            sum(1 for r in with_deadline if r["deadline_met"])
            / len(with_deadline), 4)
    if tick_stats is not None:
        out["host"] = host_overhead(tick_stats)
    return out


def format_summary(summary: dict) -> str:
    lines = ["== ds_loadgen summary =="]
    oc = " ".join(f"{k}={v}" for k, v in summary["outcomes"].items())
    lines.append(f"requests       {summary['requests']}  ({oc})")
    if summary.get("offered_rps") is not None:
        lines.append(f"offered load   {summary['offered_rps']} req/s over "
                     f"{summary['wall_s']} s wall")
    else:
        lines.append(f"wall time      {summary['wall_s']} s")
    for field, label in (("ttft_ms", "TTFT"), ("tbt_ms", "TBT"),
                         ("queue_ms", "queue wait")):
        if field in summary:
            p = summary[field]
            lines.append(f"{label:<14} p50 {p['p50']:.1f} ms   p99 {p['p99']:.1f} ms")
    lines.append(f"throughput     {summary['throughput_tok_s']} tok/s")
    lines.append(f"goodput        {summary['goodput_tok_s']} tok/s")
    lines.append(f"shed rate      {summary['shed_rate']:.2%}")
    sbr = summary.get("shed_by_reason")
    if sbr:
        parts = []
        for reason, d in sbr.items():
            hint = (f" hint~{d['retry_after_s_mean']}s"
                    if d["retry_after_s_mean"] is not None else "")
            parts.append(f"{reason}={d['count']} "
                         f"({d['with_hint']} hinted{hint})")
        lines.append("shed reasons   " + "   ".join(parts))
    if "deadline_met_frac" in summary:
        lines.append(f"deadline met   {summary['deadline_met_frac']:.2%}")
    host = summary.get("host")
    if host:
        def _ms(v):
            return f"{v:.3f} ms" if isinstance(v, (int, float)) else "-"

        lines.append(f"host overhead  dispatch {_ms(host['tick_dispatch_ms_mean'])}"
                     f"/step   blocked {_ms(host['tick_block_ms_mean'])}/step"
                     + (f"   overlap {host['overlap_frac']:.1%}"
                        if host.get("overlap_frac") is not None else ""))
        lines.append(f"blocked/token  {_ms(host['block_ms_per_token'])}  "
                     f"(pipeline depth {host['pipeline_depth']}, "
                     f"wasted {host['wasted_tokens']} tok)")
    chaos = summary.get("chaos")
    if chaos:
        lines.append(
            f"chaos          faults {chaos.get('faults', 0)}"
            + (f" (injected {chaos['injected']})" if "injected" in chaos else "")
            + f"   retries {chaos.get('retries', 0)}"
              f"   rebuilds {chaos.get('rebuilds', 0)}"
              f"   degrade level {chaos.get('degrade_level', 0)}")
        lines.append(
            f"recovery       lost ticks {chaos.get('lost_ticks', 0)}"
            f"   lost requests {chaos.get('lost_requests', 0)}"
            f"   recovered requests {chaos.get('recovered_requests', 0)}"
            f"   outage {chaos.get('outage_ms_total', 0.0)} ms")
        rms = chaos.get("recovery_ms")
        if rms:
            lines.append(f"recovery_ms    p50 {rms['p50']} ms   "
                         f"max {rms['max']} ms  ({rms['count']} rebuilds)")
        dip = chaos.get("goodput_dip")
        if dip:
            lines.append(f"goodput dip    {dip['dip_frac']:.1%}  "
                         f"(floor {dip['floor_tok_s']} tok/s vs median "
                         f"{dip['baseline_tok_s']} tok/s over "
                         f"{dip['bin_s']}s bins)")
    fleet = summary.get("fleet")
    if fleet:
        reps = "  ".join(
            f"{rid}:{info['state']} adm={info['admitted']} "
            f"mig={info['migrated_in']}/{info['migrated_out']}"
            for rid, info in fleet["replicas"].items())
        lines.append(f"fleet          {reps}")
        lines.append(
            f"               deaths {fleet['replica_deaths']}   "
            f"migrated {fleet['migrated']}   lost {fleet['lost']}   "
            f"spillovers {fleet['spillovers']}   conservation "
            + ("ok" if fleet["conservation_ok"] else "VIOLATED"))
    return "\n".join(lines) + "\n"


def format_mesh_ab(results: "Dict[str, dict]") -> str:
    """Side-by-side sharded-vs-replicated comparison (``--mesh`` sweep /
    ``--ab-mesh``): one scorecard per mesh width plus headline ratio lines
    against the replicated (``1x1``) side — throughput and host-blocked
    ms per token per tensor width, the MULTICHIP serving record."""
    def _dims(key):  # numeric order: 1x2 before 1x16
        d, _, t = key.partition("x")
        try:
            return (int(d), int(t))
        except ValueError:
            return (1 << 30, 0)

    keys = sorted(results, key=lambda k: (k != "1x1", _dims(k)))
    lines = []
    for key in keys:
        lines += [f"== mesh {key} ==", format_summary(results[key]).rstrip(), ""]
    base = results.get("1x1")
    if base is not None:
        for key in keys:
            if key == "1x1":
                continue
            cur = results[key]
            t0, t1 = base.get("throughput_tok_s"), cur.get("throughput_tok_s")
            if t0 and t1 is not None:
                lines.append(f"throughput tok/s 1x1 -> {key}: {t0} -> {t1} "
                             f"({t1 / t0:.2f}x)")
            b0 = (base.get("host") or {}).get("block_ms_per_token")
            b1 = (cur.get("host") or {}).get("block_ms_per_token")
            if b0 is not None and b1 is not None:
                lines.append(f"host-blocked ms/token 1x1 -> {key}: "
                             f"{b0:.4f} -> {b1:.4f}")
    return "\n".join(lines) + "\n"


def mesh_record(results: "Dict[str, dict]", workload_args: dict) -> dict:
    """MULTICHIP_*-style JSON serving record for a mesh sweep: per-width
    throughput + host-blocked ms/token plus the winning width, in a shape
    the on-chip bench can read back to self-tune its tensor width."""
    import jax

    per_width = {
        key: {
            "throughput_tok_s": s.get("throughput_tok_s"),
            "goodput_tok_s": s.get("goodput_tok_s"),
            "block_ms_per_token": (s.get("host") or {}).get("block_ms_per_token"),
            "overlap_frac": (s.get("host") or {}).get("overlap_frac"),
            "shed_rate": s.get("shed_rate"),
            "ttft_ms": s.get("ttft_ms"),
        }
        for key, s in results.items()
    }
    winner = max(results, key=lambda k: results[k].get("throughput_tok_s") or 0.0)
    return {
        "kind": "serving_mesh_ab",
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": jax.device_count(),
        "meshes": per_width,
        "winner": winner,
        "workload": workload_args,
        "summaries": results,
    }


def format_ab(sync: dict, pipelined: dict) -> str:
    """Side-by-side sync-vs-pipelined comparison (``--pipeline-depth`` A/B):
    the two scorecards plus the headline ratios — host-blocked ms per
    decoded token (the ≥2x acceptance metric) and throughput."""
    lines = ["== pipeline A/B: sync (depth 0) ==", format_summary(sync).rstrip(),
             "", f"== pipelined (depth {pipelined['host']['pipeline_depth']}) ==",
             format_summary(pipelined).rstrip(), ""]
    b0 = (sync.get("host") or {}).get("block_ms_per_token")
    b1 = (pipelined.get("host") or {}).get("block_ms_per_token")
    if b0 is not None and b1 is not None:
        # a (near-)zero pipelined value is the BEST case, not a missing one
        ratio = f" ({b0 / b1:.2f}x less blocking)" if 0 < b1 < b0 else ""
        lines.append(f"host-blocked ms/token: {b0:.4f} -> {b1:.4f}{ratio}")
    t0, t1 = sync.get("throughput_tok_s"), pipelined.get("throughput_tok_s")
    if t0 is not None and t1 is not None and t0 > 0:
        lines.append(f"throughput tok/s:      {t0} -> {t1} ({t1 / t0:.2f}x)")
    return "\n".join(lines) + "\n"


# -- CLI ---------------------------------------------------------------
def _parse_range(spec: str):
    lo, sep, hi = spec.partition(":")
    if not sep:
        return int(lo), int(lo)
    return int(lo), int(hi)


def _parse_kill(spec: str):
    # "12" -> (12, None); "12:40" -> (12, 40)
    tick, sep, restore = spec.partition(":")
    return int(tick), (int(restore) if sep else None)


def _parse_buckets(spec: str):
    # "2x32,1x64" -> [(2, 32), (1, 64)]
    out = []
    for part in spec.split(","):
        slots, sep, length = part.strip().partition("x")
        if not sep:
            raise ValueError(f"bucket spec {part!r} is not SLOTSxLEN")
        out.append((int(slots), int(length)))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="open-loop load generator for the serving layer: "
                    "drives ServingEngine over ContinuousBatchingEngine "
                    "and reports TTFT/TBT/goodput/shed (docs/serving.md)")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--rate", type=float, default=8.0, help="offered req/s")
    p.add_argument("--process", choices=_PROCESSES, default="poisson")
    p.add_argument("--burst-size", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prompt-range", default="4:16", metavar="LO:HI")
    p.add_argument("--new-range", default="4:16", metavar="LO:HI")
    p.add_argument("--tenants", type=int, default=1)
    p.add_argument("--priorities", type=int, default=1,
                   help="priority levels to mix (1 = all equal)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request SLO; enables goodput/deadline stats")
    p.add_argument("--preset", default="toy",
                   help="'toy' (tiny CPU-runnable model) or a "
                        "models/transformer.py preset name")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--cache-len", type=int, default=128)
    p.add_argument("--buckets", default=None, metavar="SxL,SxL",
                   help="cache_buckets instead of --slots/--cache-len, "
                        "e.g. 6x128,2x512")
    p.add_argument("--tokens-per-tick", type=int, default=1)
    p.add_argument("--pipeline-depth", type=int, default=1,
                   help="ticks kept in flight (dispatch-ahead pipelining); "
                        "0 = fully synchronous scheduler")
    p.add_argument("--no-fused-prefill", action="store_true",
                   help="admit via the separate B=1 prefill + splice "
                        "instead of riding prompt chunks inside the tick")
    p.add_argument("--no-donate", action="store_true",
                   help="disable tick-state buffer donation: the jax CPU "
                        "backend blocks at dispatch to honour donation, "
                        "which serializes the tick chain — pass this for "
                        "virtual-mesh overlap measurements (on TPU "
                        "donation and async dispatch compose; keep it on)")
    p.add_argument("--ab-pipeline", action="store_true",
                   help="run the SAME workload twice — sync (depth 0) vs "
                        "--pipeline-depth — and report both scorecards "
                        "plus the host-blocked-ms/token ratio")
    p.add_argument("--mesh", default=None, metavar="DATA:TENSOR[,..]",
                   help="serving mesh shape(s), e.g. 1:2 — tensor widths "
                        "shard attention heads / MLP hidden / vocab and "
                        "the KV cache's heads axis over that many devices "
                        "(docs/inference.md 'Tensor-parallel serving'). A "
                        "comma list sweeps widths over the same workload. "
                        "On the CPU virtual mesh combine with --no-donate "
                        "(donation serializes dispatch there)")
    p.add_argument("--ab-mesh", action="store_true",
                   help="sharded-vs-replicated A/B: run the SAME workload "
                        "on the replicated 1:1 mesh AND every --mesh "
                        "width, report per-width scorecards + throughput/"
                        "host-blocked ratios")
    p.add_argument("--mesh-out", default=None, metavar="FILE",
                   help="write the mesh sweep as a MULTICHIP_*-style JSON "
                        "serving record (per-width throughput + "
                        "host-blocked ms/token + winner)")
    p.add_argument("--chaos", default=None, metavar="PLAN.jsonl",
                   help="fault-injection plan (serving/faults.py FaultPlan "
                        "JSONL: tick/kind lines, kinds dispatch_error|"
                        "fetch_hang|preempt). Arms watchdog+recovery: "
                        "failed ticks retry with backoff, lost engines "
                        "rebuild and re-admit every in-flight request "
                        "mid-stream (bitwise resume); the summary gains "
                        "a recovery-time + goodput-dip scorecard")
    p.add_argument("--chaos-degrade", default=None, metavar="D:T[,..]",
                   help="graceful-degradation ladder for --chaos: mesh "
                        "shape(s) to rebuild on when the full-size "
                        "rebuild fails or a preemption took capacity, "
                        "e.g. 1:1 after serving --mesh 1:2")
    p.add_argument("--tick-retries", type=int, default=2,
                   help="bounded retry budget for a clean tick failure "
                        "before escalating to engine rebuild (--chaos)")
    p.add_argument("--fetch-timeout-s", type=float, default=None,
                   help="watchdog on the per-tick packed-result fetch; "
                        "an over-budget fetch abandons the engine and "
                        "triggers a rebuild (--chaos)")
    p.add_argument("--replicas", default=None, metavar="N[,N..]",
                   help="serve through a FleetRouter over N ServingEngine "
                        "replicas (docs/serving.md 'Fleet'); a comma list "
                        "(e.g. 1,2,4) sweeps fleet sizes over the SAME "
                        "workload and reports the goodput/SLO-met curve")
    p.add_argument("--kill-replica", default=None, metavar="TICK[:RESTORE]",
                   help="chaos: abruptly kill the lowest-slot healthy "
                        "replica at router tick TICK (1-based, replayable "
                        "— same surface as the fault plans); live streams "
                        "migrate to survivors and resume bitwise. With "
                        ":RESTORE, a fresh replica joins at that tick")
    p.add_argument("--rolling-restart", type=int, default=None,
                   metavar="TICK", help="start a zero-loss rolling restart "
                        "of the whole fleet at router tick TICK (add the "
                        "replacement first, then drain — capacity never "
                        "dips)")
    p.add_argument("--fleet-out", default=None, metavar="FILE",
                   help="write the --replicas sweep as a FLEET_*-style "
                        "JSON record (goodput/SLO curve per fleet size)")
    p.add_argument("--policy", default="fifo",
                   choices=("fifo", "priority", "edf", "fair"))
    p.add_argument("--queue-depth", type=int, default=64)
    p.add_argument("--kv-budget", type=int, default=None,
                   help="KV token budget (default: 2x pool capacity)")
    p.add_argument("--aging-s", type=float, default=30.0)
    p.add_argument("--ops-port", type=int, default=None, metavar="PORT",
                   help="serve the live ops plane (/metrics /healthz "
                        "/statusz — docs/telemetry.md) during the run; "
                        "0 binds an ephemeral port (printed at start). "
                        "The exporter runs on a daemon thread and never "
                        "blocks the tick loop")
    p.add_argument("--trace-out", default=None,
                   help="telemetry JSONL destination; summarize with "
                        "`ds_trace_report.py --serve`")
    p.add_argument("--replay", default=None,
                   help="replay a JSONL workload (dump_workload shape) "
                        "instead of synthesizing one")
    p.add_argument("--dump-workload", default=None,
                   help="write the synthesized workload+arrivals as "
                        "replayable JSONL")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the summary as JSON instead of the table")
    args = p.parse_args(argv)

    if args.replay:
        workload, arrivals = load_workload(args.replay)
        if arrivals is None:
            arrivals = gen_arrivals(len(workload), args.rate, args.process,
                                    args.seed, args.burst_size)
    else:
        workload = synth_workload(
            args.requests, seed=args.seed,
            prompt_range=_parse_range(args.prompt_range),
            new_range=_parse_range(args.new_range), tenants=args.tenants,
            priorities=args.priorities, deadline_ms=args.deadline_ms)
        arrivals = gen_arrivals(args.requests, args.rate, args.process,
                                args.seed, args.burst_size)
    if args.dump_workload:
        dump_workload(args.dump_workload, workload, arrivals)

    import jax

    from deepspeed_tpu.inference.continuous import ContinuousBatchingEngine
    from deepspeed_tpu.models.transformer import (
        TransformerConfig,
        TransformerModel,
    )
    from deepspeed_tpu.serving.engine import ServingEngine

    if args.preset == "toy":
        model = TransformerModel(TransformerConfig(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=max(args.cache_len, 128), dtype=args.dtype))
    else:
        model = TransformerModel.from_preset(args.preset, dtype=args.dtype)
    params = model.init(jax.random.PRNGKey(args.seed))

    chaos_plan = FaultPlan.load(args.chaos) if args.chaos else None
    degrade_shapes = []
    if args.chaos_degrade:
        if not args.chaos:
            p.error("--chaos-degrade needs --chaos (it is the rebuild "
                    "degradation ladder for the fault-injected run)")
        from deepspeed_tpu.parallel.partition import parse_mesh_arg as _pma

        degrade_shapes = [_pma(s) for s in args.chaos_degrade.split(",")]

    def build_cb(depth: int, mesh_shape=None, trace_out=None):
        cfg = {"dtype": args.dtype}
        if mesh_shape:
            cfg["mesh"] = {"shape": mesh_shape}
        if trace_out:
            cfg["telemetry"] = {"enabled": True, "trace_file": trace_out}
        elif args.ops_port is not None:
            # --ops-port without --trace-out: /metrics still needs a live
            # registry (gauges/counters/histograms are telemetry-gated),
            # so enable the hub registry-only — no trace file written
            cfg["telemetry"] = {"enabled": True, "trace_file": ""}
        engine_kwargs = {}
        if args.buckets:
            engine_kwargs["cache_buckets"] = _parse_buckets(args.buckets)
        else:
            engine_kwargs["max_slots"] = args.slots
            engine_kwargs["cache_len"] = args.cache_len
        return ContinuousBatchingEngine(
            model, params=params, config=cfg,
            tokens_per_tick=args.tokens_per_tick,
            pipeline_depth=depth,
            fused_prefill=not args.no_fused_prefill,
            donate_cache=not args.no_donate,
            **engine_kwargs)

    def build_serving(depth: int, trace_out=None, mesh_shape=None):
        cb = build_cb(depth, mesh_shape=mesh_shape, trace_out=trace_out)
        kw = {}
        if chaos_plan is not None:
            cb.fault_hook = FaultInjector(chaos_plan)

            def factory(mesh_shape=None, _depth=depth, _orig=mesh_shape):
                # replacement engines carry NO telemetry config — the
                # serving layer re-injects its hub so the trace file and
                # counters stay continuous across rebuilds; mesh_shape
                # None = rebuild at the run's original size
                return build_cb(_depth,
                                mesh_shape=mesh_shape or _orig)

            kw = dict(engine_factory=factory,
                      degrade_mesh_shapes=degrade_shapes,
                      recovery=RecoveryConfig(
                          max_tick_retries=args.tick_retries,
                          fetch_timeout_s=args.fetch_timeout_s))
        return ServingEngine(cb, policy=args.policy,
                             max_queue_depth=args.queue_depth,
                             kv_budget_tokens=args.kv_budget,
                             aging_s=args.aging_s, **kw)

    def one_run(depth: int, trace_out=None, mesh_shape=None):
        serving = build_serving(depth, trace_out=trace_out,
                                mesh_shape=mesh_shape)
        if args.ops_port is not None:
            ops = serving.start_ops_server(port=args.ops_port)
            print(f"ops server live at {ops.url} "
                  f"(/metrics /healthz /statusz)")
        records, wall_s = run_load(serving, workload, arrivals, seed=args.seed)
        summary = summarize(records, wall_s, tick_stats=serving.tick_stats())
        if chaos_plan is not None:
            injector = serving._cb.fault_hook
            summary["chaos"] = chaos_scorecard(
                records, wall_s, serving.recovery_stats(),
                injected=getattr(injector, "fired", None))
        if mesh_shape:
            summary["mesh"] = dict(mesh_shape)
        if trace_out or args.ops_port is not None:
            # close releases the exporter port so the next A/B side (or a
            # fixed --ops-port rerun) can bind it again
            serving.close()
        return summary

    meshes = []
    if args.mesh:
        from deepspeed_tpu.parallel.partition import parse_mesh_arg

        meshes = [parse_mesh_arg(s) for s in args.mesh.split(",")]
    if args.mesh_out and not meshes:
        # without --mesh the serve runs the engine's DEFAULT mesh; a
        # record labelled by an assumed shape would mislead the bench
        # that reads it back
        p.error("--mesh-out needs --mesh (the record is keyed by the "
                "explicit serving mesh shape)")
    if args.mesh_out and args.ab_pipeline:
        p.error("--mesh-out records a per-width mesh sweep; it does not "
                "combine with the depth A/B (--ab-pipeline) — run them "
                "separately")
    if args.chaos and (args.ab_pipeline or args.ab_mesh or args.mesh_out
                       or len(meshes) > 1):
        p.error("--chaos measures one fault-injected run; it does not "
                "combine with the A/B modes or the mesh sweep (compare a "
                "chaos run against a no-chaos run of the same workload)")

    # -- fleet mode (--replicas): route through a FleetRouter -----------
    if (args.kill_replica or args.rolling_restart is not None
            or args.fleet_out) and not args.replicas:
        p.error("--kill-replica / --rolling-restart / --fleet-out need "
                "--replicas (they schedule chaos on the fleet router)")
    if args.replicas:
        try:
            fleet_sizes = [int(x) for x in args.replicas.split(",")]
        except ValueError:
            p.error(f"--replicas {args.replicas!r} is not N or N,N,..")
        if any(n < 1 for n in fleet_sizes):
            p.error("--replicas sizes must be >= 1")
        if (args.ab_pipeline or args.ab_mesh or meshes or args.mesh_out
                or args.chaos):
            p.error("--replicas does not combine with the pipeline/mesh "
                    "A/B modes or engine-level --chaos — fleet chaos is "
                    "--kill-replica / --rolling-restart (replica-level "
                    "faults through the router's replayable tick hooks)")
        kill_spec = _parse_kill(args.kill_replica) if args.kill_replica \
            else None

        from deepspeed_tpu.serving.fleet import attach_replica_telemetry
        from deepspeed_tpu.serving.router import FleetRouter

        def build_fleet(n: int, trace_out=None) -> FleetRouter:
            # ONE shared hub for the whole fleet: the first replica's
            # engine is built with the telemetry config (trace file /
            # ops registry) and its hub becomes the base; every replica
            # — including the first, and any --kill-replica :RESTORE or
            # rolling-restart replacement — talks through a
            # ReplicaTelemetry facade that tags its events and metrics
            # with the replica id
            holder: dict = {}

            def factory(replica_id: str):
                if "hub" not in holder:
                    cb = build_cb(args.pipeline_depth, trace_out=trace_out)
                    holder["hub"] = cb._eng.telemetry
                else:
                    cb = build_cb(args.pipeline_depth)
                attach_replica_telemetry(cb, holder["hub"], replica_id)
                return ServingEngine(
                    cb, policy=args.policy,
                    max_queue_depth=args.queue_depth,
                    kv_budget_tokens=args.kv_budget, aging_s=args.aging_s)

            return FleetRouter(factory, replicas=n)

        def kill_lowest_healthy(router: FleetRouter):
            for rid in router.replica_ids():  # slot order
                if router.statusz()["replicas"][rid]["state"] == "healthy":
                    router.kill(rid, detail="loadgen --kill-replica")
                    return

        def one_fleet_run(n: int, trace_out=None) -> dict:
            router = build_fleet(n, trace_out=trace_out)
            if kill_spec is not None:
                tick, restore = kill_spec
                router.at_tick(tick, kill_lowest_healthy)
                if restore is not None:
                    router.at_tick(restore, lambda r: r.add())
            if args.rolling_restart is not None:
                router.at_tick(args.rolling_restart,
                               lambda r: r.rolling_restart())
            if args.ops_port is not None:
                ops = router.start_ops_server(port=args.ops_port)
                print(f"fleet ops server live at {ops.url} "
                      f"(/metrics /healthz /statusz)")
            records, wall_s = run_load(router, workload, arrivals,
                                       seed=args.seed)
            summary = summarize(records, wall_s,
                                tick_stats=router.tick_stats())
            summary["fleet"] = fleet_scorecard(router, records)
            if kill_spec is not None or args.rolling_restart is not None:
                summary["chaos"] = chaos_scorecard(
                    records, wall_s, router.recovery_stats())
            router.close()
            return summary

        results = {}
        for n in fleet_sizes:
            trace = args.trace_out
            if trace and len(fleet_sizes) > 1:
                trace = f"{trace}.x{n}.jsonl"
            results[str(n)] = one_fleet_run(n, trace_out=trace)
        if args.fleet_out:
            record = fleet_record(results, {
                "requests": len(workload), "rate": args.rate,
                "process": args.process, "seed": args.seed,
                "pipeline_depth": args.pipeline_depth,
                "slots": args.slots, "cache_len": args.cache_len,
                "deadline_ms": args.deadline_ms, "preset": args.preset,
                "kill_replica": args.kill_replica,
                "rolling_restart": args.rolling_restart})
            with open(args.fleet_out, "w") as fh:
                json.dump(record, fh, indent=2, sort_keys=True)
            print(f"fleet record written to {args.fleet_out}")
        if args.as_json:
            print(json.dumps(results if len(fleet_sizes) > 1
                             else results[str(fleet_sizes[0])],
                             indent=2, sort_keys=True))
        elif len(fleet_sizes) > 1:
            sys.stdout.write(format_fleet_sweep(results))
        else:
            sys.stdout.write(format_summary(results[str(fleet_sizes[0])]))
        if args.trace_out:
            print(f"trace written to {args.trace_out}"
                  + (".x<N>.jsonl per fleet size"
                     if len(fleet_sizes) > 1 else "")
                  + " (summarize: python tools/ds_trace_report.py "
                    "<trace> --serve)")
        return 0

    def write_mesh_record(results):
        record = mesh_record(results, {
            "requests": len(workload), "rate": args.rate,
            "process": args.process, "pipeline_depth": args.pipeline_depth,
            "donate": not args.no_donate, "slots": args.slots,
            "cache_len": args.cache_len, "preset": args.preset})
        with open(args.mesh_out, "w") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
        print(f"mesh record written to {args.mesh_out}")
    if args.ab_mesh or len(meshes) > 1:
        if args.ab_pipeline:
            p.error("--ab-pipeline does not combine with --ab-mesh / a "
                    "multi-width --mesh sweep (the sweep runs one depth "
                    "per width); run the depth A/B per width separately")
        widths = [m for m in meshes
                  if (m.get("data", 1), m.get("tensor", 1)) != (1, 1)]
        if not widths:
            p.error("--ab-mesh needs at least one non-1x1 --mesh width "
                    "to compare against the replicated baseline, e.g. "
                    "--mesh 1:2")
        # sharded-vs-replicated sweep: the replicated 1x1 mesh is always
        # the baseline side, each width replays the SAME workload
        sweep = [{"data": 1, "tensor": 1}] + widths
        results = {}
        for shape in sweep:
            key = f"{shape.get('data', 1)}x{shape.get('tensor', 1)}"
            trace = (f"{args.trace_out}.{key}.jsonl" if args.trace_out else None)
            results[key] = one_run(args.pipeline_depth, trace_out=trace,
                                   mesh_shape=shape)
        if args.mesh_out:
            write_mesh_record(results)
        if args.as_json:
            print(json.dumps(results, indent=2, sort_keys=True))
        else:
            sys.stdout.write(format_mesh_ab(results))
        return 0
    mesh_shape = meshes[0] if meshes else None

    if args.ab_pipeline:
        # BOTH sides must pay identical telemetry overhead or the A/B is
        # biased — with --trace-out the sync run writes a sibling trace
        sync_trace = args.trace_out + ".sync.jsonl" if args.trace_out else None
        sync = one_run(0, trace_out=sync_trace, mesh_shape=mesh_shape)
        pipelined = one_run(max(args.pipeline_depth, 1),
                            trace_out=args.trace_out, mesh_shape=mesh_shape)
        if sync_trace:
            print(f"sync-side trace written to {sync_trace}")
        if args.as_json:
            print(json.dumps({"sync": sync, "pipelined": pipelined},
                             indent=2, sort_keys=True))
        else:
            sys.stdout.write(format_ab(sync, pipelined))
    else:
        summary = one_run(args.pipeline_depth, trace_out=args.trace_out,
                          mesh_shape=mesh_shape)
        if args.mesh_out:  # mesh_shape is set (--mesh-out requires --mesh)
            key = f"{mesh_shape.get('data', 1)}x{mesh_shape.get('tensor', 1)}"
            write_mesh_record({key: summary})
        if args.as_json:
            print(json.dumps(summary, indent=2, sort_keys=True))
        else:
            sys.stdout.write(format_summary(summary))
    if args.trace_out:
        print(f"trace written to {args.trace_out} "
              f"(summarize: python tools/ds_trace_report.py {args.trace_out} "
              f"--serve)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
