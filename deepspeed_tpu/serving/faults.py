"""Deterministic fault injection for the serving stack.

TPU pods are preemptible by design: a serving process must expect tick
dispatches to raise, device fetches to hang, and whole engines to vanish
mid-generation. This module makes those failures *expressible and
replayable* so the recovery layer (serving/engine.py "Fault tolerance",
docs/serving.md) can be tested to the same bitwise-parity bar as every
perf change:

- :class:`FaultPlan` — a seeded, deterministic schedule of faults keyed
  on the global serving tick counter, replayable JSONL exactly like the
  loadgen workloads (``dump``/``load`` round-trip, ``synth`` for seeded
  random plans).
- :class:`FaultInjector` — the plan, armed. Installed as
  ``ContinuousBatchingEngine.fault_hook`` (an explicit injection point
  the engine calls at ``dispatch`` / ``retire`` / ``set_row`` — no
  monkeypatching), it raises the planned exception when its tick comes
  up. The injector owns the tick counter, so one plan spans engine
  rebuilds: tick indices are *serving* ticks, not per-engine ticks.
- The exception taxonomy recovery decides by: :class:`TickDispatchError`
  (transient, raised before any engine mutation — retryable),
  :class:`FetchHang` (a hung/expired device fetch — poisons the tick
  pipeline, engine rebuild), :class:`EnginePreempted` (whole-engine
  loss, optionally with capacity: rebuild, possibly on a smaller mesh).

Deliberately jax-free (stdlib only): plans are authored, validated and
round-tripped without paying a jax import, same as the scheduler
policies.
"""

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# fault kind -> the engine hook point it fires at by default
FAULT_KINDS: Dict[str, str] = {
    "dispatch_error": "dispatch",  # raised before the tick mutates anything
    "fetch_hang": "retire",        # raised at the packed-result fetch
    "preempt": "dispatch",         # whole-engine loss (before mutation)
}
HOOK_POINTS = ("dispatch", "retire", "set_row")


class InjectedFault(RuntimeError):
    """Base class for injected serving faults; ``fault`` carries the plan
    entry that fired (tick, kind, point)."""

    def __init__(self, message: str, fault: Optional[dict] = None):
        super().__init__(message)
        self.fault = fault or {}


class TickDispatchError(InjectedFault):
    """A transient tick-dispatch failure raised at the ``dispatch`` hook,
    BEFORE the engine mutates any state — the retryable fault class."""


class FetchHang(InjectedFault, TimeoutError):
    """A device fetch that hung past the watchdog (injected stand-in for
    the real ``fetch_timeout_s`` timeout): the in-flight tick's results
    are unrecoverable, the engine is poisoned."""


class EnginePreempted(InjectedFault):
    """Whole-engine preemption (the pod slice was reclaimed). ``degrade``
    signals the replacement must be smaller — the graceful-degradation
    path rebuilds on the next configured subset mesh."""

    def __init__(self, message: str, fault: Optional[dict] = None,
                 degrade: bool = False):
        super().__init__(message, fault)
        self.degrade = degrade


@dataclass
class Fault:
    """One planned fault: fires at the first hook call at ``point`` whose
    serving-tick counter has reached ``tick``, then ``count - 1`` more
    consecutive times (``count > 1`` models a persistent failure that
    exhausts the retry budget and forces escalation)."""

    tick: int
    kind: str
    point: str = ""         # defaults to the kind's natural hook point
    count: int = 1
    degrade: bool = False   # preempt only: replacement mesh must shrink
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {sorted(FAULT_KINDS)})")
        if not self.point:
            self.point = FAULT_KINDS[self.kind]
        if self.point not in HOOK_POINTS:
            raise ValueError(f"unknown hook point {self.point!r} "
                             f"(choose from {HOOK_POINTS})")
        if self.tick < 0:
            raise ValueError("fault tick must be >= 0")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")

    def to_dict(self) -> dict:
        out = {"tick": self.tick, "kind": self.kind, "point": self.point}
        if self.count != 1:
            out["count"] = self.count
        if self.degrade:
            out["degrade"] = True
        return out


class FaultPlan:
    """An ordered, replayable schedule of :class:`Fault` entries."""

    def __init__(self, faults: List[Fault]):
        self.faults = sorted(faults, key=lambda f: (f.tick, f.point, f.kind))

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self):
        return iter(self.faults)

    @classmethod
    def synth(cls, seed: int = 0, n_faults: int = 3, first_tick: int = 2,
              tick_span: int = 100, kinds: Optional[List[str]] = None,
              degrade_last: bool = False) -> "FaultPlan":
        """A seeded random plan: ``n_faults`` faults uniformly over
        ``[first_tick, first_tick + tick_span)``, kinds drawn from
        ``kinds`` (default: all three). Fully determined by ``seed`` —
        the chaos-soak analogue of ``synth_workload``."""
        rng = random.Random(seed)
        kinds = list(kinds or FAULT_KINDS)
        ticks = sorted(rng.randrange(first_tick, first_tick + tick_span)
                       for _ in range(n_faults))
        faults = [Fault(tick=t, kind=rng.choice(kinds)) for t in ticks]
        if degrade_last and faults:
            faults[-1].kind = "preempt"
            faults[-1].point = FAULT_KINDS["preempt"]
            faults[-1].degrade = True
        return cls(faults)

    def dump(self, path: str):
        """Write the plan as replayable JSONL (one fault per line)."""
        with open(path, "w") as fh:
            for f in self.faults:
                fh.write(json.dumps(f.to_dict()) + "\n")

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        faults = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                faults.append(Fault(tick=int(rec["tick"]), kind=rec["kind"],
                                    point=rec.get("point", ""),
                                    count=int(rec.get("count", 1)),
                                    degrade=bool(rec.get("degrade", False))))
        if not faults:
            raise ValueError(f"no fault records in {path}")
        return cls(faults)


class FaultInjector:
    """A :class:`FaultPlan`, armed as an engine fault hook.

    Install with ``engine.fault_hook = injector``; the engine calls
    ``injector(point, info)`` at each hook point and the injector raises
    the planned exception when a fault is due. The injector counts
    serving ticks ITSELF (one per ``dispatch`` call) so a single plan
    stays meaningful across engine rebuilds — the replacement engine's
    private tick counter restarts, the plan's does not. The serving
    layer re-installs the hook on every rebuilt engine.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.tick = 0                  # global serving ticks observed
        self.fired: List[dict] = []    # log of injected faults, in order

    def pending(self) -> int:
        """Faults that have not fully fired yet."""
        return sum(1 for f in self.plan if f.fired < f.count)

    def _due(self, point: str) -> Optional[Fault]:
        for f in self.plan:
            if f.point == point and f.fired < f.count and self.tick >= f.tick:
                return f
        return None

    def __call__(self, point: str, info: dict):
        if point == "dispatch":
            self.tick += 1
        fault = self._due(point)
        if fault is None:
            return
        fault.fired += 1
        # plan fields win; the hook's engine-local tick (which resets on
        # every rebuild) is kept under its own key so a fired record can
        # be diffed against the plan without ambiguity
        record = dict(fault.to_dict(), fired_tick=self.tick)
        for key, value in (info or {}).items():
            record.setdefault("engine_tick" if key == "tick" else key, value)
        self.fired.append(record)
        msg = (f"injected {fault.kind} at serving tick {self.tick} "
               f"(plan tick {fault.tick}, point {point})")
        if fault.kind == "dispatch_error":
            raise TickDispatchError(msg, record)
        if fault.kind == "fetch_hang":
            raise FetchHang(msg, record)
        raise EnginePreempted(msg, record, degrade=fault.degrade)
