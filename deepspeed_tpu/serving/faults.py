"""Deterministic fault injection for the serving stack — the serving
domain of the shared fault layer (:mod:`deepspeed_tpu.faults`), re-exported
under its original home.

The machinery (seeded :class:`FaultPlan` schedules, JSONL round-trip, the
armed :class:`FaultInjector` hook, the :class:`TickDispatchError` /
:class:`FetchHang` / :class:`EnginePreempted` taxonomy the recovery
ladder decides by) lives in ``deepspeed_tpu/faults.py`` so the training
column (runtime/resilience.py) shares one implementation; see that
module's docstring for the full domain table. This shim exists so every
serving import path (`serving/engine.py`, tests, docs/serving.md) keeps
working unchanged, and stays jax-free like the rest of the policy layer.
"""

from deepspeed_tpu.faults import (
    FAULT_KINDS,
    HOOK_POINTS,
    EnginePreempted,
    Fault,
    FaultInjector,
    FaultPlan,
    FetchHang,
    InjectedFault,
    TickDispatchError,
)

__all__ = [
    "FAULT_KINDS",
    "HOOK_POINTS",
    "EnginePreempted",
    "Fault",
    "FaultInjector",
    "FaultPlan",
    "FetchHang",
    "InjectedFault",
    "TickDispatchError",
]
