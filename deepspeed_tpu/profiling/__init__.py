"""Profiling (reference: deepspeed/profiling/): flops profiler over XLA cost
analysis; wall-clock timers live in utils/timer.py; jax.profiler traces are
the NVTX/nsys equivalent."""
