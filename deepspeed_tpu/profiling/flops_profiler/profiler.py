"""FLOPs / params / latency profiler.

TPU-native counterpart of the reference's ``FlopsProfiler``
(profiling/flops_profiler/profiler.py:23, 1,198 LoC of module hooks +
torch.nn.functional monkey-patching). Under XLA the compiler already knows
the op-level cost of the *whole compiled program*: ``jit(fn).lower(...)
.compile().cost_analysis()`` returns exact flops/bytes, so the hook/patch
machinery collapses into a compile-and-ask. What survives:

  - per-step triggering from config (``flops_profiler.profile_step``,
    reference engine.py:1646-1664) — `FlopsProfiler` attached to the engine;
  - ``get_model_profile(model, args)`` standalone API (reference :1112);
  - duration via timed execution (with a host-sync fetch — device timing on
    relayed backends acks early otherwise);
  - params from the pytree (no hooks needed).

Per-module breakdown (the reference's depth-wise table) maps to per-jaxpr-
equation accounting: ``flops_by_primitive`` histograms the cost over HLO op
categories, which is the actionable axis on TPU (matmul vs elementwise vs
collective share), since XLA fusion dissolves module boundaries anyway.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _cost_analysis(fn: Callable, *args, **kwargs):
    """Compile fn for the given args; returns (cost dict, compiled executable)
    so callers reuse the compilation instead of jitting twice."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    return dict(cost or {}), compiled


def count_params(tree) -> int:
    return int(sum(np.prod(l.shape or (1,)) for l in jax.tree.leaves(tree)))


def flops_by_primitive(fn: Callable, *args) -> Dict[str, float]:
    """Histogram matmul vs other flops from the jaxpr (module-free breakdown)."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    out: Dict[str, float] = {}

    def visit(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in ("dot_general", "conv_general_dilated"):
                # flops = 2 * prod(output shape) * contracted size
                aval = eqn.outvars[0].aval
                lhs = eqn.invars[0].aval
                if name == "dot_general":
                    dims = eqn.params["dimension_numbers"][0][0]
                    contracted = int(np.prod([lhs.shape[d] for d in dims])) if dims else 1
                else:
                    contracted = int(np.prod(eqn.invars[1].aval.shape[1:]))
                out[name] = out.get(name, 0.0) + 2.0 * float(np.prod(aval.shape)) * contracted
            for param in eqn.params.values():
                if hasattr(param, "eqns"):
                    visit(param)
                elif isinstance(param, (list, tuple)):
                    for p in param:
                        if hasattr(p, "eqns"):
                            visit(p)
                elif hasattr(param, "jaxpr") and hasattr(param.jaxpr, "eqns"):
                    visit(param.jaxpr)
    visit(jaxpr.jaxpr)
    return out


class FlopsProfiler:
    """Engine-attached profiler (reference FlopsProfiler; engine triggers at
    flops_profiler.profile_step)."""

    def __init__(self, model=None, engine=None):
        self.model = model
        self.engine = engine
        self.started = False
        self._t0 = 0.0
        self.flops: float = 0.0
        self.bytes_accessed: float = 0.0
        self.params: int = 0
        self.duration: float = 0.0

    def start_profile(self, ignore_list=None):
        from deepspeed_tpu.utils.timer import _sync

        self.started = True
        _sync()  # don't charge previously queued work to this profile
        self._t0 = time.time()

    def stop_profile(self):
        from deepspeed_tpu.utils.timer import _sync

        if self.started:
            _sync()  # drain async dispatch so duration is device compute
            self.duration = time.time() - self._t0
            self.started = False

    def profile_fn(self, fn: Callable, *args, **kwargs):
        """Compile+cost fn; record flops/bytes and a timed run."""
        cost, compiled = _cost_analysis(fn, *args, **kwargs)
        self.flops = float(cost.get("flops", 0.0))
        self.bytes_accessed = float(cost.get("bytes accessed", 0.0))
        out = compiled(*args, **kwargs)  # warmup (dispatch path)
        t0 = time.time()
        out = compiled(*args, **kwargs)
        # force a host transfer: block_until_ready can ack early on relayed
        # backends (see bench.py)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
        self.duration = time.time() - t0
        return out

    def get_total_flops(self, as_string: bool = False):
        return number_to_string(self.flops, "FLOPs") if as_string else self.flops

    def get_total_params(self, as_string: bool = False):
        return number_to_string(self.params, "") if as_string else self.params

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self.duration) if as_string else self.duration

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1, detailed=True, output_file=None):
        lines = [
            f"flops profiler @ step {profile_step}:",
            f"  params:   {self.get_total_params(True)}",
            f"  flops:    {self.get_total_flops(True)}",
            f"  bytes:    {number_to_string(self.bytes_accessed, 'B')}",
            f"  latency:  {self.get_total_duration(True)}",
        ]
        if self.duration > 0 and self.flops > 0:
            lines.append(f"  flops/s:  {number_to_string(self.flops / self.duration, 'FLOPS')}")
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "a") as fh:
                fh.write(text + "\n")
        else:
            log_dist(text, ranks=[0])

    def end_profile(self):
        self.stop_profile()


def get_model_profile(
    model=None,
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    input_shape: Optional[Tuple[int, ...]] = None,
    print_profile: bool = True,
    detailed: bool = True,
    as_string: bool = True,
    fn: Optional[Callable] = None,
) -> Tuple[Any, Any, Any]:
    """Standalone profile (reference get_model_profile :1112).

    Either pass ``fn``+``args`` (any jittable callable), or ``model`` with
    engine protocol (init/loss) and ``input_shape`` of int32 token batches.
    Returns (flops, macs, params) — strings if as_string.
    """
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    if fn is None:
        assert model is not None and input_shape is not None
        rng = jax.random.PRNGKey(0)
        params = jax.jit(model.init)(rng)
        prof.params = count_params(params)
        tokens = jax.numpy.zeros(input_shape, jax.numpy.int32)
        batch = {"input_ids": tokens, "labels": tokens}
        fn_, args_ = (lambda p, b: model.loss(p, b, None)), (params, batch)
    else:
        fn_, args_ = fn, args
        # convention: the first argument is the param pytree (loss(params,
        # batch) shape); counting every array arg would include batch inputs
        prof.params = count_params(args[0]) if args else 0
    prof.profile_fn(fn_, *args_, **kwargs)
    if print_profile:
        prof.print_model_profile(detailed=detailed)
        if detailed and fn is None and model is not None and hasattr(model, "cfg") and input_shape:
            print_component_table(
                component_breakdown(params, model.cfg, input_shape[0], input_shape[1])
            )
    flops = prof.get_total_flops(as_string)
    macs = number_to_string(prof.flops / 2, "MACs") if as_string else prof.flops / 2
    params_out = prof.get_total_params(as_string)
    return flops, macs, params_out


def component_breakdown(params, cfg, batch_size: int, seq_len: int) -> Dict[str, Dict[str, float]]:
    """Per-component params + forward-FLOPs table (the reference profiler's
    depth-wise module table, profiler.py:23 aggregated over hooks; here the
    components are the flagship tree's top-level subtrees and the FLOPs are
    the analytic matmul counts — XLA fusion dissolves module boundaries, so
    analytic per-component is the faithful equivalent)."""
    D = cfg.hidden_size
    L = cfg.num_layers
    V = cfg.vocab_size
    kvd = cfg.kv_heads * cfg.head_dim
    B, S = batch_size, seq_len
    tok = B * S

    def subtree_params(name):
        sub = params.get(name, {}) if isinstance(params, dict) else {}
        return count_params(sub)

    mlp_params_per_layer = (3 if cfg.activation == "silu_glu" else 2) * D * cfg.ffn_size
    if cfg.moe_num_experts > 0:
        mlp_params_per_layer = mlp_params_per_layer * cfg.moe_num_experts + D * cfg.moe_num_experts
    attn_matmul_params = 2 * D * D + 2 * D * kvd

    table = {
        "embed": {"params": subtree_params("embed"), "flops": 0.0},
        "attn (qkvo)": {"params": L * attn_matmul_params,
                        "flops": 2.0 * tok * L * attn_matmul_params},
        "attn (scores+pv)": {"params": 0,
                             "flops": 2.0 * 2.0 * B * S * S * D * L},
        "mlp": {"params": L * mlp_params_per_layer,
                "flops": 2.0 * tok * L * mlp_params_per_layer
                * (min(cfg.moe_top_k, cfg.moe_num_experts) / cfg.moe_num_experts
                   if cfg.moe_num_experts > 0 else 1.0)},
        "lm_head": {"params": subtree_params("lm_head"), "flops": 2.0 * tok * D * V},
    }
    total_flops = sum(row["flops"] for row in table.values())
    for row in table.values():
        row["flops_pct"] = 100.0 * row["flops"] / total_flops if total_flops else 0.0
    return table


def print_component_table(table: Dict[str, Dict[str, float]], output_file=None):
    lines = ["  component breakdown (fwd):"]
    for name, row in table.items():
        lines.append(
            f"    {name:<18} params={number_to_string(row['params'], ''):>10} "
            f"flops={number_to_string(row['flops'], 'FLOPs'):>12} ({row['flops_pct']:.1f}%)"
        )
    text = "\n".join(lines)
    if output_file:
        with open(output_file, "a") as fh:
            fh.write(text + "\n")
    else:
        log_dist(text, ranks=[0])


def number_to_string(num: float, unit: str = "") -> str:
    for mag, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(num) >= mag:
            return f"{num / mag:.2f} {suffix}{unit}"
    return f"{num:.2f} {unit}".rstrip()


def duration_to_string(seconds: float) -> str:
    if seconds >= 1:
        return f"{seconds:.2f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds * 1e6:.2f} us"
