"""Monitoring fan-out (reference: ``deepspeed/monitor/monitor.py``
``MonitorMaster`` + tensorboard/wandb/csv writers, rank-0 only)."""

import os
from typing import List, Tuple

from deepspeed_tpu.utils.logging import logger


class _Writer:
    enabled = False

    def write_events(self, events: List[Tuple[str, float, int]]):
        raise NotImplementedError

    def flush(self):
        pass

    def close(self):
        pass


class TensorBoardMonitor(_Writer):
    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception as e:  # pragma: no cover
            logger.warning(f"tensorboard unavailable ({e}); disabling TB monitor")
            return
        out = os.path.join(cfg.output_path or "./runs", cfg.job_name)
        self.writer = SummaryWriter(log_dir=out)
        self.enabled = True

    def write_events(self, events):
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)

    def flush(self):
        if self.enabled:
            self.writer.flush()


class CSVMonitor(_Writer):
    def __init__(self, cfg):
        self.enabled = cfg.enabled
        self._files = {}  # before the early return: flush()/close() iterate it
        if not self.enabled:
            return
        self.dir = os.path.join(cfg.output_path or "./csv_logs", cfg.job_name)
        os.makedirs(self.dir, exist_ok=True)

    def write_events(self, events):
        import csv

        touched = set()
        for name, value, step in events:
            fh = self._files.get(name)
            if fh is None:
                fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
                header = not os.path.exists(fname) or os.path.getsize(fname) == 0
                fh = self._files[name] = open(fname, "a", newline="")
                if header:
                    csv.writer(fh).writerow(["step", name])
            csv.writer(fh).writerow([step, value])
            touched.add(name)
        # rows are durable per batch (readers tail these files mid-run);
        # the win over the old code is one open() per metric, not per event
        for name in touched:
            self._files[name].flush()

    def flush(self):
        for fh in self._files.values():
            fh.flush()

    def close(self):
        for fh in self._files.values():
            fh.close()
        self._files.clear()


class WandbMonitor(_Writer):
    def __init__(self, cfg):
        self.enabled = False
        if not cfg.enabled:
            return
        try:
            import wandb
        except Exception:
            logger.warning("wandb not installed; disabling wandb monitor")
            return
        wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team)
        self.wandb = wandb
        self.enabled = True

    def write_events(self, events):
        for name, value, step in events:
            self.wandb.log({name: value}, step=step)


class MonitorMaster(_Writer):
    def __init__(self, config):
        import jax

        self.writers = []
        if jax.process_index() == 0:
            for w in (
                TensorBoardMonitor(config.tensorboard),
                CSVMonitor(config.csv_monitor),
                WandbMonitor(config.wandb),
            ):
                if w.enabled:
                    self.writers.append(w)
        self.enabled = bool(self.writers)

    def write_events(self, events):
        for w in self.writers:
            w.write_events(events)

    def flush(self):
        for w in self.writers:
            w.flush()

    def close(self):
        for w in self.writers:
            w.close()
