"""Elastic restart flow: membership change -> reshard -> resume.

TPU-native counterpart of the reference's elasticity v2
(``elasticity/elastic_agent.py:28`` DSElasticAgent, ``_invoke_run:118`` —
torchelastic restarts every worker on membership change and the job reloads
its checkpoint). On TPU there is no per-GPU worker pool to restart: a rescale
event means the pod slice changed, so the flow is

  1. recompute the batch triad for the new chip count with the v1 elastic
     batch math (``compute_elastic_config`` — same batch size stays valid,
     GAS absorbs the change),
  2. convert the latest engine checkpoint to the universal layout
     (``checkpoint/ds_to_universal``) — mesh-shape-free fp32 tensors,
  3. rebuild mesh + engine at the new world size and restore master weights
     and optimizer state exactly (``load_universal_into_engine``).

``elastic_resume`` is that flow as one call; the ``dstpu`` launcher invokes
it when started with ``--elastic`` after a rescale.
"""

import os
from typing import Any, Dict, Optional

from deepspeed_tpu.elasticity.elasticity import ElasticityError, compute_elastic_config
from deepspeed_tpu.utils.logging import logger


def maybe_elastic_resume(ds_config: Dict[str, Any], **kwargs):
    """The per-process half of ``dstpu --elastic`` (launcher/runner.py):
    when the launcher exported DSTPU_ELASTIC and a checkpoint directory is
    known, resume on however many chips this incarnation sees. Returns the
    resumed engine, or None when not launched elastically / nothing to
    resume from."""
    if os.environ.get("DSTPU_ELASTIC") != "1":
        return None
    # try every known checkpoint location, not just the first configured one
    # (a rescaled host may be missing the launcher-named mount while the
    # config's dir is present locally)
    candidates = [
        os.environ.get("DSTPU_ELASTIC_CKPT", ""),
        ds_config.get("checkpoint", {}).get("dir", ""),
    ]
    ckpt = next((c for c in candidates if c and os.path.isdir(c)), "")
    if not ckpt:
        logger.warning(
            f"DSTPU_ELASTIC set but no checkpoint dir exists (tried {[c for c in candidates if c]}) — cold start"
        )
        return None
    import jax

    try:
        return elastic_resume(ds_config, ckpt, new_world_size=jax.device_count(), **kwargs)
    except ElasticityError as e:
        logger.warning(f"elastic resume unavailable ({e}) — cold start")
        return None


def rescale_config(ds_config: Dict[str, Any], new_world_size: int) -> Dict[str, Any]:
    """Return a copy of ``ds_config`` with the batch triad recomputed for
    ``new_world_size`` chips via the elastic candidates (reference
    elasticity.py:233). Raises ElasticityIncompatibleWorldSize when the
    chip count cannot divide any valid configuration."""
    final_batch, _valid, micro = compute_elastic_config(ds_config, world_size=new_world_size)
    cfg = dict(ds_config)
    cfg["train_batch_size"] = final_batch
    cfg["train_micro_batch_size_per_gpu"] = micro
    cfg["gradient_accumulation_steps"] = final_batch // (micro * new_world_size)
    logger.info(
        f"elastic rescale to {new_world_size} chips: batch={final_batch} "
        f"micro={micro} gas={cfg['gradient_accumulation_steps']}"
    )
    return cfg


def elastic_resume(
    ds_config: Dict[str, Any],
    checkpoint_dir: str,
    new_world_size: int,
    mesh_shape: Optional[Dict[str, int]] = None,
    tag: Optional[str] = None,
    model=None,
    loss_fn=None,
    params=None,
    devices=None,
    load_optimizer_states: bool = True,
):
    """One-call membership-change restart (reference elastic_agent.py:118).

    Saves nothing itself: call after the *previous* incarnation has written a
    checkpoint. Returns the resumed engine on the new mesh. ``mesh_shape``
    defaults to all chips on the fsdp axis. ``devices`` restricts the mesh to
    a subset of local devices (a shrunk slice where the process still sees
    the old chips; also how tests rescale on one host)."""
    import deepspeed_tpu
    from deepspeed_tpu import comm
    from deepspeed_tpu.checkpoint import ds_to_universal, load_universal_into_engine

    cfg = rescale_config(ds_config, new_world_size)
    cfg["mesh"] = mesh_shape or {"data": 1, "fsdp": new_world_size}

    uni_dir = os.path.join(checkpoint_dir, "universal")
    manifest_path = os.path.join(uni_dir, "universal_manifest.json")
    if not os.path.exists(manifest_path):
        ds_to_universal(checkpoint_dir, uni_dir, tag=tag)

    comm.destroy()
    if devices is None:
        import jax

        devices = jax.devices()[:new_world_size] if len(jax.devices()) > new_world_size else None
    mesh = comm.init_distributed(mesh_shape=cfg["mesh"], devices=devices, verbose=False)
    os.environ["_DSTPU_ELASTIC_ACTIVE"] = "1"  # guard: initialize() must not re-enter us
    try:
        engine, *_ = deepspeed_tpu.initialize(
            model=model, loss_fn=loss_fn, params=params, config=cfg, mesh=mesh
        )
    finally:
        os.environ.pop("_DSTPU_ELASTIC_ACTIVE", None)
    load_universal_into_engine(engine, uni_dir, load_optimizer_states=load_optimizer_states)
    logger.info(
        f"elastic resume complete: world={new_world_size} "
        f"global_steps={engine.global_steps} mesh={dict(engine.mesh.shape)}"
    )
    return engine
