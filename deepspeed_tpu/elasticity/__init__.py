from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    get_best_candidate_batch_size,
    get_valid_gpus,
)

__all__ = [
    "ElasticityConfig",
    "ElasticityConfigError",
    "ElasticityError",
    "ElasticityIncompatibleWorldSize",
    "compute_elastic_config",
    "get_best_candidate_batch_size",
    "get_valid_gpus",
]
