from deepspeed_tpu.elasticity.elastic_agent import elastic_resume, maybe_elastic_resume, rescale_config
from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    get_best_candidate_batch_size,
    get_valid_gpus,
)

__all__ = [
    "ElasticityConfig",
    "ElasticityConfigError",
    "ElasticityError",
    "ElasticityIncompatibleWorldSize",
    "compute_elastic_config",
    "elastic_resume",
    "maybe_elastic_resume",
    "get_best_candidate_batch_size",
    "get_valid_gpus",
    "rescale_config",
]
