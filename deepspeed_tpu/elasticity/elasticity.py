"""Elastic training batch-size math.

TPU-native counterpart of the reference's elasticity v1
(elasticity/elasticity.py:233 ``compute_elastic_config``,
``get_compatible_gpus``): choose a ``train_batch_size`` that stays valid for
every chip count in [min, max], so checkpoints survive rescaling of the pod
slice. The algorithm is the reference's: enumerate candidate batch sizes as
micro_batch x power-of-two accumulation steps up to the cap, score by
(divisible chip counts, batch size), pick the best.

v2 (torchelastic agent restarts) maps to re-running the dstpu launcher on the
new slice and resuming from the universal checkpoint — the resharding that
torchelastic needs agent machinery for is a plain restore here
(checkpoint/universal_checkpoint.py).
"""

from typing import Dict, List, Tuple

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parsed 'elasticity' config block (reference elasticity/config.py)."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get("enabled", False)
        self.max_train_batch_size = int(param_dict.get("max_train_batch_size", 2000))
        mbs = param_dict.get("micro_batch_sizes", [2, 4, 6])
        self.micro_batches = [int(m) for m in mbs]
        if any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"micro_batch_sizes must be positive: {self.micro_batches}")
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(f"bad gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = int(param_dict.get("min_time", 0))
        self.version = float(param_dict.get("version", LATEST_ELASTICITY_VERSION))
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get("ignore_non_elastic_batch_info", False)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_gpus: int, max_gpus: int) -> List[int]:
    """Chip counts that evenly consume ``batch_size`` with some micro batch
    (reference elasticity.py get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_steps = batch_size // mb
        for ngpu in range(min_gpus, min(max_gpus, max_steps) + 1):
            if max_steps % ngpu == 0:
                valid.add(ngpu)
    return sorted(valid)


def get_best_candidate_batch_size(
    max_batch: int, micro_batches: List[int], min_gpus: int, max_gpus: int, prefer_larger: bool = True
) -> Tuple[int, List[int]]:
    """Search candidate batch sizes (micro x 2^k, and micro x max_acc grid),
    maximizing the number of valid chip counts (reference
    _get_compatible_gpus_v01)."""
    candidates = set()
    for mb in micro_batches:
        steps = 1
        while mb * steps <= max_batch:
            candidates.add(mb * steps)
            steps *= 2
        if max_batch >= mb:
            candidates.add((max_batch // mb) * mb)
    best: Tuple[int, int] = (-1, -1)  # (num_valid, batch)
    best_valid: List[int] = []
    for batch in sorted(candidates, reverse=prefer_larger):
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        key = (len(valid), batch if prefer_larger else -batch)
        if key > (best[0], best[1] if prefer_larger else -best[1]):
            best = (len(valid), batch)
            best_valid = valid
    if best[1] < 0 or not best_valid:
        raise ElasticityConfigError(
            f"no feasible batch size <= {max_batch} for micro batches {micro_batches} "
            f"with chip range [{min_gpus}, {max_gpus}]"
        )
    return best[1], best_valid


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "", world_size: int = 0):
    """Reference API (elasticity.py:233): returns
    (final_batch_size, valid_gpus, micro_batch_per_gpu[, gradient_accumulation]).
    If ``world_size`` > 0, also validates it and resolves the micro batch."""
    block = ds_config.get("elasticity")
    if block is None:
        raise ElasticityConfigError("'elasticity' block missing from config")
    cfg = ElasticityConfig(block)
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity.enabled is false")
    if cfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(f"unsupported elasticity version {cfg.version}")

    final_batch, valid_gpus = get_best_candidate_batch_size(
        cfg.max_train_batch_size, cfg.micro_batches, cfg.min_gpus, cfg.max_gpus,
        prefer_larger=cfg.prefer_larger_batch_size,
    )
    if world_size <= 0:
        return final_batch, valid_gpus, None
    if world_size not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in elastic-compatible counts {valid_gpus}"
        )
    # largest micro batch that fits: batch = micro * gas * world
    for mb in sorted(cfg.micro_batches, reverse=True):
        if final_batch % (mb * world_size) == 0:
            return final_batch, valid_gpus, mb
    raise ElasticityIncompatibleWorldSize(
        f"no micro batch in {cfg.micro_batches} divides {final_batch} over {world_size} chips"
    )
