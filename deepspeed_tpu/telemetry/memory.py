"""HBM memory accounting: attribute device bytes to named components
(params / KV cache / optimizer state / scratch) from live arrays, and
export the attribution as ``hbm_bytes{component=...}`` gauges plus a
``memory_snapshot`` trace event (emitted on engine build, rebuild, and
bucket migration).

All byte math here is PER-CHIP and metadata-only: a leaf's contribution
is its shard shape under its actual sharding (``sharding.shard_shape``)
times the dtype width — no device buffer is touched, no fetch happens,
and the numbers are exact on both the virtual CPU mesh and real TPUs
(each device of a NamedSharding holds exactly one shard; replicated
leaves contribute their full size). Per-chip is the quantity that
matters: HBM pressure is per device, and the admission headroom a
serving replica consults is the headroom of its fullest chip.

``scratch`` is the live residual ``bytes_in_use - sum(components)`` when
the backend reports allocator stats (TPU does; the CPU backend does
not), i.e. everything the accountant cannot attribute — XLA temp
buffers, donated-copy slack, other engines in the process. On backends
without allocator stats the component is simply absent rather than
guessed. :func:`program_memory` additionally reads a compiled program's
``memory_analysis()`` (temp/argument/output bytes) where the backend
implements it — the per-program-family view of scratch.
"""

import sys
from typing import Dict, Optional

import numpy as np


def leaf_device_bytes(leaf) -> int:
    """Bytes ONE device holds of ``leaf`` — the per-shard footprint under
    the leaf's actual sharding. 0 for host (numpy) leaves and anything
    without a device placement. Metadata-only: never blocks, never
    fetches, safe on in-flight (async-dispatched) arrays."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or not hasattr(leaf, "dtype"):
        return 0  # host array / non-array leaf: not HBM
    try:
        shard_shape = sharding.shard_shape(leaf.shape)
    except Exception:  # noqa: BLE001 — exotic shardings fall back to global
        shard_shape = leaf.shape
    return int(np.prod(shard_shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize


def tree_device_bytes(tree) -> int:
    """Per-chip bytes of a whole pytree (params, a KV cache, opt state);
    int8-quantized ``{"q8", "s"}`` leaves are plain leaves here."""
    import jax

    return sum(leaf_device_bytes(leaf) for leaf in jax.tree.leaves(tree))


def device_memory_limit(override_bytes: int = 0) -> Optional[int]:
    """Per-device memory capacity for the headroom gauge: the explicit
    telemetry override when set, else the backend allocator's
    ``bytes_limit`` (TPU), else None (unknown — the CPU virtual mesh has
    no meaningful HBM limit unless the config declares one)."""
    if override_bytes:
        return int(override_bytes)
    if "jax" not in sys.modules:
        # no jax in this process means no live devices to ask — a
        # host-only fleet router must not pay the jax import just to
        # read a limit that cannot exist
        return None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        return limit or None
    except Exception:  # noqa: BLE001 — stats are strictly best-effort
        return None


def device_bytes_in_use() -> Optional[int]:
    """Live allocator ``bytes_in_use`` on device 0, or None where the
    backend keeps no stats (CPU) — feeds the ``scratch`` residual."""
    if "jax" not in sys.modules:
        return None  # host-only process: no allocator, no import
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        used = int(stats.get("bytes_in_use", 0))
        return used or None
    except Exception:  # noqa: BLE001 — stats are strictly best-effort
        return None


def program_memory(compiled) -> Dict[str, int]:
    """Per-program memory attribution from a compiled executable's
    ``memory_analysis()`` — temp (scratch), argument, output, and code
    bytes. Empty dict where the backend does not implement the analysis
    (jax CPU) so callers can merge it opportunistically."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — optional backend surface
        return {}
    if mem is None:
        return {}
    out = {}
    for field, name in (("temp_size_in_bytes", "temp_bytes"),
                        ("argument_size_in_bytes", "argument_bytes"),
                        ("output_size_in_bytes", "output_bytes"),
                        ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(mem, field, None)
        if isinstance(v, int):
            out[name] = v
    return out


def emit_snapshot(telemetry, components: Dict[str, int], reason: str,
                  programs: Optional[Dict[str, dict]] = None) -> Optional[dict]:
    """Export one HBM attribution: set the ``hbm_bytes{component=...}`` /
    ``hbm_total_bytes`` / ``hbm_headroom_bytes`` gauges and emit a
    ``memory_snapshot`` trace event. ``reason`` names the trigger
    (``build`` / ``rebuild`` / ``migration``). When the allocator reports
    live usage above the attributed total, the residual lands in a
    ``scratch`` component. Returns the emitted event (None when
    telemetry is disabled)."""
    if telemetry is None or not telemetry.enabled:
        return None
    components = {k: int(v) for k, v in components.items()}
    total = sum(components.values())
    in_use = device_bytes_in_use()
    if in_use is not None and in_use > total:
        components["scratch"] = in_use - total
        total = in_use
    reg = telemetry.registry
    for name, b in components.items():
        reg.gauge("hbm_bytes", {"component": name}).set(b)
    reg.gauge("hbm_total_bytes").set(total)
    event = {"reason": reason, "total_bytes": total, "components": components}
    limit = device_memory_limit(getattr(telemetry.cfg, "hbm_limit_bytes", 0))
    if limit:
        headroom = limit - total
        event["limit_bytes"] = limit
        event["headroom_bytes"] = headroom
        reg.gauge("hbm_headroom_bytes").set(headroom)
    if programs:
        event["programs"] = programs
    telemetry.emit("memory_snapshot", event)
    return event


def headroom_bytes(telemetry, components: Dict[str, int]) -> Optional[int]:
    """Point-in-time headroom (limit - attributed-or-live bytes) for the
    admission path / ``/statusz`` — same math as :func:`emit_snapshot`
    without touching gauges or the trace. None when no limit is known."""
    limit = device_memory_limit(
        getattr(getattr(telemetry, "cfg", None), "hbm_limit_bytes", 0)
        if telemetry is not None else 0)
    if not limit:
        return None
    total = sum(int(v) for v in components.values())
    in_use = device_bytes_in_use()
    if in_use is not None and in_use > total:
        total = in_use
    return limit - total
