"""Per-request timeline reconstruction from ``"span"`` trace events.

The write side (``telemetry/spans.py`` + the serving/inference/router/
train emit sites) records request-scoped spans into the same JSONL trace
every other telemetry event rides: one ``kind: "span"`` line per closed
span, carrying ``trace_id`` (the request identity — stable across
migration and engine rebuilds), ``span_id`` / ``parent_id`` causality,
and a monotonic-clock ``t0``/``t1`` window. This module is the READ
side: group spans by trace_id, stitch the parent/child tree (a
``migration`` span bridges replica tags, so one trace_id reconstructs
across engine generations), find orphans, and attribute each request's
wall time to the span kind that dominated it — the "why is THIS request
slow" answer the aggregate tables cannot give.

Deliberately stdlib-only and self-contained (no intra-package imports):
``tools/ds_trace_report.py`` / ``tools/ds_trace_timeline.py`` load this
file by path so the CLIs stay runnable off-pod, and the jax-free CI
stage imports it under the namespace-stubbed package. The span-kind
tables live HERE for that reason; ``telemetry/spans.py`` (the write
side) imports them from this module, never the reverse.
"""

import json
from typing import Dict, Iterable, List, Optional

# Every span kind the stack emits. Serving request lifecycle: queue
# (submit -> handover), admission (the handover/engine-submit work),
# then per-tick windows (prefill_chunk / decode_window /
# spec_verify_round) from the continuous engine's retire path.
# Cross-replica: migration (router-emitted, bridges the dead replica's
# spans to the survivor's). Recovery: recovery_replay (in-process
# rebuild re-admission). Ops: drain_wait (drain() -> queue dry).
# Training reuses the same model: train_step / train_retry /
# train_rebuild under a ``step:N`` trace_id.
SPAN_KINDS = (
    "queue",
    "admission",
    "prefill_chunk",
    "decode_window",
    "spec_verify_round",
    "migration",
    "recovery_replay",
    "drain_wait",
    "train_step",
    "train_retry",
    "train_rebuild",
)

# Coarse queue-vs-compute-vs-recovery attribution for the blame tables.
SPAN_CATEGORY = {
    "queue": "queue",
    "drain_wait": "queue",
    "admission": "compute",
    "prefill_chunk": "compute",
    "decode_window": "compute",
    "spec_verify_round": "compute",
    "train_step": "compute",
    "migration": "recovery",
    "recovery_replay": "recovery",
    "train_retry": "recovery",
    "train_rebuild": "recovery",
}


class Span:
    """One closed span parsed off a trace event."""

    __slots__ = ("trace_id", "span_id", "parent_id", "kind", "t0", "t1",
                 "replica", "attrs", "ts")

    def __init__(self, event: dict):
        self.trace_id = str(event["trace_id"])
        self.span_id = str(event["span_id"])
        parent = event.get("parent_id")
        self.parent_id = str(parent) if parent is not None else None
        self.kind = str(event["span"])
        self.t0 = float(event["t0"])
        self.t1 = max(float(event["t1"]), self.t0)
        self.replica = event.get("replica")
        self.attrs = event.get("attrs") or {}
        self.ts = event.get("ts")

    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0

    def __repr__(self):  # pragma: no cover — debugging aid
        return (f"Span({self.kind} {self.span_id} trace={self.trace_id} "
                f"[{self.t0:.6f},{self.t1:.6f}])")


class Timeline:
    """All spans of one trace_id, stitched into a parent/child forest.

    ``orphans`` lists spans whose ``parent_id`` names a span_id absent
    from the trace — causality the writer claimed but the file cannot
    back (a missed migration stitch, a rotated-away parent). A clean
    reconstruction has zero."""

    def __init__(self, trace_id: str, spans: List[Span]):
        self.trace_id = trace_id
        self.spans = sorted(spans, key=lambda s: (s.t0, s.t1, s.span_id))
        self.by_id = {s.span_id: s for s in self.spans}
        self.orphans = [s for s in self.spans
                        if s.parent_id is not None
                        and s.parent_id not in self.by_id]
        self.roots = [s for s in self.spans if s.parent_id is None]

    @property
    def t_start(self) -> float:
        return min(s.t0 for s in self.spans)

    @property
    def t_end(self) -> float:
        return max(s.t1 for s in self.spans)

    @property
    def duration_ms(self) -> float:
        return (self.t_end - self.t_start) * 1000.0

    @property
    def replicas(self) -> List[str]:
        """Replica tags touched, in first-seen (time) order."""
        seen = []
        for s in self.spans:
            if s.replica is not None and s.replica not in seen:
                seen.append(s.replica)
        return seen

    def depth(self, span: Span) -> int:
        """Ancestor count via parent links (root = 0); an orphan's chain
        stops at the missing parent."""
        d, cur, hops = 0, span, 0
        while cur.parent_id is not None and hops <= len(self.spans):
            nxt = self.by_id.get(cur.parent_id)
            if nxt is None:
                break
            d += 1
            cur = nxt
            hops += 1
        return d

    def children(self, span_id: str) -> List[Span]:
        return [s for s in self.spans if s.parent_id == span_id]

    # -- attribution ----------------------------------------------------
    def critical_path(self) -> Dict[str, float]:
        """{span kind: ms} — every instant of [t_start, t_end] charged to
        the DEEPEST span covering it (ties: the later-starting one — the
        most specific work running then). Instants no span covers are
        charged to ``"gap"``. Sums exactly to ``duration_ms``."""
        if not self.spans:
            return {}
        cuts = sorted({t for s in self.spans for t in (s.t0, s.t1)})
        out: Dict[str, float] = {}
        for lo, hi in zip(cuts, cuts[1:]):
            if hi <= lo:
                continue
            covering = [s for s in self.spans if s.t0 <= lo and s.t1 >= hi]
            if covering:
                best = max(covering, key=lambda s: (self.depth(s), s.t0))
                kind = best.kind
            else:
                kind = "gap"
            out[kind] = out.get(kind, 0.0) + (hi - lo) * 1000.0
        return out

    def attribution(self) -> Dict[str, float]:
        """Critical-path ms folded to queue / compute / recovery / gap."""
        out: Dict[str, float] = {}
        for kind, ms in self.critical_path().items():
            cat = SPAN_CATEGORY.get(kind, "gap")
            out[cat] = out.get(cat, 0.0) + ms
        return out

    def dominant_kind(self) -> Optional[str]:
        """The span kind holding the most critical-path time (gap
        excluded unless it is all there is)."""
        path = self.critical_path()
        real = {k: v for k, v in path.items() if k != "gap"}
        pool = real or path
        if not pool:
            return None
        return max(sorted(pool), key=lambda k: pool[k])


def iter_events(path: str) -> Iterable[dict]:
    """Parsed events off a JSONL trace, torn/malformed lines skipped —
    the same tolerance as ``telemetry.trace.read_trace`` (duplicated
    here so this module stays loadable by file path, off-repo)."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                yield ev


def spans_of(events: Iterable[dict]) -> List[Span]:
    out = []
    for ev in events:
        if ev.get("kind") != "span":
            continue
        try:
            out.append(Span(ev))
        except (KeyError, TypeError, ValueError):
            continue  # torn span line: same tolerance as read_trace
    return out


def build_timelines(events: Iterable[dict]) -> Dict[str, Timeline]:
    """{trace_id: Timeline} over every span event in the iterable."""
    grouped: Dict[str, List[Span]] = {}
    for span in spans_of(events):
        grouped.setdefault(span.trace_id, []).append(span)
    return {tid: Timeline(tid, spans) for tid, spans in grouped.items()}


def slo_blame(events: Iterable[dict],
              timelines: Optional[Dict[str, Timeline]] = None) -> List[dict]:
    """SLO-miss blame rows: join ``inference_request`` events that missed
    their deadline (``deadline_met: false``) with their reconstructed
    timeline's dominant span kind. Rows sorted worst-first by ttft."""
    events = list(events)
    if timelines is None:
        timelines = build_timelines(events)
    rows = []
    for ev in events:
        if ev.get("kind") != "inference_request":
            continue
        if ev.get("deadline_met") is not False:
            continue
        tid = ev.get("trace_id")
        tl = timelines.get(str(tid)) if tid is not None else None
        rows.append({
            "trace_id": str(tid) if tid is not None else None,
            "request": ev.get("request"),
            "tenant": ev.get("tenant"),
            "deadline_ms": ev.get("deadline_ms"),
            "ttft_ms": ev.get("ttft_ms"),
            "queue_ms": ev.get("queue_ms"),
            "dominant": tl.dominant_kind() if tl is not None else None,
            "attribution": tl.attribution() if tl is not None else None,
            "replicas": tl.replicas if tl is not None else [],
        })
    rows.sort(key=lambda r: -(r["ttft_ms"] or 0.0))
    return rows


# -- Chrome-trace / Perfetto export -------------------------------------

def to_chrome_trace(timelines: Dict[str, Timeline]) -> dict:
    """Chrome trace-event JSON (the format Perfetto / chrome://tracing
    load): one complete (``ph: "X"``) event per span, microsecond
    timestamps rebased to the earliest span in the export, one pid per
    replica tag (spans with no tag share pid 0), one tid per trace_id —
    so a migrated request renders as the SAME thread lane crossing
    process (replica) groups. ``process_name`` / ``thread_name``
    metadata events label the lanes."""
    all_spans = [s for tl in timelines.values() for s in tl.spans]
    if not all_spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    origin = min(s.t0 for s in all_spans)
    replicas = sorted({s.replica for s in all_spans if s.replica is not None})
    pid_of = {rep: i + 1 for i, rep in enumerate(replicas)}
    tid_of = {tid: i + 1 for i, tid in enumerate(sorted(timelines))}
    events = []
    for rep, pid in [(None, 0)] + sorted(pid_of.items()):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": rep if rep is not None else "unscoped"}})
    for tid_str, tl in sorted(timelines.items()):
        for pid in sorted({pid_of.get(s.replica, 0) for s in tl.spans}):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid_of[tid_str],
                "args": {"name": f"trace {tid_str}"}})
    for s in sorted(all_spans, key=lambda s: (s.t0, s.t1, s.span_id)):
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        events.append({
            "name": s.kind,
            "cat": SPAN_CATEGORY.get(s.kind, "other"),
            "ph": "X",
            "ts": round((s.t0 - origin) * 1e6, 3),
            "dur": round((s.t1 - s.t0) * 1e6, 3),
            "pid": pid_of.get(s.replica, 0),
            "tid": tid_of[s.trace_id],
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(doc: dict) -> List[str]:
    """Structural lint for an export (the golden-format gate): returns
    human-readable problems, empty when the document is loadable
    trace-event JSON."""
    problems = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["not a trace-event document (no traceEvents key)"]
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            problems.append(f"event {i}: unexpected ph {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i}: missing {field}")
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)) or ev["ts"] < 0:
                problems.append(f"event {i}: bad ts {ev.get('ts')!r}")
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                problems.append(f"event {i}: bad dur {ev.get('dur')!r}")
    return problems
