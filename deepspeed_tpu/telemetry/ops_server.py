"""Live ops plane: a stdlib-only threaded HTTP exporter for one serving
replica (or training engine) — the scrape surface an operator or the
fleet router reads WITHOUT stopping the process.

Endpoints:

- ``/metrics`` — Prometheus text exposition (format 0.0.4) rendered from
  a :class:`MetricsRegistry` dump: counters and gauges verbatim,
  histograms as summaries (``_count``/``_sum`` plus p50/p95 as
  ``quantile``-labeled sample lines). Metric names sanitize to the
  Prometheus charset (dots from ``<kind>.<field>`` histograms become
  underscores); label values escape per the exposition rules.
- ``/healthz`` — ``{"status": ...}``; HTTP 200 only for ``"ok"``.
  ``"recovering"`` / ``"poisoned"`` / ``"draining"`` answer 503 so a
  load balancer's readiness probe fails exactly when the replica must
  not take traffic (draining IS the point of drain()).
- ``/statusz`` — one JSON object from the ``status`` callback
  (``ServingEngine.statusz()``: slots, queue depth, committed KV
  tokens, in-flight depth, tick overlap, recovery generation, uptime).

The server runs on a daemon thread and never blocks the tick loop: every
handler only READS (a registry dump under its own lock, atomic-copy
snapshots of serving state), and a callback that raises answers 500
instead of propagating into the serving process. Deliberately
jax-free and dependency-free — importable (and testable) anywhere.

    srv = ServingEngine(engine, ...)
    ops = srv.start_ops_server(port=0)       # 0 = ephemeral
    print(ops.url)                           # http://127.0.0.1:NNNNN
    # curl $URL/metrics | grep serve_queue_depth
"""

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Union

# statuses whose readiness probe must FAIL (everything except "ok"):
# recovering (circuit breaker open), poisoned (engine state untrusted,
# no recovery armed), draining (operator removing the replica)
HEALTHY = "ok"


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    """Label-value escaping per the exposition format: backslash, quote,
    newline."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _parse_key(key: str):
    """Invert ``registry.metric_key``: ``name{k=v,...}`` -> (name, labels).
    Registry label values never contain ``,``/``=`` (they are enum-ish
    strings: component/family/kind/outcome), so the plain split is exact."""
    if "{" not in key or not key.endswith("}"):
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        k, _, v = part.partition("=")
        labels[k] = v
    return name, labels


def _label_str(labels: dict, quantile: Optional[str] = None) -> str:
    items = [(k, labels[k]) for k in sorted(labels)]
    if quantile is not None:
        items.append(("quantile", quantile))
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in items) + "}"


def _num(v) -> str:
    v = float(v)
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(dump: dict) -> str:
    """Prometheus text format from a ``MetricsRegistry.dump()`` dict.

    Counters/gauges render one sample per labeled key; histograms render
    as summaries — ``quantile``-labeled p50/p95 sample lines plus
    ``_count``/``_sum`` — since the registry keeps a percentile reservoir,
    not fixed buckets. Output is deterministic: metric names sorted, then
    label sets sorted, labels within a set sorted (quantile last)."""
    lines = []
    for section, ptype in (("counters", "counter"), ("gauges", "gauge")):
        grouped = {}
        for key, value in dump.get(section, {}).items():
            name, labels = _parse_key(key)
            grouped.setdefault(_sanitize(name), []).append((labels, value))
        for name in sorted(grouped):
            lines.append(f"# TYPE {name} {ptype}")
            for labels, value in sorted(grouped[name],
                                        key=lambda lv: _label_str(lv[0])):
                lines.append(f"{name}{_label_str(labels)} {_num(value)}")
    grouped = {}
    for key, snap in dump.get("histograms", {}).items():
        name, labels = _parse_key(key)
        grouped.setdefault(_sanitize(name), []).append((labels, snap))
    for name in sorted(grouped):
        lines.append(f"# TYPE {name} summary")
        for labels, snap in sorted(grouped[name],
                                   key=lambda lv: _label_str(lv[0])):
            for q, field in (("0.5", "p50"), ("0.95", "p95")):
                lines.append(f"{name}{_label_str(labels, q)} "
                             f"{_num(snap.get(field, 0.0))}")
            lines.append(f"{name}_sum{_label_str(labels)} "
                         f"{_num(snap.get('sum', 0.0))}")
            lines.append(f"{name}_count{_label_str(labels)} "
                         f"{_num(snap.get('count', 0))}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "dstpu-ops/1"

    def _respond(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        ops: "OpsServer" = self.server.ops  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = render_prometheus(ops.registry_dump()).encode("utf-8")
                self._respond(200, body,
                              "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                status = ops.health()
                body = json.dumps({"status": status}).encode("utf-8")
                self._respond(200 if status == HEALTHY else 503, body,
                              "application/json")
            elif path == "/statusz":
                body = json.dumps(ops.status(), default=str,
                                  sort_keys=True).encode("utf-8")
                self._respond(200, body, "application/json")
            else:
                self._respond(404, b'{"error": "unknown endpoint"}',
                              "application/json")
        except Exception as e:  # noqa: BLE001 — a broken callback must
            # answer 500, never propagate into (or kill) the serving thread
            body = json.dumps({"error": f"{type(e).__name__}: {e}"})
            try:
                self._respond(500, body.encode("utf-8"), "application/json")
            except OSError:
                pass  # client went away mid-error: nothing left to tell it

    def log_message(self, *args):
        """Silence the default stderr access log: scrape traffic must not
        interleave with the serving process's own output."""


class OpsServer:
    """Threaded HTTP exporter over a metrics registry + health/status
    callbacks. ``registry`` is a :class:`MetricsRegistry` (its ``dump()``
    is called per scrape) or a zero-arg callable returning a dump-shaped
    dict. ``port=0`` binds an ephemeral port (read it back from
    ``.port`` / ``.url``)."""

    def __init__(self, registry: Union[object, Callable[[], dict], None] = None,
                 health: Optional[Callable[[], str]] = None,
                 status: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._registry = registry
        self._health = health
        self._status = status
        self._host = host
        self._want_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- handler callbacks ---------------------------------------------
    def registry_dump(self) -> dict:
        reg = self._registry
        if reg is None:
            return {}
        if callable(reg):
            return reg()
        return reg.dump()

    def health(self) -> str:
        return self._health() if self._health is not None else HEALTHY

    def status(self) -> dict:
        return self._status() if self._status is not None else {}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "OpsServer":
        if self._httpd is not None:
            return self  # already serving: idempotent
        httpd = ThreadingHTTPServer((self._host, self._want_port), _Handler)
        httpd.daemon_threads = True  # scrapes never pin process exit
        httpd.ops = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="dstpu-ops-server", daemon=True,
            kwargs={"poll_interval": 0.1})
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        assert self._httpd is not None, "start() the server first"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def close(self):
        """Stop serving and release the port. Idempotent; safe to call
        from shutdown paths (never raises)."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except Exception:  # noqa: BLE001 — shutdown must not raise
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
