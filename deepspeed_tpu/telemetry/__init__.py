"""Unified telemetry layer: labeled metrics, structured JSONL step/request
traces, MFU accounting, and jax.profiler capture hooks.

Entry points:
  - :class:`Telemetry` — per-engine hub (``TpuEngine.telemetry``,
    ``InferenceEngine.telemetry``), built from the ``telemetry`` config
    block (default off).
  - :class:`MetricsRegistry` — standalone counters/gauges/histograms/spans.
  - :class:`TraceWriter` / :func:`read_trace` — the JSONL format
    (``"schema": 1``) consumed by ``tools/ds_trace_report.py``.
"""

from deepspeed_tpu.telemetry.compile_log import CompileRecorder
from deepspeed_tpu.telemetry.config import TelemetryConfig
from deepspeed_tpu.telemetry.ops_server import OpsServer, render_prometheus
from deepspeed_tpu.telemetry.registry import MetricsRegistry, metric_key, percentile
from deepspeed_tpu.telemetry.telemetry import Telemetry
from deepspeed_tpu.telemetry.trace import SCHEMA_VERSION, TraceWriter, read_trace

# deepspeed_tpu.telemetry.memory (the HBM accountant) is deliberately NOT
# imported here: it touches jax, and this package must stay importable by
# the jax-free tools (ds_trace_report, the ops-server tests).

__all__ = [
    "Telemetry",
    "TelemetryConfig",
    "MetricsRegistry",
    "TraceWriter",
    "read_trace",
    "metric_key",
    "percentile",
    "SCHEMA_VERSION",
    "OpsServer",
    "render_prometheus",
    "CompileRecorder",
]
