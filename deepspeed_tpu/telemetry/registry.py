"""Labeled metrics primitives: counters, gauges, histograms, spans.

The registry is the process-local aggregation layer under the telemetry
hub: every emitted trace event also folds its numeric fields into
histograms here, so ``Telemetry.summary()`` can report p50/p95/max without
re-reading the JSONL file. Deliberately dependency-free (no jax import) —
the trace-report CLI and tests use it standalone.
"""

import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence


def percentile(values: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile (q in [0, 100]) of a sequence."""
    if not values:
        return 0.0
    vals = sorted(values)
    if len(vals) == 1:
        return float(vals[0])
    rank = (q / 100.0) * (len(vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(vals) - 1)
    frac = rank - lo
    return float(vals[lo] * (1.0 - frac) + vals[hi] * frac)


def metric_key(name: str, labels: Optional[dict] = None) -> str:
    """Canonical "name{k=v,...}" key; label order never matters."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter."""

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0):
        self.value += n


class Gauge:
    """Last-value-wins scalar."""

    def __init__(self):
        self.value = 0.0

    def set(self, v: float):
        self.value = float(v)


class Histogram:
    """Running count/sum/min/max plus a bounded reservoir of recent
    observations for percentiles (long-running servers must not grow
    unboundedly; the window covers the recent behavior operators ask
    about)."""

    def __init__(self, reservoir: int = 4096):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._values = deque(maxlen=reservoir)

    def observe(self, v: float):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self._values.append(v)

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p95": 0.0}
        vals = list(self._values)
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
            "p50": percentile(vals, 50.0),
            "p95": percentile(vals, 95.0),
        }


class _Span:
    """Context manager timing a block into ``histogram(name, labels)`` in
    milliseconds (and counting entries via the histogram count)."""

    def __init__(self, registry: "MetricsRegistry", name: str, labels: Optional[dict]):
        self._registry = registry
        self._name = name
        self._labels = labels
        self.elapsed_ms = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # host-side span primitive: callers timing device work own the sync
        # (engines block_until_ready under telemetry.sync_timers before the
        # span closes)  # ds-lint: disable=unsynced-timing
        self.elapsed_ms = (time.perf_counter() - self._t0) * 1000.0
        self._registry.histogram(self._name, self._labels).observe(self.elapsed_ms)
        return False


class MetricsRegistry:
    """Process-local labeled metrics store.

    >>> reg = MetricsRegistry()
    >>> reg.counter("requests", {"path": "fused"}).inc()
    >>> reg.gauge("loss_scale").set(65536.0)
    >>> with reg.span("step_ms"):
    ...     pass
    >>> reg.dump()["counters"]["requests{path=fused}"]
    1.0
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str, labels: Optional[dict] = None) -> Counter:
        key = metric_key(name, labels)
        with self._lock:
            return self._counters.setdefault(key, Counter())

    def gauge(self, name: str, labels: Optional[dict] = None) -> Gauge:
        key = metric_key(name, labels)
        with self._lock:
            return self._gauges.setdefault(key, Gauge())

    def histogram(self, name: str, labels: Optional[dict] = None) -> Histogram:
        key = metric_key(name, labels)
        with self._lock:
            return self._histograms.setdefault(key, Histogram())

    def span(self, name: str, labels: Optional[dict] = None) -> _Span:
        return _Span(self, name, labels)

    def dump(self) -> dict:
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {k: h.snapshot() for k, h in self._histograms.items()},
            }
