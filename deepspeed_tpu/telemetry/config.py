"""Telemetry config block, shared verbatim by the training config
(``TpuConfig.telemetry``) and the inference config
(``InferenceConfig.telemetry``). Default off: with ``enabled: false`` the
engines behave bit-identically to a build without the telemetry layer and
no trace file is ever created.

JSON shape (see docs/telemetry.md for the full schema):

    "telemetry": {
        "enabled": true,
        "trace_file": "runs/trace.jsonl",
        "profile_start_step": 10,
        "profile_num_steps": 3
    }
"""

from dataclasses import dataclass


@dataclass
class TelemetryConfig:
    enabled: bool = False
    # JSONL destination, one event per line ("schema": 1). Written by
    # process 0 only. Relative paths resolve against the CWD.
    trace_file: str = "telemetry_trace.jsonl"
    # mirror numeric event fields into MonitorMaster writers
    # (tensorboard/csv/wandb) when any are configured
    emit_to_monitor: bool = True
    # block on device work at micro-step/step boundaries so fwd/step wall
    # times measure compute, not dispatch. Costs the dispatch overlap —
    # that is the price of honest per-phase numbers; turn off to keep the
    # async pipeline and accept dispatch-time phase attribution.
    sync_timers: bool = True
    # per-device peak FLOP/s (in TFLOP/s) for the MFU denominator.
    # 0 = auto-detect from jax device_kind (v4/v5e/v5p/v6e table),
    # falling back to the v5e peak (197) on unknown hardware — override
    # for anything else.
    peak_tflops_per_device: float = 0.0
    # jax.profiler device-trace capture window: start at this global step
    # (0 = never) and run for profile_num_steps steps. On the serving
    # tick loop the window is TICK-indexed (the continuous engine drives
    # maybe_capture once per scheduler tick), so a capture can be pointed
    # at the pooled-tick hot path. The xplane dump lands in profile_dir
    # (default: alongside the trace file).
    profile_start_step: int = 0
    profile_num_steps: int = 1
    profile_dir: str = ""
    # size bound (bytes) on the JSONL trace file: 0 = unbounded (the
    # historical behavior); > 0 rotates the file to <trace_file>.1 once a
    # flushed write reaches the bound (one rotated generation is kept, so
    # disk stays <= ~2x the bound) and counts each rotation in the
    # trace_rotations counter. Soak runs set this; short runs never hit it.
    max_trace_bytes: int = 0
    # per-device HBM capacity override (bytes) for the hbm_headroom_bytes
    # gauge and memory_snapshot events. 0 = use the backend allocator's
    # bytes_limit when it reports one (TPU), else headroom is unknown
    # and the gauge is simply absent (the CPU virtual mesh).
    hbm_limit_bytes: int = 0
