"""Telemetry hub: one object per engine fanning events into every export
path — the JSONL trace file, the in-process :class:`MetricsRegistry`
(for ``summary()`` percentiles), and ``MonitorMaster`` writers
(tensorboard/csv/wandb) — plus the optional ``jax.profiler`` device-trace
capture window.

Disabled (the default) it is inert: ``emit`` returns immediately, no file
is opened, no profiler started. Engines therefore construct one
unconditionally and guard hot-path measurement (timers, host syncs) on
``telemetry.enabled`` only.
"""

import json
import os
import time
from typing import Optional

from deepspeed_tpu.telemetry.config import TelemetryConfig
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.trace import SCHEMA_VERSION, TraceWriter
from deepspeed_tpu.utils.logging import logger

# Per-chip bf16 peaks (TFLOP/s) by jax device_kind substring; the MFU
# denominator. Override via telemetry.peak_tflops_per_device.
_DEVICE_PEAK_TFLOPS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)
_FALLBACK_PEAK_TFLOPS = 197.0  # v5e, the repo's headline bench part


def _numeric_items(payload: dict):
    for k, v in payload.items():
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            yield k, float(v)


class Telemetry:
    def __init__(self, cfg: Optional[TelemetryConfig] = None, monitor=None,
                 role: str = "train"):
        self.cfg = cfg if cfg is not None else TelemetryConfig()
        self.enabled = self.cfg.enabled
        self.role = role
        self.monitor = monitor
        self.registry = MetricsRegistry()
        self._writer = None
        self._write_warned = False
        self._profiling = False
        self._peak_flops_per_device = None
        self._compile_recorder = None
        if self.enabled and self.cfg.trace_file:
            import jax

            if jax.process_index() == 0:
                self._writer = TraceWriter(self.cfg.trace_file,
                                           max_bytes=self.cfg.max_trace_bytes)

    # ------------------------------------------------------------------
    def span(self, name: str, labels: Optional[dict] = None):
        return self.registry.span(name, labels)

    def emit(self, kind: str, payload: dict, monitor_prefix: Optional[str] = None,
             monitor_step: Optional[int] = None):
        """Fan one structured event into every export path. ``payload`` is
        flat-ish JSON (nested dicts allowed; only top-level numerics feed
        the registry/monitor). Returns the full event dict (None when
        disabled)."""
        if not self.enabled:
            return None
        event = {"role": self.role}
        event.update(payload)
        for field, value in _numeric_items(payload):
            self.registry.histogram(f"{kind}.{field}").observe(value)
        if self._writer is not None:
            try:
                rotations_before = self._writer.rotations
                self._writer.write(kind, event)
                if self._writer.rotations != rotations_before:
                    self.registry.counter("trace_rotations").inc(
                        self._writer.rotations - rotations_before)
            except OSError as e:  # telemetry must never kill the step loop
                # a transient disk hiccup must not permanently blind the
                # trace: count it, warn ONCE (not per event), and drop the
                # file handle so the NEXT emit retries through the lazy
                # reopen — while the disk stays broken each emit fails
                # into this branch again (counter grows, no log spam)
                self.registry.counter("trace_write_errors").inc()
                if not self._write_warned:
                    logger.warning(
                        f"telemetry trace write failed (will retry on the "
                        f"next event; trace_write_errors counts drops): {e}")
                    self._write_warned = True
                try:
                    self._writer.close()
                except OSError:
                    self._writer._fh = None  # force the lazy reopen anyway
        if (monitor_prefix and self.cfg.emit_to_monitor
                and self.monitor is not None and self.monitor.enabled):
            step = int(monitor_step if monitor_step is not None
                       else payload.get("step", 0))
            self.monitor.write_events(
                [(f"{monitor_prefix}/{field}", value, step)
                 for field, value in _numeric_items(payload)]
            )
        event.setdefault("schema", SCHEMA_VERSION)
        event.setdefault("kind", kind)
        return event

    # ------------------------------------------------------------------
    def compile_recorder(self):
        """The hub's compile flight recorder (telemetry/compile_log.py),
        created lazily and shared across engine generations — a serving
        rebuild re-injects this hub, so the replacement engine's compiles
        are correctly flagged as recompiles."""
        if self._compile_recorder is None:
            from deepspeed_tpu.telemetry.compile_log import CompileRecorder

            self._compile_recorder = CompileRecorder(self)
        return self._compile_recorder

    # ------------------------------------------------------------------
    def peak_flops_per_device(self) -> float:
        """MFU denominator in FLOP/s per local device."""
        if self._peak_flops_per_device is None:
            tflops = self.cfg.peak_tflops_per_device
            if not tflops:
                kind = ""
                try:
                    import jax

                    kind = jax.local_devices()[0].device_kind.lower()
                except Exception:
                    pass
                tflops = next(
                    (peak for sub, peak in _DEVICE_PEAK_TFLOPS if sub in kind),
                    _FALLBACK_PEAK_TFLOPS,
                )
            self._peak_flops_per_device = tflops * 1e12
        return self._peak_flops_per_device

    # ------------------------------------------------------------------
    def maybe_capture(self, step: int):
        """Drive the configured jax.profiler window: start when ``step``
        reaches ``profile_start_step``, stop ``profile_num_steps`` later.
        Failures never propagate into the training loop."""
        cfg = self.cfg
        if not self.enabled or cfg.profile_start_step <= 0:
            return
        try:
            import jax.profiler
        except Exception:
            return
        try:
            if not self._profiling and step == cfg.profile_start_step:
                logdir = cfg.profile_dir or os.path.join(
                    os.path.dirname(os.path.abspath(cfg.trace_file or ".")),
                    "xla_trace",
                )
                jax.profiler.start_trace(logdir)
                self._profiling = True
            elif self._profiling and step >= cfg.profile_start_step + cfg.profile_num_steps:
                jax.profiler.stop_trace()
                self._profiling = False
        except Exception as e:
            logger.warning(f"telemetry profiler capture failed: {e}")
            self._profiling = False

    # ------------------------------------------------------------------
    def summary(self) -> dict:
        """Aggregated view of everything emitted so far (counters, gauges,
        per-field histogram percentiles)."""
        return {
            "schema": SCHEMA_VERSION,
            "role": self.role,
            "metrics": self.registry.dump(),
        }

    def dump_summary(self, path: str) -> dict:
        s = self.summary()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as fh:
            json.dump(s, fh, indent=2, sort_keys=True)
        return s

    def close(self):
        if self._profiling:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._profiling = False
        if self._writer is not None:
            self._writer.close()
