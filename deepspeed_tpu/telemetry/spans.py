"""Request-scoped span emission over the telemetry hub.

One ``SpanEmitter`` per emitting scope (a serving engine, the fleet
router, a train supervisor) writes closed spans as ``kind: "span"``
trace events through the scope's hub — so a ``ReplicaTelemetry`` facade
stamps its ``replica`` tag on every span exactly like on every other
event, and a disabled hub keeps the whole layer inert (``emit`` is one
attribute check). Span ids are unique per process (a module-level scope
counter feeds each emitter's prefix), which is what the fleet needs:
N replicas share ONE trace file, and a migrated request's survivor-side
spans must never collide with the dead replica's.

Timestamps are monotonic-clock seconds (``time.monotonic`` by default;
emitters owning a different monotonic clock — the serving engine's
injected ``clock``, the train supervisor's ``perf_counter`` — pass it
in, and every span in one trace file must share one clock domain for
the read side's interval math to mean anything). The read side is
``telemetry/timeline.py``, which also owns the span-kind tables this
module validates against — that module stays loadable by file path, so
imports only ever point from here to there.
"""

import itertools
import time
from typing import Callable, Optional

from deepspeed_tpu.telemetry.timeline import SPAN_KINDS

_SCOPES = itertools.count()


class SpanEmitter:
    """Emit closed spans for one scope through a telemetry hub.

    ``telemetry`` is a hub-shaped object (``.enabled`` + ``.emit``) or
    None; disabled/None hubs make every call a no-op returning None.
    ``new_span_id()`` mints ids without emitting — the migration stitch
    allocates the bridge span's id first, hands it to the survivor as a
    parent, and emits the bridge only once placement succeeded."""

    def __init__(self, telemetry=None, clock: Callable[[], float] = time.monotonic):
        self._tele = telemetry
        self.clock = clock
        self._scope = next(_SCOPES)
        self._seq = 0

    @property
    def enabled(self) -> bool:
        tele = self._tele
        return tele is not None and bool(getattr(tele, "enabled", False))

    def rebind(self, telemetry):
        """Point at another hub (a rebuilt engine adopting the survivor
        hub); span ids keep their scope — causality survives the swap."""
        self._tele = telemetry

    def new_span_id(self) -> str:
        self._seq += 1
        return f"s{self._scope}-{self._seq}"

    def emit(self, span: str, trace_id, t0: float, t1: float, *,
             span_id: Optional[str] = None, parent_id: Optional[str] = None,
             attrs: Optional[dict] = None) -> Optional[str]:
        """Write one closed span; returns its span_id (None when the hub
        is disabled or the request is sampled out — ``trace_id`` None).
        ``t1 < t0`` clamps to a zero-length span rather than lying."""
        if trace_id is None or not self.enabled:
            return None
        if span not in SPAN_KINDS:
            raise ValueError(f"unknown span kind {span!r} "
                             f"(register it in telemetry/timeline.py)")
        sid = span_id if span_id is not None else self.new_span_id()
        t0 = float(t0)
        t1 = max(float(t1), t0)
        payload = {
            "span": span,
            "trace_id": str(trace_id),
            "span_id": sid,
            "t0": t0,
            "t1": t1,
            "dur_ms": (t1 - t0) * 1000.0,
        }
        if parent_id is not None:
            payload["parent_id"] = str(parent_id)
        if attrs:
            payload["attrs"] = dict(attrs)
        self._tele.emit("span", payload)
        return sid


def make_trace_sampler(rate: float, seed: int = 0):
    """Deterministic per-request sampling decision for span emission
    (``ds_loadgen --trace-sample P``): a pure hash of (seed, rid) —
    stable across replicas, re-admissions, and runs with the same seed,
    with no RNG state to share or lock. Returns ``sampler(rid) -> bool``;
    rate >= 1 traces everything, rate <= 0 nothing."""
    if rate >= 1.0:
        return lambda rid: True
    if rate <= 0.0:
        return lambda rid: False
    threshold = int(rate * (1 << 32))

    def sampler(rid: int) -> bool:
        # splitmix64-style integer hash: uniform over the rid space and
        # identical on every host that shares the seed
        x = (int(rid) + 0x9E3779B97F4A7C15 * (seed + 1)) & 0xFFFFFFFFFFFFFFFF
        x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        return ((x ^ (x >> 31)) & 0xFFFFFFFF) < threshold

    return sampler
