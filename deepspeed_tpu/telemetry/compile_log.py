"""Compile flight recorder: every compiled-program build in the decode /
serving / training stacks emits a ``compile_event`` trace record plus a
``compile_ms{family=...}`` histogram, so runtime recompile storms — the
thing ds-lint's static recompile-hazard rule can only guess at — become
a visible counter on ``/metrics``.

Mechanism: ``jax.jit`` compiles lazily at the first dispatch, so the
recorder wraps a freshly built jitted callable and times that FIRST call
(dispatch blocks through tracing + XLA compile, then returns futures —
the measured span is compile cost, not execution). Every later call goes
straight through with one flag check of overhead. The ``recompile`` flag
is keyed on ``(family, shapes key)`` per telemetry hub: the hub survives
serving-engine rebuilds (PR 7 re-injects it into replacement engines),
so an LRU-evicted-and-rebuilt program family or a rebuilt engine's
re-compiles are flagged ``recompile: true`` while genuinely new shapes
are first compiles.
"""

import time
from typing import Optional


class _FirstCallTimer:
    """Callable wrapper timing only the first invocation (the one that
    pays tracing + XLA compile). Forwards attribute access to the wrapped
    function so AOT surfaces (``.lower``) keep working."""

    __slots__ = ("_fn", "_recorder", "_family", "_key", "_fields", "_done")

    def __init__(self, fn, recorder, family, key, fields):
        self._fn = fn
        self._recorder = recorder
        self._family = family
        self._key = key
        self._fields = fields
        self._done = False

    def __call__(self, *args, **kwargs):
        if self._done:
            return self._fn(*args, **kwargs)
        self._done = True
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        # the first dispatch of a jitted fn blocks through trace + XLA
        # compile and returns execution FUTURES — the unsynced span IS the
        # compile cost, by design
        self._recorder.record(self._family, self._key,
                              # ds-lint: disable=unsynced-timing
                              (time.perf_counter() - t0) * 1000.0,
                              **self._fields)
        return out

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)


class _DeferredFirstCallTimer:
    """Like :class:`_FirstCallTimer`, but resolves the telemetry hub at
    FIRST CALL instead of wrap time — for programs built before a shared
    hub is injected. Serving recovery builds replacement engines with the
    factory's telemetry off and re-injects the serving hub afterwards;
    ``jax.jit`` compiles lazily, so the first dispatch (the compile this
    recorder exists to journal) lands after injection. A hub still
    disabled at first call records nothing and the wrapper degrades to a
    plain passthrough."""

    __slots__ = ("_fn", "_get_tele", "_family", "_key", "_done")

    def __init__(self, fn, get_tele, family, key):
        self._fn = fn
        self._get_tele = get_tele
        self._family = family
        self._key = key
        self._done = False

    def __call__(self, *args, **kwargs):
        if self._done:
            return self._fn(*args, **kwargs)
        self._done = True
        tele = self._get_tele()
        if tele is None or not tele.enabled:
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        tele.compile_recorder().record(
            self._family, self._key,
            # first dispatch blocks through trace + XLA compile, returns
            # futures — the unsynced span IS the compile cost, by design
            # ds-lint: disable=unsynced-timing
            (time.perf_counter() - t0) * 1000.0)
        return out

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)


def wrap_deferred(get_telemetry, fn, family: str, key):
    """Arm ``fn`` to journal its first dispatch against whatever hub
    ``get_telemetry()`` resolves to AT THAT MOMENT (see
    :class:`_DeferredFirstCallTimer`)."""
    return _DeferredFirstCallTimer(fn, get_telemetry, family, key)


class CompileRecorder:
    """Per-telemetry-hub compile journal. ``record`` emits one
    ``compile_event`` (family, shapes key, compile_ms, first-vs-recompile)
    and folds the duration into ``compile_ms{family=...}``; ``wrap`` arms
    a freshly built jitted callable so its first dispatch records
    itself."""

    def __init__(self, telemetry):
        self._tele = telemetry
        self._seen = set()

    def record(self, family: str, key, compile_ms: float, **fields) -> bool:
        """Journal one compile. Returns the recompile flag (True when
        this (family, key) compiled before under this hub)."""
        ident = (family, str(key))
        recompile = ident in self._seen
        self._seen.add(ident)
        tele = self._tele
        if tele.enabled:
            reg = tele.registry
            reg.histogram("compile_ms", {"family": family}).observe(compile_ms)
            reg.counter("compile_event_total", {"family": family}).inc()
            if recompile:
                reg.counter("recompile_total", {"family": family}).inc()
            event = {"family": family, "key": str(key),
                     "compile_ms": round(compile_ms, 3),
                     "recompile": recompile}
            event.update(fields)
            tele.emit("compile_event", event)
        return recompile

    def wrap(self, fn, family: str, key, **fields):
        """Arm ``fn`` (a freshly built jitted callable) to record its
        first dispatch as a compile. With telemetry disabled the function
        is returned untouched — zero hot-path cost."""
        if not self._tele.enabled:
            return fn
        return _FirstCallTimer(fn, self, family, key, fields)


def wrap_compiled(telemetry, family: str, key, value):
    """Arm the recorder on a compiled-fn cache entry as ``cached_fn``
    builds it: a bare callable wraps directly; a tuple entry wraps its
    leading callable (the convention every cached_fn builder follows —
    ``(fn, cache_sharding, ...)``). Anything else passes through."""
    if telemetry is None or not telemetry.enabled:
        return value
    rec = telemetry.compile_recorder()
    if isinstance(value, tuple):
        if value and callable(value[0]):
            return (rec.wrap(value[0], family, key),) + value[1:]
        return value
    if callable(value):
        return rec.wrap(value, family, key)
    return value
