"""Structured JSONL trace writer.

One event per line; every event carries ``"schema": 1`` (bump on any
incompatible field change), a ``"kind"`` discriminator ("train_step",
"inference_request", "comm_summary", ...) and a wall-clock ``"ts"``.
``tools/ds_trace_report.py`` renders per-kind p50/p95/max tables from
these files; docs/telemetry.md documents the per-kind fields.
"""

import json
import os
import time

SCHEMA_VERSION = 1


def _json_default(obj):
    """Coerce numpy/jax scalars (and anything with .item()) to JSON."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class TraceWriter:
    """Append-only JSONL writer; the file opens lazily on the first event
    (so a constructed-but-never-used writer creates nothing) and each line
    is flushed — a crashed run keeps every completed event.

    ``max_bytes`` > 0 size-bounds the file: once a completed write
    reaches the limit the file rotates to ``<path>.1`` (one generation —
    the previous ``.1`` is replaced, so disk use stays <= ~2x the bound)
    and the next event lazily reopens a fresh file. ``rotations`` counts
    rotations for the hub's ``trace_rotations`` counter. Rotation happens
    AFTER the triggering line is flushed, so no event is ever torn across
    files."""

    def __init__(self, path: str, max_bytes: int = 0):
        self.path = path
        self.max_bytes = int(max_bytes or 0)
        self.rotations = 0
        self._fh = None

    def write(self, kind: str, payload: dict):
        event = {"schema": SCHEMA_VERSION, "kind": kind, "ts": time.time()}
        event.update(payload)
        if self._fh is None:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            self._fh = open(self.path, "a")
        self._fh.write(json.dumps(event, default=_json_default) + "\n")
        self._fh.flush()
        if self.max_bytes > 0 and self._fh.tell() >= self.max_bytes:
            self._rotate()
        return event

    def _rotate(self):
        self._fh.close()
        self._fh = None
        os.replace(self.path, self.path + ".1")
        self.rotations += 1

    def flush(self):
        if self._fh is not None:
            self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_trace(path: str):
    """Yield parsed events from a JSONL trace, skipping malformed lines
    (a crashed writer may leave a torn final line)."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                yield ev
