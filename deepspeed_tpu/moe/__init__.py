from deepspeed_tpu.moe.layer import MLPExpert, MoE
from deepspeed_tpu.moe.sharded_moe import (
    GateOutput,
    compute_capacity,
    moe_forward,
    top1_gating,
    top2_gating,
    topk_gating,
)
