"""User-facing MoE layer.

Reference: ``deepspeed/moe/layer.py`` (MoE :16 — wraps an expert module with
a TopKGate + MOELayer and exposes ``forward -> (output, l_aux, exp_counts)``)
and ``moe/experts.py`` (Experts — per-expert replicas). Functional TPU form:
``MoE.init(rng) -> params`` / ``MoE.apply(params, x, rng) -> (out, l_aux,
exp_counts)`` with expert params stacked on a leading E dim carrying the
``expert`` logical axis, so the ShardingPolicy places them on the ``expert``
mesh axis (the reference's expert-parallel process groups,
utils/groups.py:108, are that axis)."""

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import moe_forward


class MLPExpert:
    """Default expert: 2-layer MLP (reference experts are arbitrary modules;
    this mirrors the common FFN expert)."""

    def __init__(self, hidden_size: int, ffn_size: int, activation=jax.nn.gelu):
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size
        self.activation = activation

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        D, F = self.hidden_size, self.ffn_size
        return {
            "wi": jax.random.normal(k1, (D, F), jnp.float32) / math.sqrt(D),
            "wo": jax.random.normal(k2, (F, D), jnp.float32) / math.sqrt(F),
        }

    def apply(self, params, x):
        return self.activation(x @ params["wi"]) @ params["wo"]

    def logical_specs(self):
        return {"wi": ("expert", "embed", "mlp"), "wo": ("expert", "mlp", "embed")}


class MoE:
    """Mixture of experts over a stacked expert module.

    Args mirror the reference MoE (moe/layer.py:16): num_experts, k (top-k),
    capacity_factor, eval_capacity_factor, min_capacity, drop_tokens, use_rts,
    noisy_gate_policy, use_residual. ``ep_size`` is implicit: the ``expert``
    mesh axis.

    ``use_residual=True`` is PR-MoE (reference moe/layer.py:28,45): a dense
    MLP (same shape as one expert) runs every token, and a learned per-token
    2-way softmax coefficient mixes it with the MoE output:
    ``out = moe * coef[..., :1] + dense * coef[..., 1:2]`` (reference
    moe/layer.py:123 channel order).
    """

    def __init__(
        self,
        hidden_size: int,
        expert=None,
        num_experts: int = 1,
        k: int = 1,
        capacity_factor: float = 1.0,
        eval_capacity_factor: float = 1.0,
        min_capacity: int = 4,
        drop_tokens: bool = True,
        use_rts: bool = True,
        noisy_gate_policy: Optional[str] = None,
        ffn_size: Optional[int] = None,
        use_residual: bool = False,
    ):
        assert k in (1, 2), "only top-1 / top-2 gating supported (reference TopKGate :358)"
        self.hidden_size = hidden_size
        self.expert = expert if expert is not None else MLPExpert(hidden_size, ffn_size or 4 * hidden_size)
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts
        self.noisy_gate_policy = noisy_gate_policy
        self.use_residual = use_residual

    def init(self, rng):
        gate_rng, exp_rng, res_rng, coef_rng = jax.random.split(rng, 4)
        expert_params = jax.vmap(self.expert.init)(jax.random.split(exp_rng, self.num_experts))
        gate_w = jax.random.normal(gate_rng, (self.hidden_size, self.num_experts), jnp.float32) * 0.02
        params = {"gate": {"w": gate_w}, "experts": expert_params}
        if self.use_residual:
            params["residual_mlp"] = self.expert.init(res_rng)
            params["coefficient"] = {
                "w": jax.random.normal(coef_rng, (self.hidden_size, 2), jnp.float32) * 0.02,
                "b": jnp.zeros((2,), jnp.float32),
            }
        return params

    def logical_specs(self):
        specs = {"gate": {"w": ("embed", None)}}
        if hasattr(self.expert, "logical_specs"):
            specs["experts"] = self.expert.logical_specs()
        else:
            specs["experts"] = None
        if self.use_residual:
            # dense residual expert: expert specs minus the leading E axis
            if specs["experts"] is not None:
                specs["residual_mlp"] = {
                    k: tuple(a for a in v if a != "expert")
                    for k, v in specs["experts"].items()
                }
            else:
                specs["residual_mlp"] = None
            specs["coefficient"] = {"w": ("embed", None), "b": (None,)}
        return specs

    def apply(self, params, x, rng=None, training: bool = True):
        cf = self.capacity_factor if training else self.eval_capacity_factor
        moe_out, l_aux, exp_counts = moe_forward(
            x,
            params["gate"]["w"],
            self.expert.apply,
            params["experts"],
            k=self.k,
            capacity_factor=cf,
            min_capacity=self.min_capacity,
            rng=rng,
            use_rts=self.use_rts and rng is not None,
            drop_tokens=self.drop_tokens,
            noisy_gate_policy=self.noisy_gate_policy,
        )
        if self.use_residual:
            dense_out = self.expert.apply(params["residual_mlp"], x)
            coef_p = params["coefficient"]
            coef = jax.nn.softmax(x @ coef_p["w"] + coef_p["b"], axis=-1)
            # channel order matches reference moe/layer.py:123:
            # channel 0 scales the expert branch, channel 1 the dense MLP
            moe_out = moe_out * coef[..., 0:1] + dense_out * coef[..., 1:2]
        return moe_out, l_aux, exp_counts

    __call__ = apply
