"""Sharded Mixture-of-Experts: gating + dispatch/combine.

Reference: ``deepspeed/moe/sharded_moe.py`` (TopKGate :343, MOELayer :420,
top1gating :179, top2gating :277, ``_AllToAll`` autograd op :90). TPU
redesign: the dispatch is the GShard einsum formulation —

    dispatch   (N,E,C) x (N,D)   -> (E,C,D)     "tokens to experts"
    expert     (E,C,D) x (E,D,F) -> (E,C,F)     batched per-expert GEMM (MXU)
    combine    (E,C,D) x (N,E,C) -> (N,D)       "experts back to tokens"

with the (E,...) dims sharded over the ``expert`` mesh axis: GSPMD lowers the
token-layout change into exactly the all-to-alls the reference issues by hand,
and everything stays static-shape (capacity-dropped) for XLA.

Capacity semantics follow the reference: ``capacity = max(min_capacity,
ceil(tokens/E * capacity_factor * k))``; tokens over capacity are dropped
(their combine weight is zero, so the residual path carries them).
Random-token-selection (use_rts, reference :152) adds uniform noise to the
drop priority so dropped tokens aren't always the sequence tail.
"""

import math
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GateOutput(NamedTuple):
    combine_weights: jnp.ndarray  # (N, E, C) float
    dispatch_mask: jnp.ndarray  # (N, E, C) bool
    aux_loss: jnp.ndarray  # scalar load-balancing loss
    expert_counts: jnp.ndarray  # (E,) tokens routed per expert (pre-drop)


def compute_capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int, k: int = 1) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor * k))
    return max(cap, min_capacity)


def _assign_positions(mask: jnp.ndarray, priority: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Position of each selected token within its expert's capacity buffer.

    mask: (N, E) 0/1 selection. priority: optional (N,) — lower goes first
    (reference: exclusive cumsum in token order; RTS shuffles this order).
    Returns (N, E) int positions (valid where mask==1).
    """
    if priority is None:
        # exclusive cumsum over token dim
        return jnp.cumsum(mask, axis=0) - mask
    order = jnp.argsort(priority)  # token indices, best first
    inv = jnp.argsort(order)
    mask_sorted = jnp.take(mask, order, axis=0)
    pos_sorted = jnp.cumsum(mask_sorted, axis=0) - mask_sorted
    return jnp.take(pos_sorted, inv, axis=0)


def _load_balance_loss(gates: jnp.ndarray, mask1: jnp.ndarray) -> jnp.ndarray:
    """Switch/GShard aux loss: E * sum_e mean(gates_e) * mean(mask_e)
    (reference top1gating :222)."""
    E = gates.shape[1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    return jnp.sum(me * ce) * E


def topk_gating(
    logits: jnp.ndarray,
    k: int,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng: Optional[jax.Array] = None,
    use_rts: bool = True,
    drop_tokens: bool = True,
    noisy_gate_policy: Optional[str] = None,
) -> GateOutput:
    """Top-k gating with static capacity (k=1 -> Switch, k=2 -> GShard).

    logits: (N, E) router outputs. Returns dense (N, E, C) dispatch/combine.
    """
    N, E = logits.shape
    C = compute_capacity(N, E, capacity_factor, min_capacity, k)
    if not drop_tokens:
        C = N  # full capacity: nothing dropped (reference drop_tokens=False)

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        logits_for_select = logits + jax.random.normal(sub, logits.shape) / E
    else:
        logits_for_select = logits
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (N, E)

    combine = jnp.zeros((N, E, C), jnp.float32)
    dispatch = jnp.zeros((N, E, C), jnp.bool_)
    aux_loss = jnp.float32(0.0)
    expert_counts = jnp.zeros((E,), jnp.int32)

    masked_logits = logits_for_select.astype(jnp.float32)
    selected_gates = []
    selected_masks = []
    for i in range(k):
        idx = jnp.argmax(masked_logits, axis=-1)  # (N,)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        if i == 0:
            aux_loss = _load_balance_loss(gates, mask)
        priority = None
        if use_rts and rng is not None:
            rng, sub = jax.random.split(rng)
            priority = jax.random.uniform(sub, (N,))
        pos = _assign_positions(mask, priority)  # (N, E)
        if selected_masks:
            # later choices start after slots taken by earlier choices in the
            # same expert buffer (reference top2gating :307 locations2 offset)
            offset = sum(jnp.sum(m, axis=0) for m in selected_masks)  # (E,)
            pos = pos + offset[None, :]
        keep = (pos < C) & (mask > 0)
        expert_counts = expert_counts + jnp.sum(mask, axis=0).astype(jnp.int32)
        gate_i = jnp.sum(gates * mask, axis=-1)  # (N,)
        oh_pos = jax.nn.one_hot(jnp.clip(pos, 0, C - 1).astype(jnp.int32), C, dtype=jnp.float32)  # (N, E, C)
        sel = (mask[..., None] * oh_pos) * keep[..., None].astype(jnp.float32)
        selected_gates.append(gate_i)
        selected_masks.append(mask * keep.astype(jnp.float32))
        combine = combine + sel * gate_i[:, None, None]
        dispatch = dispatch | (sel > 0)
        # mask out the chosen expert for the next iteration
        masked_logits = jnp.where(mask > 0, -jnp.inf, masked_logits)

    if k > 1:
        # renormalize combine weights over the selected experts (reference
        # top2gating :320: denom = gates1_s + gates2_s)
        denom = sum(g * jnp.sum(m, axis=-1) for g, m in zip(selected_gates, selected_masks))
        denom = jnp.maximum(denom, jnp.finfo(jnp.float32).eps)
        combine = combine / denom[:, None, None]

    return GateOutput(combine, dispatch, aux_loss, expert_counts)


def top1_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=1, **kw)


def top2_gating(logits, **kw) -> GateOutput:
    return topk_gating(logits, k=2, **kw)


def _expert_sharding_constraint(x):
    """Pin (E, ...) tensors to the expert mesh axis so GSPMD materializes the
    all-to-all at this boundary (the compiled _AllToAll, reference :90)."""
    try:
        from deepspeed_tpu import comm

        mesh = comm.get_mesh()
        spec = ["expert"] + [None] * (x.ndim - 1)
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, PartitionSpec(*spec)))
    except Exception:
        return x


def moe_forward(
    x: jnp.ndarray,
    gate_w: jnp.ndarray,
    expert_fn: Callable,
    expert_params,
    k: int = 1,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng: Optional[jax.Array] = None,
    use_rts: bool = True,
    drop_tokens: bool = True,
    noisy_gate_policy: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full MoE layer: route, dispatch, expert compute, combine.

    x: (..., D) tokens; gate_w: (D, E); expert_params: pytree with leading E
    dim on every leaf; expert_fn(params_slice, tokens (C', D)) -> (C', F').
    Returns (out (..., F'), aux_loss, expert_counts).
    """
    orig_shape = x.shape
    D = x.shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]

    logits = jnp.einsum("nd,de->ne", xf.astype(jnp.float32), gate_w.astype(jnp.float32))
    gate = topk_gating(
        logits, k, capacity_factor=capacity_factor, min_capacity=min_capacity,
        rng=rng, use_rts=use_rts, drop_tokens=drop_tokens, noisy_gate_policy=noisy_gate_policy,
    )

    dispatch = gate.dispatch_mask.astype(x.dtype)  # (N, E, C)
    expert_inputs = jnp.einsum("nec,nd->ecd", dispatch, xf)  # (E, C, D)
    expert_inputs = _expert_sharding_constraint(expert_inputs)
    expert_outputs = jax.vmap(expert_fn)(expert_params, expert_inputs)  # (E, C, F')
    expert_outputs = _expert_sharding_constraint(expert_outputs)
    out = jnp.einsum("ecf,nec->nf", expert_outputs, gate.combine_weights.astype(x.dtype))
    return out.reshape(orig_shape[:-1] + (out.shape[-1],)), gate.aux_loss, gate.expert_counts
