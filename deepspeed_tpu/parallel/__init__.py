from deepspeed_tpu.parallel.partition import (  # noqa: F401
    DEFAULT_RULES,
    kv_shard_width,
    match_partition_rules,
    mesh_tensor_width,
    parse_mesh_arg,
    partition_params,
    serving_mesh,
    tree_path_names,
)
