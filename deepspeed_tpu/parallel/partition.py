"""Regex-driven partition rules for inference tensor parallelism.

TPU-native counterpart of the reference's ``module_inject`` layer: where
the reference rewrites ``nn.Linear`` modules into column/row-parallel
shards (replace_module.py + auto_tp.py), on TPU the same split is pure
*placement* — a table of ``(regex, PartitionSpec)`` rules matched against
each parameter's ``/``-joined tree path assigns every weight a
``NamedSharding`` over the mesh, and GSPMD inserts the per-layer
collectives the reference codes by hand (the EasyLM/fmengine
``match_partition_rules`` recipe).

Two rule sources compose, in order:

1. ``InferenceConfig.mesh.rules`` — user overrides, matched first;
2. the model-family default table (``DEFAULT_RULES`` covers the builtin
   transformer naming every ``module_inject`` policy converts into):
   attention heads, MLP hidden, and vocab/embed shard on ``tensor``;
   biases/norms/scales replicate.

The engine prefers the model's own ``logical_specs`` annotations when it
has them (they carry per-dim intent the regex cannot see, e.g. MoE expert
dims); regex rules serve models WITHOUT annotations — custom ``cfg/init/
apply`` model objects and checkpoint trees loaded outside the builtin
family — and user overrides win over both.
"""

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Default regex rule table over the builtin transformer param naming
# (models/transformer.py init(); every module_inject HF policy — gpt2,
# llama, neox, opt, bloom, auto-TP — converts into this naming, so one
# table serves them all). First match wins; the trailing catch-all
# replicates anything unmatched (scalars, buffers). Mirrors
# runtime/zero/sharding.DEFAULT_LOGICAL_AXIS_RULES: qkv/heads/mlp/vocab
# on "tensor", kv heads replicated-by-default is NOT wanted here — the
# KV cache shards on heads, so wk/wv shard their head-output dim too
# (falling back to replicated at apply time when kv_heads don't divide).
DEFAULT_RULES: Tuple[Tuple[str, PartitionSpec], ...] = (
    # attention: column-split q/k/v (output dim = heads*head_dim),
    # row-split output projection (input dim = heads*head_dim) — the
    # reference AutoTP column/row pattern, allreduce after wo
    (r"attn/w[qkv]$", PartitionSpec(None, "tensor")),
    (r"attn/wo$", PartitionSpec("tensor", None)),
    (r"attn/b[qkv]$", PartitionSpec("tensor")),
    (r"attn/bo$", PartitionSpec()),
    # MLP: column-split in/gate, row-split out, allreduce after wo
    (r"mlp/(wi|wg|res_wi|res_wg)$", PartitionSpec(None, "tensor")),
    (r"mlp/(wo|res_wo)$", PartitionSpec("tensor", None)),
    (r"mlp/(bi|res_bi)$", PartitionSpec("tensor")),
    (r"mlp/(bo|res_bo|gate|coef_w|coef_b)", PartitionSpec()),
    # embeddings / lm head: vocab-split (no collective on the logits
    # matmul — the contraction dim stays replicated)
    (r"embed/tok$", PartitionSpec("tensor", None)),
    (r"lm_head/w$", PartitionSpec(None, "tensor")),
    (r"lm_head/b$", PartitionSpec("tensor")),
    # norms, positional tables, heads' scalar leaves: replicate
    (r".*", PartitionSpec()),
)

# Rules describe a weight's TRAILING dims — the matmul dims every rule
# cares about sit last, while leading dims (the stacked "layers" scan
# dim, an MoE expert dim) are stack dims these rules never shard. A
# matched spec shorter than the leaf's rank is therefore LEFT-padded
# with None (see _align_spec): P(None, "tensor") on a stacked MoE wi
# (layers, expert, embed, mlp) lands "tensor" on mlp hidden, not on the
# expert dim a trailing pad would hit.


def tree_path_names(params, sep: str = "/"):
    """Flatten a param pytree to ``[(path_name, leaf), ...]`` with
    ``sep``-joined string paths (dict keys / sequence indices / attr
    names), the name format the regex rules match against."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            elif hasattr(p, "name"):
                parts.append(str(p.name))
            else:  # pragma: no cover - future path types
                parts.append(str(p))
        out.append((sep.join(parts), leaf))
    return out


def normalize_rules(rules) -> List[Tuple[str, PartitionSpec]]:
    """Canonicalize a rule table: entries may be ``(regex,
    PartitionSpec)`` or the JSON-friendly config form ``[regex, [axis,
    ...]]`` where each axis is a mesh-axis name, a list of names, or
    None. Returns ``[(regex, PartitionSpec)]``."""
    out = []
    for entry in rules:
        pattern, spec = entry[0], entry[1]
        if not isinstance(spec, PartitionSpec):
            axes = []
            for ax in (spec if isinstance(spec, (list, tuple)) else [spec]):
                if isinstance(ax, list):
                    ax = tuple(ax)
                axes.append(ax)
            spec = PartitionSpec(*axes)
        out.append((str(pattern), spec))
    return out


def _align_spec(spec: PartitionSpec, shape) -> PartitionSpec:
    """Align a matched rule spec to a leaf's rank: rules describe the
    TRAILING dims, so a shorter spec is left-padded with None — the
    stacked layers scan dim and any MoE expert dim stay unsharded while
    the matmul dims the rule names keep their placement. Empty specs
    (replicate) and exact-rank specs pass through."""
    if len(spec) == 0 or len(spec) >= len(shape):
        return spec
    return PartitionSpec(*([None] * (len(shape) - len(spec)) + list(spec)))


def _spec_for(name: str, shape, compiled):
    """First-match-wins rule lookup for ONE leaf (shared by the
    whole-tree and per-leaf-override paths so their matching semantics
    can never diverge): the rank-aligned spec of the first regex that
    ``search``-matches the ``/``-joined path, ``PartitionSpec()`` for
    scalars/1-element leaves, or None when nothing matches."""
    if len(shape) == 0 or int(np.prod(shape)) == 1:
        return PartitionSpec()
    for pat, spec in compiled:
        if pat.search(name) is not None:
            return _align_spec(spec, shape)
    return None


def match_partition_rules(rules, params, on_miss: str = "error"):
    """PartitionSpec pytree for ``params``: each leaf takes the spec of
    the FIRST rule whose regex ``search``-matches its ``/``-joined path
    (rank-aligned per _align_spec). Scalars (and 1-element leaves) never
    partition. ``on_miss``: ``"error"`` raises naming the unmatched
    param (the EasyLM contract — a silent replicate hides a sharding
    bug); ``"replicate"`` maps misses to ``PartitionSpec()`` (the
    catch-all ``(".*", P())`` tail in DEFAULT_RULES has the same effect
    explicitly)."""
    compiled = [(re.compile(pat), spec) for pat, spec in normalize_rules(rules)]

    def get_spec(name, leaf):
        spec = _spec_for(name, getattr(leaf, "shape", ()), compiled)
        if spec is not None:
            return spec
        if on_miss == "replicate":
            return PartitionSpec()
        raise ValueError(f"no partition rule matches param {name!r}")

    flat = tree_path_names(params)
    specs = [get_spec(name, leaf) for name, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def _clip_spec_to_mesh(spec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    """Drop spec axes a dim cannot honour on ``mesh`` (dim size not
    divisible by the axis product, or axis missing): jax would raise at
    placement, but a rule table is written once per model family and must
    degrade per-weight — e.g. 3 kv_heads on tensor=2 replicates wk/wv
    while wq/wo stay sharded, exactly like _decode_shardings' kv_tensor
    fallback for the cache."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, entry in zip(shape, entries[: len(shape)]):
        if entry is None:
            out.append(None)
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        keep = []
        factor = 1
        for ax in axes:
            size = mesh.shape.get(ax, 1)
            if size > 1 and dim % (factor * size) == 0:
                keep.append(ax)
                factor *= size
        out.append(keep[0] if len(keep) == 1 else (tuple(keep) or None))
    return PartitionSpec(*out)


def partition_params(mesh: Mesh, abstract_params, rules=None,
                     on_miss: str = "replicate"):
    """NamedSharding pytree for ``abstract_params`` from a regex rule
    table (``rules`` tried first when given, then DEFAULT_RULES), each
    spec clipped to what the mesh and the weight's actual dims support.
    This is the whole module_inject flow for a mesh backend: returns the
    ``param_shardings`` every compiled serving program takes."""
    table = normalize_rules(rules or ()) + normalize_rules(DEFAULT_RULES)
    pspecs = match_partition_rules(table, abstract_params, on_miss=on_miss)
    return jax.tree.map(
        lambda leaf, spec: NamedSharding(
            mesh, _clip_spec_to_mesh(spec, getattr(leaf, "shape", ()), mesh)),
        abstract_params, pspecs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def apply_rule_overrides(mesh: Mesh, abstract_params, base_shardings, rules):
    """Overlay USER regex rules onto an existing sharding pytree: leaves
    whose path matches a rule take that rule's (mesh-clipped) spec;
    everything else KEEPS its base placement. This is how config
    ``mesh.rules`` composes with a model's own ``logical_specs``
    annotations — the override is per-leaf, so one attention rule cannot
    silently strip the expert/vocab intent the annotations carry for the
    rest of the tree (``use_rules`` is the whole-tree regex switch)."""
    compiled = [(re.compile(p), s) for p, s in normalize_rules(rules)]
    flat = tree_path_names(abstract_params)
    base_leaves = jax.tree_util.tree_leaves(base_shardings)
    assert len(flat) == len(base_leaves), (len(flat), len(base_leaves))
    out = []
    for (name, leaf), base in zip(flat, base_leaves):
        shape = getattr(leaf, "shape", ())
        # scalars keep their base placement (a replicated scalar stays
        # replicated either way; never let a rule "match" one)
        spec = None if len(shape) == 0 or int(np.prod(shape)) == 1 \
            else _spec_for(name, shape, compiled)
        if spec is None:
            out.append(base)
        else:
            out.append(NamedSharding(mesh, _clip_spec_to_mesh(spec, shape, mesh)))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(abstract_params), out)


def serving_mesh(data: int = 1, tensor: int = 1, devices=None) -> Mesh:
    """A ``("data", "tensor")``-shaped serving mesh over the FIRST
    ``data*tensor`` devices — unlike ``comm.init_distributed`` it builds
    subset meshes (an 8-device host can carry a 1x2 serving mesh for a
    virtual-mesh A/B) and never touches the global comm state, so two
    engines with different widths coexist in one process (the
    sharded-vs-replicated loadgen A/B). Axis order follows comm.MESH_AXES
    (tensor innermost: contiguous devices, fastest ICI)."""
    from deepspeed_tpu import comm

    devices = list(devices if devices is not None else jax.devices())
    need = int(data) * int(tensor)
    if need < 1:
        raise ValueError(f"mesh needs >= 1 device, got {data}x{tensor}")
    if need > len(devices):
        raise ValueError(
            f"mesh {data}x{tensor} needs {need} devices, "
            f"only {len(devices)} available")
    return comm.build_mesh({"data": data, "tensor": tensor},
                           devices=devices[:need])


def parse_mesh_arg(spec: str) -> Dict[str, int]:
    """``"DATA:TENSOR"`` (the ds_loadgen/prewarm ``--mesh`` syntax, e.g.
    ``1:2``) or ``"axis=N,axis=M"`` → a mesh-shape dict."""
    spec = spec.strip()
    if "=" in spec:
        out = {}
        for part in spec.split(","):
            ax, _, n = part.partition("=")
            out[ax.strip()] = int(n)
        return out
    lo, sep, hi = spec.partition(":")
    if not sep:
        raise ValueError(f"--mesh wants DATA:TENSOR, got {spec!r}")
    return {"data": int(lo), "tensor": int(hi)}


def mesh_tensor_width(mesh: Optional[Mesh]) -> int:
    """Size of the ``tensor`` axis (1 when the mesh has none)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("tensor", 1))


def kv_shard_width(mesh: Optional[Mesh], cfg) -> int:
    """How many ways the KV cache's heads axis is ACTUALLY split on this
    mesh — the ONE divisor behind per-chip ``kv_bytes_read`` accounting,
    mirroring _decode_shardings' kv_tensor choice exactly: the heads dim
    shards over ``tensor`` only when kv_heads divide evenly; otherwise
    the cache replicates and every chip reads full rows."""
    t = mesh_tensor_width(mesh)
    if t <= 1 or cfg.kv_heads % t != 0:
        return 1
    return t
