"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

The reference (v0.9.1) has NO sequence-parallel axis (SURVEY.md §2.2: its
long-sequence story is Triton block-sparse attention + curriculum seqlen +
random-LTD). This module provides the modern first-class equivalent the
capability list requires, shaped for TPU ICI:

  - **Ring attention** (`ring_attention`): activations stay sharded over the
    ``sequence`` mesh axis; KV blocks rotate around the ring via
    ``ppermute`` while each device accumulates its queries' attention with an
    online (flash-style) softmax. Memory per device is O(S/n · S/n) per step
    and the ppermute overlaps with the block matmul — the pattern ICI's
    torus topology is built for.
  - **Ulysses attention** (`ulysses_attention`): DeepSpeed-Ulysses-style
    all-to-all that re-shards from sequence-split to head-split, runs plain
    (or flash) attention on full sequences for a head subset, and
    all-to-alls back. Cheaper at moderate sequence lengths; requires
    num_heads % axis_size == 0.

Both are written as *local* functions to be wrapped in a partial-manual
``jax.shard_map`` over only the ``sequence`` axis (other mesh axes stay under
GSPMD), via ``sequence_parallel_attention``.
"""

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

NEG_INF = -1e30


def _pcast_varying(tree, axis_name):
    """Mark arrays as device-varying over ``axis_name`` (JAX >= 0.9 VMA
    typing for shard_map carries); no-op on older versions."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(tree, (axis_name,), to="varying")
    return tree


def ring_attention(q, k, v, causal: bool = True, axis_name: str = "sequence",
                   sm_scale: Optional[float] = None):
    """Blockwise ring attention over ``axis_name`` (call inside shard_map).

    q: (B, S_local, H, hd); k/v: (B, S_local, Hkv, hd). Returns
    (B, S_local, H, hd). GQA is handled by repeating KV heads locally.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Sq, H, hd = q.shape
    nkv = k.shape[2]
    if nkv != H:
        rep = H // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    q32 = q.astype(jnp.float32)
    qpos = my * Sq + jnp.arange(Sq)

    m0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, hd), jnp.float32)
    m0, l0, acc0 = _pcast_varying((m0, l0, acc0), axis_name)
    perm = None  # built lazily from n (static under jit)

    def step(carry, i):
        kb, vb, m, l, acc = carry
        src = (my - i) % n  # global block index of the KV we currently hold
        kpos = src * Sq + jnp.arange(Sq)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kb.astype(jnp.float32)) * scale
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
        shift = [(j, (j + 1) % n) for j in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, shift)
        vb = jax.lax.ppermute(vb, axis_name, shift)
        return (kb, vb, m_new, l_new, acc_new), None

    (kb, vb, m, l, acc), _ = jax.lax.scan(step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l[..., None], 1e-20)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(q, k, v, causal: bool = True, axis_name: str = "sequence", attn_fn=None,
                      sm_scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style all-to-all attention (call inside shard_map).

    Re-shards (B, S/n, H, hd) -> (B, S, H/n, hd), runs full-sequence
    attention on the local head subset, then re-shards back.
    """
    H = q.shape[2]
    nkv = k.shape[2]
    if nkv != H:
        rep = H // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # scatter heads, gather sequence
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)
    if attn_fn is None:
        attn_fn = partial(_full_causal_attention, causal=causal, sm_scale=sm_scale)
    out = attn_fn(qh, kh, vh)
    # scatter sequence, gather heads
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True)


def _full_causal_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    # one implementation of plain attention in the repo (VERDICT r3 weak #7):
    # the Ulysses local step reuses the flash module's jnp reference
    from deepspeed_tpu.ops.pallas.flash_attention import mha_reference

    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


def sequence_parallel_attention(
    q,
    k,
    v,
    impl: str = "ring",
    causal: bool = True,
    mesh=None,
    seq_axis: str = "sequence",
    attn_impl: str = "xla",
    sm_scale: Optional[float] = None,
):
    """Top-level SPMD entry: q/k/v are (B, S, H, hd) global arrays; the
    attention runs sequence-parallel over ``seq_axis`` via partial-manual
    shard_map (other mesh axes remain under GSPMD). ``attn_impl='pallas'``
    runs the Ulysses local (full-sequence, head-subset) attention through
    the flash kernel — the memory win that makes long-context Ulysses
    practical (ring attention has its own online softmax already)."""
    if mesh is None:
        from deepspeed_tpu import comm

        mesh = comm.get_mesh()
    n = mesh.shape[seq_axis]
    if n <= 1:
        return _full_causal_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    S = q.shape[1]
    assert S % n == 0, f"seq len {S} must divide over {n} sequence shards"
    # combined sequence x tensor meshes: the ring and the xla Ulysses local
    # step are jnp einsums GSPMD partitions over 'tensor' on its own, but a
    # pallas_call is GSPMD-unpartitionable (it would all-gather and compute
    # every head replicated — see models/transformer._head_shard_map). When
    # Ulysses runs the flash kernel and a tensor axis is live, take that
    # axis manual too: heads shard over 'tensor' AND redistribute over
    # 'sequence' via the all-to-all, so each device runs H/(n*tp) heads.
    manual_axes = {seq_axis}
    head_axis = None
    tp = mesh.shape.get("tensor", 1)
    if impl == "ulysses":
        assert q.shape[2] % n == 0, f"num_heads {q.shape[2]} must divide over {n} for Ulysses"
        attn_fn = None
        if attn_impl == "pallas":
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

            if tp > 1 and q.shape[2] % (n * tp) == 0 and k.shape[2] % tp == 0:
                manual_axes = {seq_axis, "tensor"}
                head_axis = "tensor"
            attn_fn = partial(flash_attention, causal=causal, sm_scale=sm_scale,
                              vma=tuple(sorted(manual_axes)))
        local = partial(ulysses_attention, causal=causal, axis_name=seq_axis, attn_fn=attn_fn,
                        sm_scale=sm_scale)
    elif impl == "ring":
        local = partial(ring_attention, causal=causal, axis_name=seq_axis, sm_scale=sm_scale)
    else:
        raise ValueError(f"unknown sequence-parallel impl '{impl}' (ring | ulysses)")
    spec = PartitionSpec(None, seq_axis, head_axis, None)
    fn = _partial_manual_shard_map(local, mesh, manual_axes,
                                   in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _partial_manual_shard_map(fn, mesh, manual_axes, in_specs, out_specs):
    """shard_map manual over ``manual_axes`` only (other mesh axes stay
    under GSPMD): jax >= 0.8 spells that ``axis_names=``. Older jax's
    partial-auto support raises NotImplementedError on the collectives
    inside, so the fallback goes full-manual over every mesh axis — the
    specs only name seq/tensor axes, so inputs reshard (replicate) over
    the rest; a perf cost on combined meshes, never a wrong answer."""
    try:
        return jax.shard_map(fn, mesh=mesh, axis_names=manual_axes,
                             in_specs=in_specs, out_specs=out_specs)
    except (AttributeError, TypeError):
        # no jax.shard_map at all, or one without axis_names support
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_rep=False)
