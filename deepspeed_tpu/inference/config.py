"""Inference config (reference: ``deepspeed/inference/config.py``
DeepSpeedInferenceConfig — dtype, tensor_parallel, moe, quant,
replace_with_kernel_inject, max_out_tokens)."""

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deepspeed_tpu.runtime.config_utils import from_dict
from deepspeed_tpu.telemetry.config import TelemetryConfig


@dataclass
class QuantConfig:
    enabled: bool = False
    num_bits: int = 8


@dataclass
class MeshConfig:
    """Serving mesh for tensor-parallel inference (docs/inference.md
    "Tensor-parallel serving"). ``shape`` maps mesh axis names
    (comm.MESH_AXES; serving uses ``data``/``tensor``) to sizes — an
    explicit shape smaller than the host's device count builds a SUBSET
    mesh over the first ``prod(shape)`` devices (virtual-mesh A/Bs run
    several widths in one process); ``-1`` absorbs remaining devices as
    before. ``rules`` are regex partition-rule overrides,
    ``[[pattern, [axis, ...]], ...]`` matched against ``/``-joined param
    paths: on a model carrying ``logical_specs`` annotations they
    override placement PER MATCHED LEAF (unmatched params keep their
    annotation); without annotations — or under ``use_rules`` — they
    front the whole-tree regex table (parallel/partition.DEFAULT_RULES).
    The default (no shape, no rules) is the single-chip degenerate mesh
    — bit-identical to pre-mesh configs."""

    shape: Optional[Dict[str, int]] = None
    # regex partition-rule overrides (see class docstring)
    rules: Optional[List[Any]] = None
    # force the regex rule table even for models carrying logical_specs
    # annotations (default: annotations win, regex serves models without)
    use_rules: bool = False


@dataclass
class TensorParallelConfig:
    tp_size: int = 1
    enabled: bool = True


@dataclass
class MoEInferenceConfig:
    enabled: bool = False
    ep_size: int = 1


@dataclass
class SpeculativeConfig:
    """Speculative decoding (lossless: emitted tokens follow the target
    model's sampling distribution; greedy mode matches plain greedy decode
    token-for-token). ``mode`` picks the proposal source: ``"draft"`` — a
    second (smaller) model resident on the same mesh; ``"ngram"`` —
    jax-free self-drafting from the request's own token history
    (inference/ngram.py), no second model needed. ``pool`` additionally
    enables the speculative CONTINUOUS-BATCHING tick (docs/inference.md
    "Speculative decoding"): every pooled serving tick proposes
    ``num_draft_tokens`` per active row and verifies them in one target
    forward; requires single-token ticks."""

    enabled: bool = False
    num_draft_tokens: int = 4  # gamma: draft proposals verified per round
    mode: str = "draft"        # "draft" | "ngram"
    pool: bool = False         # speculate inside the pooled serving tick
    ngram_max_order: int = 3   # longest context suffix the ngram matcher tries


@dataclass
class InferenceConfig:
    dtype: str = "bfloat16"  # float32 | float16 | bfloat16 | int8 (weight quant)
    # KV-cache storage format: "model" (cache in model dtype) or "int8"
    # (per-token-per-head symmetric quantization — halves the cache-read
    # bytes that bound decode at long context and doubles servable context;
    # compute dequantizes at the attention read)
    kv_cache_dtype: str = "model"
    # tight-read cache geometry (default ON): decode/segment steps attend a
    # bucketed ACTIVE length (power-of-2 from kv_read_floor, block-granular
    # static slices over the cache time axis with the tail masked) instead
    # of the full allocated cache_len, and the per-token decode loop grows
    # its cache by bucket migration instead of allocating max_len upfront.
    # Decode is an HBM-bandwidth roofline — cache bytes streamed per token
    # are the cost — so this is a direct throughput lever at long
    # allocations (docs/inference.md "Cache geometry"). Token streams are
    # identical (the masked tail contributes exact zeros). Rolling (ring)
    # caches and speculative decoding keep their own geometry.
    kv_tight_read: bool = True
    # smallest tight-read bucket / initial migrated-cache allocation; each
    # growth doubles it. Keep a multiple of 128 on real TPUs (lane-aligned
    # slices); tests shrink it to exercise migration on tiny models.
    kv_read_floor: int = 128
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    moe: MoEInferenceConfig = field(default_factory=MoEInferenceConfig)
    quant: QuantConfig = field(default_factory=QuantConfig)
    speculative: SpeculativeConfig = field(default_factory=SpeculativeConfig)
    # structured request traces + latency metrics (docs/telemetry.md);
    # default off — generate() behavior is unchanged when disabled
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    # fuse the whole generation (prefill + lax.scan over decode steps) into
    # ONE compiled program: a single dispatch per generate() call instead of
    # one per token — per-token host dispatch dominates decode latency on
    # remote-dispatch links and costs ~100us/token even locally. Retraces per
    # distinct (batch, cache_len, max_new_tokens, sampling) combination;
    # disable for workloads that sweep many generation lengths.
    fused_generate: bool = True
    # rolling (ring-buffer) KV cache for uniform-sliding-window models
    # (Mistral): the cache holds only the last `window` positions — decode
    # HBM footprint and cache-read bytes are O(window) instead of O(total
    # length). Auto-applies when safe (uniform window, rope/no pos-emb,
    # flash prefill available, no speculative decoding); exact — slot
    # positions derive modulo the cache length.
    rolling_kv_cache: bool = True
    # chunked prefill: stream the prompt through a fixed (B, chunk) prefill
    # program instead of one program per prompt length. Serving workloads
    # with varied prompt lengths compile ONE prefill (each distinct length
    # otherwise pays its own 20-40s remote compile) and prefill peak memory
    # is bounded by the chunk. Trades the fused single-dispatch generate
    # for ceil(S/chunk) + per-token dispatches; token streams unchanged.
    prefill_chunk_size: Optional[int] = None
    # override the model's attention implementation for inference
    # ("xla" | "pallas" | "block_sparse"); None keeps the model config's.
    # Flash ("pallas") is exact and the TPU bench winner — converted
    # Llama/Mistral checkpoints already default to it via their policy.
    attn_impl: Optional[str] = None
    max_tokens: int = 1024  # alias accepted from reference configs
    replace_with_kernel_inject: bool = False  # TPU: kernels come from XLA/Pallas
    replace_method: str = "auto"
    enable_cuda_graph: bool = False  # no-op: XLA compiles whole programs
    profile_model_time: bool = False
    # serving mesh block: shape + regex partition-rule overrides; a plain
    # {axis: size} dict (the pre-mesh-block form) still parses as the
    # shape alone. None = the engine's default mesh (single-chip
    # degenerate unless tensor_parallel.tp_size says otherwise).
    mesh: MeshConfig = field(default_factory=MeshConfig)

    @classmethod
    def parse(cls, config) -> "InferenceConfig":
        if isinstance(config, cls):
            return config
        config = dict(config or {})
        # reference compat: max_tokens is the old name for max_out_tokens
        if "max_tokens" in config and "max_out_tokens" not in config:
            config["max_out_tokens"] = config["max_tokens"]
        # reference compat: mp_size / tensor_parallel.tp_size
        if "mp_size" in config:
            config.setdefault("tensor_parallel", {})
            if isinstance(config["tensor_parallel"], dict):
                config["tensor_parallel"].setdefault("tp_size", config.pop("mp_size"))
            else:
                config.pop("mp_size")
        tp = config.get("tensor_parallel", {})
        moe = config.get("moe", {})
        if isinstance(moe, bool):
            moe = {"enabled": moe}
        quant = config.get("quant", {})
        if isinstance(quant, bool):
            quant = {"enabled": quant}
        dtype = config.get("dtype", "bfloat16")
        if not isinstance(dtype, str):
            dtype = {"torch.float32": "float32", "torch.float16": "float16",
                     "torch.bfloat16": "bfloat16", "torch.int8": "int8"}.get(str(dtype), "bfloat16")
        spec = config.get("speculative", {})
        if isinstance(spec, bool):
            spec = {"enabled": spec}
        telemetry = config.get("telemetry", {})
        if isinstance(telemetry, bool):
            telemetry = {"enabled": telemetry}
        if isinstance(telemetry, TelemetryConfig):
            telemetry = dict(telemetry.__dict__)
        mesh = config.get("mesh", {})
        if not isinstance(mesh, MeshConfig):
            mesh = dict(mesh or {})
            if mesh and not (set(mesh) & set(MeshConfig.__dataclass_fields__)):
                # pre-mesh-block form: a plain {axis: size} dict IS the shape
                mesh = {"shape": mesh}
            mesh = from_dict(MeshConfig, mesh)
        known = {f for f in cls.__dataclass_fields__}
        base = {k: v for k, v in config.items()
                if k in known and k not in ("tensor_parallel", "moe", "quant", "speculative",
                                            "telemetry", "dtype", "mesh")}
        return cls(
            dtype=dtype,
            tensor_parallel=from_dict(TensorParallelConfig, tp if isinstance(tp, dict) else {}),
            moe=from_dict(MoEInferenceConfig, moe),
            quant=from_dict(QuantConfig, quant),
            speculative=from_dict(SpeculativeConfig, spec),
            telemetry=from_dict(TelemetryConfig, telemetry),
            mesh=mesh,
            **base,
        )
