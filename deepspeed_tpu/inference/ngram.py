"""Jax-free n-gram (self-drafting) proposal for speculative decoding.

The draft-free fallback of the speculative pooled tick
(decoding.compile_spec_pool_tick_fn, ngram variant): proposals come from
the request's OWN token history — find the longest recent n-gram whose
suffix matches the current context tail and propose the tokens that
followed it last time (prompt-echo, code, and structured output make this
surprisingly effective; cf. "prompt lookup decoding" / REST-style
retrieval drafting). Pure numpy on host state the scheduler already
holds, so drafting costs no device dispatch and no second model.

Losslessness does not depend on proposal quality: an n-gram proposal is a
point mass q = δ(d), for which the accept rule degenerates to
``u < p(d)`` and the residual to p with d's mass removed — any proposal
stream yields exactly the target distribution (greedy: exactly the argmax
chain). Under dispatch-ahead pipelining the host context LAGS the device
by up to ``pipeline_depth`` rounds; that only lowers the acceptance rate,
never correctness.
"""

from typing import Optional

import numpy as np


def propose(context, gamma: int, max_order: int = 3) -> np.ndarray:
    """``gamma`` proposed next tokens for one row given its token
    ``context`` (prompt + emitted so far, 1-D int array-like).

    Longest-suffix match: for order n = ``max_order``..1, find the MOST
    RECENT earlier occurrence of the context's last n tokens; the tokens
    that followed it are the proposal, extended greedily (the matched
    continuation may itself recur). Falls back to repeating the last
    token — a cheap constant proposal that still wins on runs."""
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    ctx = np.asarray(context, np.int32).reshape(-1)
    out = np.empty(gamma, np.int32)
    fill = ctx[-1] if ctx.size else 0
    start = _match_start(ctx, max_order)
    for i in range(gamma):
        if start is not None and start < ctx.size:
            out[i] = fill = ctx[start]
            start += 1
        else:
            out[i] = fill
    return out


def _match_start(ctx: np.ndarray, max_order: int) -> Optional[int]:
    """Index right after the most recent earlier occurrence of the longest
    matching context suffix (highest order wins; ties to recency), or None
    when nothing matches. Vectorized over candidate windows — this runs
    per active row per serving tick, so a python scan over the context
    would put O(context) host work on the tick hot path."""
    m = ctx.size
    for n in range(min(max_order, m - 1), 0, -1):
        tail = ctx[m - n:]
        # windows ctx[j:j+n] for j <= m-n-1 (ending before the tail
        # itself); one vectorized compare, most recent hit wins
        wins = np.lib.stride_tricks.sliding_window_view(ctx[:m - 1], n)
        hits = np.flatnonzero((wins == tail).all(axis=1))
        if hits.size:
            return int(hits[-1]) + n
    return None


def propose_rows(contexts, gamma: int, max_order: int = 3) -> np.ndarray:
    """(B, gamma) int32 proposals for a batch of per-row contexts (a list
    of 1-D arrays; rows may differ in length). Rows with empty context
    propose zeros."""
    return np.stack([propose(c, gamma, max_order) for c in contexts])
