"""Shared KV-cached decode machinery.

Single home for the compile-and-sample logic used by BOTH the standalone
``InferenceEngine`` (inference/engine.py) and the RLHF ``TpuHybridEngine``
(runtime/hybrid_engine.py) — same sharding selection, same prefill/decode
jits, same sampling loop, so fixes propagate to both surfaces.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec


def compile_decode_fns(mesh, cfg, param_shardings, batch_size: int, cache_len: int):
    """Build (prefill_fn, decode_fn, cache_sharding, batch_sharding) for a
    TransformerConfig ``cfg`` with params placed per ``param_shardings``."""
    from deepspeed_tpu.models import transformer as tf

    dp = mesh.shape["data"] * mesh.shape["fsdp"]
    batch_axes = ("data", "fsdp") if batch_size % dp == 0 else None
    kv_tensor = "tensor" if cfg.kv_heads % mesh.shape["tensor"] == 0 else None
    batch_sh = NamedSharding(mesh, PartitionSpec(batch_axes))
    cache_sh = jax.tree.map(
        lambda _: NamedSharding(mesh, PartitionSpec(None, batch_axes, None, kv_tensor, None)),
        tf.init_cache(cfg, 1, 8),
    )

    def prefill(params, tokens, cache):
        return tf.forward_with_cache(params, cfg, tokens, cache, 0)

    def decode(params, tok, cache, pos):
        logits, cache = tf.forward_with_cache(params, cfg, tok, cache, pos)
        return logits[:, -1], cache

    prefill_fn = jax.jit(
        prefill,
        in_shardings=(param_shardings, batch_sh, cache_sh),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )
    decode_fn = jax.jit(
        decode,
        in_shardings=(param_shardings, batch_sh, cache_sh, None),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )
    return prefill_fn, decode_fn, cache_sh, batch_sh


def select_token(logits, temperature: float, top_k: int, rng, top_p: float = 1.0) -> jnp.ndarray:
    """Greedy / temperature / top-k / nucleus (top-p) sampling, one token
    per row."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        # keep the smallest prefix of the sorted distribution with
        # cumulative mass >= top_p; the first token is always kept
        # (top_p <= 0 therefore means top-1)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # mass BEFORE this token still below p; the epsilon floor keeps the
        # top token in-support even at top_p=0.0
        keep = cum - probs < max(top_p, 1e-9)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def decode_loop(prefill_fn, decode_fn, params, tokens, cache, max_new_tokens: int,
                temperature: float, top_k: int, rng, top_p: float = 1.0) -> jnp.ndarray:
    """Prefill + token-by-token decode; returns (B, S + max_new_tokens)."""
    if max_new_tokens <= 0:
        return tokens
    S = tokens.shape[1]
    logits, cache = prefill_fn(params, tokens, cache)
    last = select_token(logits[:, -1], temperature, top_k, rng, top_p)
    out = [last]
    pos = S
    for _ in range(max_new_tokens - 1):
        rng, sub = jax.random.split(rng)
        step_logits, cache = decode_fn(params, out[-1][:, None], cache, pos)
        out.append(select_token(step_logits, temperature, top_k, sub, top_p))
        pos += 1
    return jnp.concatenate([tokens, jnp.stack(out, axis=1)], axis=1)


def bounded_cache_len(total: int, max_seq_len: int, max_out_tokens: Optional[int]) -> int:
    """KV-cache allocation: bounded by max_out_tokens, grown when the request
    needs more, never past max_seq_len."""
    if not max_out_tokens:
        return max_seq_len
    return max(total, min(max_seq_len, max_out_tokens))
