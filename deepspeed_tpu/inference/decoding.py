"""Shared KV-cached decode machinery.

Single home for the compile-and-sample logic used by BOTH the standalone
``InferenceEngine`` (inference/engine.py) and the RLHF ``TpuHybridEngine``
(runtime/hybrid_engine.py) — same sharding selection, same prefill/decode
jits, same sampling loop, so fixes propagate to both surfaces.
"""

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec


def _mark_first_token(timings: Optional[dict], token):
    """TTFT hook: when the caller passes a ``timings`` dict (telemetry
    enabled), block on the first sampled token and stamp its wall-clock.
    ``None`` (the default everywhere) keeps the async dispatch pipeline
    untouched."""
    if timings is not None:
        jax.block_until_ready(token)
        timings["first_token_s"] = time.time()


def read_bucket(n: int, cap: int, floor: int = 16) -> int:
    """Smallest power-of-2 length >= n (starting at ``floor``), clamped to
    ``cap``. The ONE bucketing rule for the whole decode stack: continuous-
    batching admission buckets, tight-read lengths, and the bucket-migrated
    cache growth all use it, so their geometries can never disagree."""
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)


def read_stages(prompt_len: int, n_steps: int, cache_len: int,
                floor: Optional[int]):
    """[(read_len_or_None, n_steps)] decode-step stages for a generation:
    step j attends ``prompt_len + j + 1`` cached slots, so it reads the
    bucket covering that extent. Consecutive steps sharing a bucket fuse
    into one stage (one ``lax.scan`` in the fused program, one compiled
    read geometry on the host-driven loop). ``floor=None`` = tight reads
    off — a single full-length stage. A read_len of ``None`` inside a
    stage means "the whole allocation" (bucket reached cache_len)."""
    if n_steps <= 0:
        return []
    if floor is None:
        return [(None, n_steps)]
    stages, j = [], 0
    while j < n_steps:
        r = read_bucket(prompt_len + j + 1, cache_len, floor)
        if r >= cache_len:
            stages.append((None, n_steps - j))
            break
        n = min(n_steps, r - prompt_len) - j
        stages.append((r, n))
        j += n
    return stages


def decode_kv_bytes(cfg, prompt_len: int, new_tokens: int, cache_len: int,
                    floor: Optional[int] = None, tp: int = 1) -> int:
    """Deterministic host-side accounting: KV-cache bytes ONE sequence row
    streams across the ``new_tokens - 1`` decode steps of a generation
    (prefill excluded — its read is the segment itself). This mirrors the
    read geometry the compiled programs actually execute (read_stages), so
    telemetry's ``kv_bytes_read`` is assertable in tests and comparable
    across tight/full configurations. ``tp`` (the cache's heads-axis shard
    width, parallel.partition.kv_shard_width) makes the number PER-CHIP:
    each chip of a tensor-parallel mesh streams only its head shard."""
    from deepspeed_tpu.models.transformer import kv_read_bytes_per_row

    total = 0
    for r, n in read_stages(prompt_len, new_tokens - 1, cache_len, floor):
        total += n * kv_read_bytes_per_row(cfg, r if r is not None else cache_len,
                                           tp=tp)
    return total


def _decode_shardings(mesh, cfg, batch_size: int):
    """(batch_sharding, cache_sharding) — the ONE sharding-selection policy
    for every cached-decode program (plain and speculative paths must place
    batch/KV identically or each call pays a reshard)."""
    from deepspeed_tpu.models import transformer as tf

    dp = mesh.shape["data"] * mesh.shape["fsdp"]
    batch_axes = ("data", "fsdp") if batch_size % dp == 0 else None
    kv_tensor = "tensor" if cfg.kv_heads % mesh.shape["tensor"] == 0 else None
    batch_sh = NamedSharding(mesh, PartitionSpec(batch_axes))
    cache_sh = jax.tree.map(
        lambda _: NamedSharding(mesh, PartitionSpec(None, batch_axes, None, kv_tensor, None)),
        tf.init_cache(cfg, 1, 8),
    )
    return batch_sh, cache_sh


def _tick_shardings(mesh, cfg, batch_size: int):
    """(row_sh, cache_sh, batch_sh) for the serving tick programs. The
    per-row scheduling state (pos/gen/quota/rids, the threaded
    last_tok/done) and the packed ``(B, k+2)`` acceptance buffer stay
    FULLY REPLICATED over the mesh: the host uploads/fetches them every
    tick, and a replicated buffer keeps that one coalesced transfer per
    tick instead of a per-device gather — the row vectors are a few
    hundred int32s, so replication costs nothing while the KV cache and
    params carry the real sharding (heads/hidden/vocab on ``tensor``)."""
    batch_sh, cache_sh = _decode_shardings(mesh, cfg, batch_size)
    row_sh = NamedSharding(mesh, PartitionSpec())
    return row_sh, cache_sh, batch_sh


def compile_decode_fns(mesh, cfg, param_shardings, batch_size: int, cache_len: int):
    """Build (prefill_fn, decode_fn, cache_sharding, batch_sharding) for a
    TransformerConfig ``cfg`` with params placed per ``param_shardings``."""
    from deepspeed_tpu.models import transformer as tf

    batch_sh, cache_sh = _decode_shardings(mesh, cfg, batch_size)

    def prefill(params, tokens, cache):
        return tf.forward_with_cache(params, cfg, tokens, cache, 0)

    def decode(params, tok, cache, pos):
        logits, cache = tf.forward_with_cache(params, cfg, tok, cache, pos)
        return logits[:, -1], cache

    prefill_fn = jax.jit(
        prefill,
        in_shardings=(param_shardings, batch_sh, cache_sh),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )
    decode_fn = jax.jit(
        decode,
        in_shardings=(param_shardings, batch_sh, cache_sh, None),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )
    return prefill_fn, decode_fn, cache_sh, batch_sh


def compile_generate_fn(mesh, cfg, param_shardings, batch_size: int, cache_len: int,
                        max_new_tokens: int, temperature: float, top_k: int,
                        top_p: float, read_floor: Optional[int] = None):
    """Whole-generation jit: prefill + ``lax.scan`` over the decode steps in
    ONE compiled program — one dispatch per ``generate()`` call instead of
    one per token (the per-token host round trip dominates decode wall time
    on remote-dispatch links: r5 measured 22.3 ms/token at 350M against a
    ~1 ms roofline). Token stream is bitwise-identical to ``decode_loop``:
    same rng split order, same select_token calls.

    ``read_floor`` enables tight cache reads inside the fused program: the
    decode scan splits into bucket stages (read_stages) so early steps
    attend a power-of-2 window over the active cache prefix instead of the
    full allocation — same token stream (the masked tail is exact zeros),
    roughly half the cache bytes per generation at typical lengths.

    Returns ``(generate_fn, cache_sh, batch_sh)`` with
    ``generate_fn(params, tokens, cache, rng) -> (B, S + max_new_tokens)``.
    """
    from functools import partial

    from deepspeed_tpu.models import transformer as tf

    batch_sh, cache_sh = _decode_shardings(mesh, cfg, batch_size)

    def run(params, tokens, cache, rng):
        S = tokens.shape[1]
        logits, cache = tf.forward_with_cache(params, cfg, tokens, cache, 0)
        first = select_token(logits[:, -1], temperature, top_k, rng, top_p)

        def body(carry, _, read_len=None):
            last, cache, rng, pos = carry
            rng, sub = jax.random.split(rng)
            step_logits, cache = tf.forward_with_cache(
                params, cfg, last[:, None], cache, pos, read_len=read_len)
            tok = select_token(step_logits[:, -1], temperature, top_k, sub, top_p)
            return (tok, cache, rng, pos + 1), tok

        carry = (first, cache, rng, jnp.int32(S))
        outs = []
        for r, n in read_stages(S, max_new_tokens - 1, cache_len, read_floor):
            carry, toks = jax.lax.scan(partial(body, read_len=r), carry, None,
                                       length=n)
            outs.append(toks)
        cache = carry[1]
        rest = (jnp.moveaxis(jnp.concatenate(outs, axis=0), 0, 1)
                if outs else tokens[:, :0])
        seq = jnp.concatenate([tokens, first[:, None], rest], axis=1)
        # the final cache is returned (and dropped by the caller) so the
        # donated input cache aliases an output instead of warning
        return seq, cache

    jitted = jax.jit(
        run,
        in_shardings=(param_shardings, batch_sh, cache_sh, None),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )

    def fn(params, tokens, cache, rng):
        seq, _ = jitted(params, tokens, cache, rng)
        return seq

    return fn, cache_sh, batch_sh


def compile_ragged_prefill_fn(mesh, cfg, param_shardings, batch_size: int, cache_len: int):
    """Jit a prefill over LEFT- or RIGHT-padded prompts: explicit (B, S)
    positions (pads carry position >= cache_len so their KV writes drop;
    real tokens pack densely at 0..len-1 per row). Returns
    (ragged_prefill_fn, cache_sh, batch_sh)."""
    from deepspeed_tpu.models import transformer as tf

    batch_sh, cache_sh = _decode_shardings(mesh, cfg, batch_size)

    def prefill(params, tokens, positions, cache):
        zero = jnp.zeros((tokens.shape[0],), jnp.int32)
        return tf.forward_with_cache(params, cfg, tokens, cache, zero, positions=positions)

    fn = jax.jit(
        prefill,
        in_shardings=(param_shardings, batch_sh, batch_sh, cache_sh),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(3,),
    )
    return fn, cache_sh, batch_sh


def _segment_decode_tail(segment_fn, params, first_tok, cache, prompt_lens,
                         n_more: int, temperature: float, top_k: int, rng,
                         top_p: float, active0: Optional[int] = None):
    """Per-row-position decode loop shared by the ragged and chunked-prefill
    generate paths: ``first_tok`` (B,) was already sampled from the prefill
    logits; emits ``n_more`` further tokens. ``active0`` (the longest row's
    cached extent before the first step, host int) opts into tight reads:
    each step passes the active extent to a read-geometry-aware
    ``segment_fn`` dispatcher (the engine's) — plain 4-arg compiled segment
    fns are called unchanged when it is None."""
    out = [first_tok]
    pos = jnp.asarray(prompt_lens)
    for i in range(n_more):
        rng, sub = jax.random.split(rng)
        if active0 is None:
            step_logits, cache = segment_fn(params, out[-1][:, None], cache, pos)
        else:
            step_logits, cache = segment_fn(params, out[-1][:, None], cache, pos,
                                            active=active0 + i + 1)
        out.append(select_token(step_logits[:, 0], temperature, top_k, sub, top_p))
        pos = pos + 1
    return jnp.stack(out, axis=1)


def ragged_decode_loop(ragged_prefill_fn, segment_fn, params, tokens, attention_mask,
                       cache, cache_len: int, max_new_tokens: int, temperature: float,
                       top_k: int, rng, top_p: float = 1.0,
                       timings: Optional[dict] = None,
                       tight_read: bool = False) -> jnp.ndarray:
    """Generate over a PADDED prompt batch (HF attention_mask semantics,
    left or right padding): prefill once with per-row dense positions, then
    per-row-position decode. Returns (B, S + max_new_tokens) — the prompt
    region is returned as given (pads included); generated tokens follow.
    """
    import numpy as np

    mask = np.asarray(attention_mask)
    B, S = tokens.shape
    if max_new_tokens <= 0:
        return tokens
    assert mask.shape == (B, S), (mask.shape, (B, S))
    prompt_lens = mask.sum(axis=1).astype(np.int32)
    assert (prompt_lens > 0).all(), "every row needs at least one real token"
    # dense per-row positions; pads land at cache_len -> dropped writes
    positions = np.where(mask > 0, np.cumsum(mask, axis=1) - 1, cache_len).astype(np.int32)
    logits, cache = ragged_prefill_fn(params, jnp.asarray(tokens), jnp.asarray(positions), cache)
    # logits column of each row's LAST real token
    last_col = np.array([np.nonzero(mask[b])[0][-1] for b in range(B)])
    last_logits = jnp.take_along_axis(
        logits, jnp.asarray(last_col)[:, None, None], axis=1
    )[:, 0]
    nxt = select_token(last_logits, temperature, top_k, rng, top_p)
    _mark_first_token(timings, nxt)
    gen = _segment_decode_tail(segment_fn, params, nxt, cache, prompt_lens,
                               max_new_tokens - 1, temperature, top_k, rng, top_p,
                               active0=int(prompt_lens.max()) if tight_read else None)
    return jnp.concatenate([jnp.asarray(tokens), gen], axis=1)


def chunked_generate(ragged_prefill_fn, segment_fn, params, tokens, cache,
                     cache_len: int, chunk: int, max_new_tokens: int,
                     temperature: float, top_k: int, rng,
                     top_p: float = 1.0, attention_mask=None,
                     timings: Optional[dict] = None,
                     tight_read: bool = False) -> jnp.ndarray:
    """Generate with CHUNKED prefill: the prompt streams through a fixed
    (B, chunk) prefill program, so ONE compiled program serves every prompt
    length (each distinct length otherwise compiles its own prefill — 20-40s
    per variant through a remote-compile link) and prefill peak memory is
    bounded by the chunk, not the prompt. The final (padded) chunk drops its
    pad writes via out-of-range positions; decode then shares the ragged
    per-row segment tail. Token streams are identical to the unchunked path
    (same cache contents, same sampling order).

    ``attention_mask`` ((B, S) of 0/1, HF semantics, left or right padding)
    composes: per-row dense positions come from the mask — the varied-width
    serving batches that motivate chunking in the first place still reuse
    the one chunk program.
    """
    import numpy as np

    B, S = tokens.shape
    if max_new_tokens <= 0:
        return tokens
    assert chunk >= 1, chunk
    if attention_mask is None:
        mask = np.ones((B, S), np.int64)
    else:
        mask = np.asarray(attention_mask)
        assert mask.shape == (B, S), (mask.shape, (B, S))
        assert (mask.sum(axis=1) > 0).all(), "every row needs at least one real token"
    prompt_lens = mask.sum(axis=1).astype(np.int32)
    # dense per-row positions; pads park at cache_len -> writes drop and
    # their garbage logits are never selected
    positions_all = np.where(mask > 0, np.cumsum(mask, axis=1) - 1, cache_len).astype(np.int32)
    last_col_all = np.array([np.nonzero(mask[b])[0][-1] for b in range(B)])

    n_chunks = -(-S // chunk)
    padded_toks = np.zeros((B, n_chunks * chunk), np.int32)
    padded_toks[:, :S] = np.asarray(tokens)
    padded_pos = np.full((B, n_chunks * chunk), cache_len, np.int32)
    padded_pos[:, :S] = positions_all

    last_logits = None
    for i in range(n_chunks):
        lo, hi = i * chunk, (i + 1) * chunk
        if (padded_pos[:, lo:hi] >= cache_len).all():
            continue  # all-pad chunk (left padding / width padding)
        logits, cache = ragged_prefill_fn(
            params, jnp.asarray(padded_toks[:, lo:hi]),
            jnp.asarray(padded_pos[:, lo:hi]), cache)
        # rows whose LAST real token lands in this chunk take their logits
        in_chunk = (last_col_all >= lo) & (last_col_all < hi)
        col = jnp.asarray(np.where(in_chunk, last_col_all - lo, 0))
        picked = jnp.take_along_axis(logits, col[:, None, None], axis=1)[:, 0]
        sel = jnp.asarray(in_chunk)[:, None]
        last_logits = picked if last_logits is None else jnp.where(sel, picked, last_logits)
    nxt = select_token(last_logits, temperature, top_k, rng, top_p)
    _mark_first_token(timings, nxt)
    gen = _segment_decode_tail(segment_fn, params, nxt, cache, prompt_lens,
                               max_new_tokens - 1, temperature, top_k, rng, top_p,
                               active0=int(prompt_lens.max()) if tight_read else None)
    return jnp.concatenate([jnp.asarray(tokens), gen], axis=1)


def _filter_logits(logits, temperature: float, top_k: int, top_p: float):
    """Temperature / top-k / nucleus filtering over (B, V) logits. The ONE
    implementation shared by plain sampling (select_token) and the
    speculative p/q distributions — speculative losslessness requires both
    paths to filter identically."""
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        # keep the smallest prefix of the sorted distribution with
        # cumulative mass >= top_p; the first token is always kept
        # (top_p <= 0 therefore means top-1)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # mass BEFORE this token still below p; the epsilon floor keeps the
        # top token in-support even at top_p=0.0
        keep = cum - probs < max(top_p, 1e-9)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


def select_token(logits, temperature: float, top_k: int, rng, top_p: float = 1.0) -> jnp.ndarray:
    """Greedy / temperature / top-k / nucleus (top-p) sampling, one token
    per row."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, _filter_logits(logits, temperature, top_k, top_p), axis=-1
    ).astype(jnp.int32)


def decode_loop(prefill_fn, decode_fn, params, tokens, cache, max_new_tokens: int,
                temperature: float, top_k: int, rng, top_p: float = 1.0,
                timings: Optional[dict] = None) -> jnp.ndarray:
    """Prefill + token-by-token decode; returns (B, S + max_new_tokens)."""
    if max_new_tokens <= 0:
        return tokens
    S = tokens.shape[1]
    logits, cache = prefill_fn(params, tokens, cache)
    last = select_token(logits[:, -1], temperature, top_k, rng, top_p)
    _mark_first_token(timings, last)
    out = [last]
    pos = S
    for _ in range(max_new_tokens - 1):
        rng, sub = jax.random.split(rng)
        step_logits, cache = decode_fn(params, out[-1][:, None], cache, pos)
        out.append(select_token(step_logits, temperature, top_k, sub, top_p))
        pos += 1
    return jnp.concatenate([tokens, jnp.stack(out, axis=1)], axis=1)


def compile_segment_fn(mesh, cfg, param_shardings, batch_size: int, cache_len: int,
                       read_len: Optional[int] = None):
    """Jit a cached segment forward with PER-ROW positions (``pos``: (B,)
    int32); any segment width retraces under the same jit wrapper. Used by
    speculative decoding, where rows advance by their own accepted counts.
    ``read_len`` builds the tight-read variant: attention streams only the
    first ``read_len`` cache slots — the caller (the engine's bucket
    dispatcher, the continuous pools' tick) guarantees every live row's
    extent fits. Returns (segment_fn, cache_sh, batch_sh)."""
    from deepspeed_tpu.models import transformer as tf

    batch_sh, cache_sh = _decode_shardings(mesh, cfg, batch_size)

    def segment(params, toks, cache, pos):
        return tf.forward_with_cache(params, cfg, toks, cache, pos,
                                     read_len=read_len)

    segment_fn = jax.jit(
        segment,
        in_shardings=(param_shardings, batch_sh, cache_sh, batch_sh),
        out_shardings=(batch_sh, cache_sh),
        donate_argnums=(2,),
    )
    return segment_fn, cache_sh, batch_sh


def request_keys(base_key, rids, gens):
    """Per-row sampling keys for the serving tick programs:
    ``fold_in(fold_in(base, rid), gen)`` vmapped over the batch. A request's
    sampled stream therefore depends only on (engine seed, request id, token
    index) — never on which slot it landed in, which tick it joined, or how
    many ticks are in flight. That independence is what makes the pipelined
    (dispatch-ahead) and fused-prefill tick modes bitwise-identical to the
    sync scheduler: scheduling may shift WHEN a token is produced, never
    WHAT it is."""
    def one(rid, gen):
        return jax.random.fold_in(jax.random.fold_in(base_key, rid), gen)

    return jax.vmap(one)(rids, gens)


# Speculative tick RNG lanes: a THIRD fold_in on top of request_keys'
# (seed, rid, token_index) identity separates the three independent draws
# speculation makes per token index — the draft proposal, the acceptance
# uniform, and the bonus/correction draw. Lane keys can never collide with
# the plain path's two-fold keys (different fold depth), and rejection
# sampling stays correct because the residual draw at an index is
# independent of the acceptance uniform that rejected the proposal there.
LANE_DRAFT, LANE_ACCEPT, LANE_BONUS = 1, 2, 3


def spec_request_keys(base_key, rids, gens, lane: int):
    """Per-row speculative sampling keys:
    ``fold_in(fold_in(fold_in(base, rid), gen), lane)`` vmapped over the
    batch. Like :func:`request_keys`, the key depends only on (engine
    seed, request id, token index, lane) — never on slot placement, tick
    depth, or how many proposals earlier rounds accepted — so speculative
    sampled streams are reproducible across pipeline depths, fusion modes,
    and gamma."""
    def one(rid, gen):
        k = jax.random.fold_in(jax.random.fold_in(base_key, rid), gen)
        return jax.random.fold_in(k, lane)

    return jax.vmap(one)(rids, gens)


def select_token_rows(logits, temperature: float, top_k: int, keys,
                      top_p: float = 1.0) -> jnp.ndarray:
    """Row-wise :func:`select_token`: one key per row (request_keys) instead
    of one key per batch, same temperature/top-k/top-p filter."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = _filter_logits(logits, temperature, top_k, top_p)
    return jax.vmap(jax.random.categorical)(keys, filtered).astype(jnp.int32)


def compile_pool_tick_fn(mesh, cfg, param_shardings, batch_size: int,
                         cache_len: int, n_tokens: int, temperature: float,
                         top_k: int, top_p: float,
                         eos_token_id: Optional[int] = None,
                         read_len: Optional[int] = None,
                         chunk: Optional[int] = None,
                         donate: bool = True):
    """One continuous-batching scheduler tick as ONE compiled program with
    ON-DEVICE ACCEPTANCE: the forward, per-row sampling (request_keys),
    EOS/quota done detection, position advance, and emission masking all
    run inside the jit, and the tick returns one small packed int32 buffer
    — ``(B, n_tokens + 2)``: ``[:, :k]`` sampled tokens, ``[:, k]``
    n_emitted, ``[:, k+1]`` the done flag — so the host fetches a single
    coalesced buffer per tick instead of per-row logits/acceptance state.
    ``last_tok`` and ``done`` are device-THREADED (returned as outputs that
    feed the next tick's inputs), which is what lets the engine keep a tick
    in flight: tick N+1 can be dispatched on tick N's output futures before
    the host ever looks at tick N's packed result.

    Plain / burst (``chunk=None``)::

        tick_fn(params, cache, last_tok, done, pos, gen, quota, rids, key)
          -> (packed, cache, last_tok, done)

    ``pos``/``gen``/``quota``/``rids`` are per-row int32 vectors the host
    uploads each tick (it knows them deterministically for live rows; rows
    it parks carry ``pos = cache_len`` so their KV writes drop). ``quota``
    is the row's max_new_tokens; a row whose token hits EOS or exhausts the
    quota flips its done flag and freezes (emission masked, last_tok/pos
    held) for any remaining burst steps and for any tick already in flight.

    Fused prefill (``chunk=W``, requires ``n_tokens == 1``): the same tick
    additionally prefills ONE admitting row's next W-wide prompt chunk
    inside the same dispatch (Dynamic-SplitFuse-style) — decode rows ride
    column 0, the admitting row carries ``chunk_toks``/``chunk_pos`` (pads
    parked at ``cache_len``), and ``emit_col``/``emit_mask`` route sampling
    to the admitting row's last real prompt column on its final chunk::

        tick_fn(params, cache, last_tok, done, pos, gen, quota, rids, key,
                chunk_toks, chunk_pos, admit_slot, emit_col, emit_mask)
          -> (packed, cache, last_tok, done)

    The cache AND the threaded last_tok/done buffers are donated
    (``donate_argnums``), so per-tick copies of the KV pool disappear from
    HBM traffic. ``donate=False`` opts out: the jax CPU backend implements
    donation by BLOCKING at dispatch until the donated buffer is free,
    which serializes the tick chain and defeats dispatch-ahead pipelining
    — the virtual-mesh loadgen A/B runs donation-off to measure the
    overlap; on TPU donation and async dispatch compose and both stay on.
    Returns ``(tick_fn, cache_sh, batch_sh)``.
    """
    from deepspeed_tpu.models import transformer as tf

    row_sh, cache_sh, _ = _tick_shardings(mesh, cfg, batch_size)
    k = n_tokens
    assert k >= 1, k
    donate_argnums = (1, 2, 3) if donate else ()

    def accept(tok, last_tok, done, gen, quota, emit_mask):
        """Shared acceptance: which rows emit this step, updated state."""
        live = (done == 0) & (emit_mask == 1)
        gen2 = jnp.where(live, gen + 1, gen)
        stop = gen2 >= quota
        if eos_token_id is not None:
            stop = stop | (tok == eos_token_id)
        done2 = jnp.where(live & stop, 1, done)
        last2 = jnp.where(live, tok, last_tok)
        return last2, done2, gen2, live.astype(jnp.int32)

    def sample(logits, rids, gen, base_key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        keys = request_keys(base_key, rids, gen)
        return select_token_rows(logits, temperature, top_k, keys, top_p)

    if chunk is None:
        ones = jnp.ones((batch_size,), jnp.int32)

        def run(params, cache, last_tok, done, pos, gen, quota, rids, base_key):
            def body(carry, _):
                cache, last_tok, done, pos, gen = carry
                logits, cache = tf.forward_with_cache(
                    params, cfg, last_tok[:, None], cache, pos,
                    read_len=read_len)
                tok = sample(logits[:, 0], rids, gen, base_key)
                last2, done2, gen2, emitted = accept(
                    tok, last_tok, done, gen, quota, ones)
                pos2 = jnp.where(done == 0, pos + 1, pos)
                return (cache, last2, done2, pos2, gen2), (tok, emitted)

            (cache, last_tok, done, _, _), (toks, emitted) = jax.lax.scan(
                body, (cache, last_tok, done, pos, gen), None, length=k)
            packed = jnp.concatenate(
                [jnp.moveaxis(toks, 0, 1),
                 emitted.sum(axis=0, dtype=jnp.int32)[:, None],
                 done[:, None]], axis=1)
            return packed, cache, last_tok, done

        fn = jax.jit(
            run,
            in_shardings=(param_shardings, cache_sh, row_sh, row_sh,
                          row_sh, row_sh, row_sh, row_sh, None),
            out_shardings=(row_sh, cache_sh, row_sh, row_sh),
            donate_argnums=donate_argnums,
        )
        return fn, cache_sh, row_sh

    assert k == 1, "fused-prefill ticks are single-token (burst admits " \
                   "between bursts via the separate-prefill path)"
    W = chunk

    def run(params, cache, last_tok, done, pos, gen, quota, rids, base_key,
            chunk_toks, chunk_pos, admit_slot, emit_col, emit_mask):
        toks = jnp.zeros((batch_size, W), jnp.int32).at[:, 0].set(last_tok)
        toks = toks.at[admit_slot].set(chunk_toks)
        positions = jnp.full((batch_size, W), cache_len, jnp.int32)
        positions = positions.at[:, 0].set(pos).at[admit_slot].set(chunk_pos)
        logits, cache = tf.forward_with_cache(
            params, cfg, toks, cache, pos, positions=positions,
            read_len=read_len)
        sel = jnp.take_along_axis(logits, emit_col[:, None, None], axis=1)[:, 0]
        tok = sample(sel, rids, gen, base_key)
        last2, done2, gen2, emitted = accept(
            tok, last_tok, done, gen, quota, emit_mask)
        packed = jnp.concatenate(
            [tok[:, None], emitted[:, None], done2[:, None]], axis=1)
        return packed, cache, last2, done2

    fn = jax.jit(
        run,
        in_shardings=(param_shardings, cache_sh, row_sh, row_sh,
                      row_sh, row_sh, row_sh, row_sh, None,
                      None, None, None, row_sh, row_sh),
        out_shardings=(row_sh, cache_sh, row_sh, row_sh),
        donate_argnums=donate_argnums,
    )
    return fn, cache_sh, row_sh


def compile_row_update_fn(mesh, cfg, batch_size: int, donate: bool = True):
    """Tiny jitted row update for the device-threaded tick state: admission
    sets one slot's ``last_tok``/``done`` without fetching or rebuilding the
    (possibly still in-flight) arrays — the update is dispatched against the
    current output futures and chains behind any tick already queued. Both
    operands are donated (in-place on device); ``donate`` follows the
    engine's ``donate_cache`` knob — the CPU backend blocks donated
    dispatches, and admission must stay enqueue-only in overlap
    measurements. Returns ``set_row(last_tok, done, slot, tok, flag) ->
    (last_tok, done)``."""
    row_sh, _, _ = _tick_shardings(mesh, cfg, batch_size)

    def set_row(last_tok, done, slot, tok, flag):
        return last_tok.at[slot].set(tok), done.at[slot].set(flag)

    return jax.jit(
        set_row,
        in_shardings=(row_sh, row_sh, None, None, None),
        out_shardings=(row_sh, row_sh),
        donate_argnums=(0, 1) if donate else (),
    )


def compile_spec_pool_tick_fn(mesh, cfg, param_shardings, batch_size: int,
                              cache_len: int, gamma: int, temperature: float,
                              top_k: int, top_p: float,
                              eos_token_id: Optional[int] = None,
                              read_len: Optional[int] = None,
                              donate: bool = True,
                              draft_cfg=None, draft_param_shardings=None):
    """Speculative continuous-batching tick: per dispatch, every active row
    proposes ``gamma`` tokens, ONE target forward over the (gamma+1)-wide
    window verifies all rows at once, and the lossless accept/correct rule
    (the on-device mirror of :func:`_accept_round`) runs inside the jit —
    per-row accept counts, the bonus token, and the rollback positions land
    in one packed int32 buffer, so the host keeps its single coalesced
    fetch per tick and ``pipeline_depth`` dispatch-ahead composes
    unchanged.

    Two drafting variants share the verify/accept machinery:

    Draft-model (``draft_cfg`` + ``draft_param_shardings`` given): a second
    param tree resident on the same mesh proposes autoregressively through
    its own pool-geometry KV cache (gamma single-token steps + one extra
    step caching the final proposal's KV, mirroring
    :func:`speculative_decode_loop`)::

        run(params, draft_params, cache, draft_cache, last_tok, done, pos,
            gen, quota, rids, run_mask, base_key)
          -> (packed, cache, draft_cache, last_tok, done, pos, gen)

    N-gram / self-drafting (``draft_cfg=None``): the host proposes
    ``drafts`` (B, gamma) from each request's own emitted context
    (inference/ngram.py) — a POINT-MASS proposal q = δ(d), for which the
    acceptance rule degenerates to ``u < p(d)`` and the residual to p with
    d's mass removed; losslessness holds for any proposal, so speculation
    needs no second model::

        run(params, cache, last_tok, done, pos, gen, quota, rids, run_mask,
            drafts, base_key)
          -> (packed, cache, last_tok, done, pos, gen)

    ``pos``/``gen`` are device-THREADED here (unlike the plain tick, where
    the host mirrors them exactly): a row advances by its own accepted
    count, which the host only learns at retire time, so the authoritative
    copies ride the tick chain and the host keeps an upper-bound mirror
    for read-geometry selection only. ``run_mask`` (1 = this row decodes
    this tick) parks rows the host cannot run (mid-prefill, quota already
    covered by in-flight ticks) without touching their threaded state.
    Parked and done rows write at position ``cache_len`` — the vector-pos
    cache scatter drops out-of-range columns, which also makes the
    quota-tail window overrun safe: columns past a row's last needed
    position drop their KV writes and their outputs are quota-clipped out
    of acceptance.

    ``packed`` is (B, gamma+4) int32: ``[:, :gamma+1]`` the emitted tokens
    (accepted prefix then bonus/correction), ``[:, gamma+1]`` n_emitted,
    ``[:, gamma+2]`` the done flag, ``[:, gamma+3]`` the accepted draft
    count (telemetry + host mirror reconciliation). Greedy mode emits the
    target argmax chain token-for-token identically to the plain tick;
    sampled mode draws from lane-separated :func:`spec_request_keys`, so
    streams are reproducible across scheduling but (like any rejection
    sampler) equal to the plain stream in distribution, not bitwise.
    Returns ``(run_fn, cache_sh, row_sh)``."""
    from deepspeed_tpu.models import transformer as tf

    row_sh, cache_sh, _ = _tick_shardings(mesh, cfg, batch_size)
    assert gamma >= 1, gamma
    B, g1 = batch_size, gamma + 1
    greedy = temperature <= 0.0
    draft_mode = draft_cfg is not None
    if draft_mode:
        _, draft_cache_sh = _decode_shardings(mesh, draft_cfg, batch_size)
    iota_g = jnp.arange(gamma, dtype=jnp.int32)
    iota_g1 = jnp.arange(g1, dtype=jnp.int32)

    def accept_round(vlogits, drafts, qstack, active, pos, gen, quota,
                     last_tok, done, rids, base_key):
        """On-device mirror of :func:`_accept_round` plus the emission/
        state bookkeeping the host loop does around it. ``qstack`` None
        means a point-mass proposal (ngram)."""
        if greedy:
            tgt = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # (B, g1)
            match = drafts == tgt[:, :gamma]
        else:
            V = vlogits.shape[-1]
            p = _filtered_probs(
                vlogits.reshape(B * g1, V), temperature, top_k, top_p
            ).reshape(B, g1, V)
            p_at = jnp.take_along_axis(
                p[:, :gamma], drafts[..., None], axis=2)[..., 0]
            if qstack is None:
                ratio = p_at  # point-mass proposal: q(d) == 1
            else:
                q_at = jnp.take_along_axis(
                    qstack, drafts[..., None], axis=2)[..., 0]
                ratio = p_at / jnp.maximum(q_at, 1e-20)

            def urow(rid, g0):
                def at(i):
                    k = jax.random.fold_in(
                        jax.random.fold_in(base_key, rid), g0 + i)
                    return jax.random.uniform(
                        jax.random.fold_in(k, LANE_ACCEPT))

                return jax.vmap(at)(iota_g)

            u = jax.vmap(urow)(rids, gen)
            match = u < jnp.minimum(1.0, ratio)
        n_acc = jnp.where(
            match.all(axis=1), gamma,
            jnp.argmin(match.astype(jnp.int32), axis=1)).astype(jnp.int32)

        rem = jnp.maximum(quota - gen, 0)
        n_take = jnp.minimum(n_acc, rem)
        if eos_token_id is not None:
            eos_mask = (drafts == eos_token_id) & (iota_g[None] < n_take[:, None])
            took_eos = eos_mask.any(axis=1)
            first_eos = jnp.where(
                took_eos, jnp.argmax(eos_mask.astype(jnp.int32), axis=1), gamma)
            n_take = jnp.minimum(n_take, first_eos + 1)
        else:
            took_eos = jnp.zeros((B,), bool)
        took_eos = took_eos & active
        n_take = jnp.where(active, n_take, 0).astype(jnp.int32)

        bonus_ok = active & ~took_eos & (n_take == n_acc) & (gen + n_take < quota)
        if greedy:
            bonus = jnp.take_along_axis(tgt, n_take[:, None], axis=1)[:, 0]
        else:
            p_b = jnp.take_along_axis(p, n_take[:, None, None], axis=1)[:, 0]
            if qstack is None:
                d_b = jnp.take_along_axis(
                    drafts, jnp.minimum(n_take, gamma - 1)[:, None], axis=1)[:, 0]
                q_b = jax.nn.one_hot(d_b, p_b.shape[-1], dtype=p_b.dtype)
            else:
                q_b = jnp.take_along_axis(
                    qstack, jnp.minimum(n_take, gamma - 1)[:, None, None],
                    axis=1)[:, 0]
            residual = jnp.maximum(p_b - q_b, 0.0)
            dist = jnp.where((n_take < gamma)[:, None], residual, p_b)
            tot = dist.sum(axis=1, keepdims=True)
            dist = jnp.where(tot > 0, dist / jnp.where(tot > 0, tot, 1.0), p_b)
            bkeys = spec_request_keys(base_key, rids, gen + n_take, LANE_BONUS)
            bonus = jax.vmap(jax.random.categorical)(
                bkeys, jnp.where(dist > 0, jnp.log(dist), -1e30)
            ).astype(jnp.int32)

        n_emit = n_take + bonus_ok.astype(jnp.int32)
        pad_drafts = jnp.concatenate(
            [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
        tok_out = jnp.where(
            iota_g1[None] < n_take[:, None], pad_drafts,
            jnp.where((iota_g1[None] == n_take[:, None]) & bonus_ok[:, None],
                      bonus[:, None], 0))
        gen2 = jnp.where(active, gen + n_emit, gen)
        done2 = jnp.where(active & (took_eos | (gen2 >= quota)), 1, done)
        if eos_token_id is not None:
            done2 = jnp.where(active & bonus_ok & (bonus == eos_token_id),
                              1, done2)
        last2 = jnp.where(active & bonus_ok, bonus, last_tok)
        # rollback-on-rejection IS the position rule: the next round's
        # window starts right after the last verified input column the row
        # consumed, so rejected drafts' KV is overwritten before any later
        # query's causal extent reaches it (windows tile contiguously)
        pos2 = jnp.where(active, pos + n_take + 1, pos)
        packed = jnp.concatenate(
            [tok_out, n_emit[:, None], done2[:, None], n_take[:, None]],
            axis=1)
        return packed, last2, done2, pos2, gen2

    if not draft_mode:
        def run(params, cache, last_tok, done, pos, gen, quota, rids,
                run_mask, drafts, base_key):
            active = (done == 0) & (run_mask == 1)
            wpos = jnp.where(active, pos, cache_len)
            seg = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            vlogits, cache = tf.forward_with_cache(
                params, cfg, seg, cache, wpos, read_len=read_len)
            packed, last2, done2, pos2, gen2 = accept_round(
                vlogits, drafts, None, active, pos, gen, quota,
                last_tok, done, rids, base_key)
            return packed, cache, last2, done2, pos2, gen2

        fn = jax.jit(
            run,
            in_shardings=(param_shardings, cache_sh, row_sh, row_sh, row_sh,
                          row_sh, row_sh, row_sh, row_sh, row_sh, None),
            out_shardings=(row_sh, cache_sh, row_sh, row_sh, row_sh, row_sh),
            donate_argnums=(1, 2, 3, 4, 5) if donate else (),
        )
        return fn, cache_sh, row_sh

    def run(params, draft_params, cache, draft_cache, last_tok, done, pos,
            gen, quota, rids, run_mask, base_key):
        active = (done == 0) & (run_mask == 1)
        wpos = jnp.where(active, pos, cache_len)

        def dbody(carry, i):
            dcache, cur = carry
            dlogits, dcache = tf.forward_with_cache(
                draft_params, draft_cfg, cur[:, None], dcache,
                jnp.where(active, pos + i, cache_len), read_len=read_len)
            lg = dlogits[:, 0]
            if greedy:
                d = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (dcache, d), d
            q = _filtered_probs(lg, temperature, top_k, top_p)
            keys = spec_request_keys(base_key, rids, gen + i, LANE_DRAFT)
            d = jax.vmap(jax.random.categorical)(
                keys, jnp.where(q > 0, jnp.log(q), -1e30)).astype(jnp.int32)
            return (dcache, d), (d, q)

        (draft_cache, dlast), ys = jax.lax.scan(
            dbody, (draft_cache, last_tok), iota_g)
        if greedy:
            drafts, qstack = jnp.moveaxis(ys, 0, 1), None
        else:
            drafts = jnp.moveaxis(ys[0], 0, 1)        # (B, gamma)
            qstack = jnp.moveaxis(ys[1], 0, 1)        # (B, gamma, V)
        # one extra draft step caches the final proposal's KV so the draft
        # context stays complete when every proposal is accepted
        _, draft_cache = tf.forward_with_cache(
            draft_params, draft_cfg, dlast[:, None], draft_cache,
            jnp.where(active, pos + gamma, cache_len), read_len=read_len)

        seg = jnp.concatenate([last_tok[:, None], drafts], axis=1)
        vlogits, cache = tf.forward_with_cache(
            params, cfg, seg, cache, wpos, read_len=read_len)
        packed, last2, done2, pos2, gen2 = accept_round(
            vlogits, drafts, qstack, active, pos, gen, quota,
            last_tok, done, rids, base_key)
        return packed, cache, draft_cache, last2, done2, pos2, gen2

    fn = jax.jit(
        run,
        in_shardings=(param_shardings, draft_param_shardings, cache_sh,
                      draft_cache_sh, row_sh, row_sh, row_sh, row_sh,
                      row_sh, row_sh, row_sh, None),
        out_shardings=(row_sh, cache_sh, draft_cache_sh, row_sh, row_sh,
                       row_sh, row_sh),
        donate_argnums=(2, 3, 4, 5, 6, 7) if donate else (),
    )
    return fn, cache_sh, row_sh


def compile_spec_row_update_fn(mesh, cfg, batch_size: int, donate: bool = True):
    """:func:`compile_row_update_fn` for the speculative tick's WIDER
    device-threaded state: ``pos``/``gen`` ride the tick chain too (a row
    advances by its own accepted count, which only the device knows at
    dispatch time), so admission must splice them in the same
    enqueue-only way. Returns ``set_row(last_tok, done, pos, gen, slot,
    tok, flag, p, g) -> (last_tok, done, pos, gen)``."""
    row_sh, _, _ = _tick_shardings(mesh, cfg, batch_size)

    def set_row(last_tok, done, pos, gen, slot, tok, flag, p, g):
        return (last_tok.at[slot].set(tok), done.at[slot].set(flag),
                pos.at[slot].set(p), gen.at[slot].set(g))

    return jax.jit(
        set_row,
        in_shardings=(row_sh, row_sh, row_sh, row_sh, None, None, None,
                      None, None),
        out_shardings=(row_sh, row_sh, row_sh, row_sh),
        donate_argnums=(0, 1, 2, 3) if donate else (),
    )


def _filtered_probs(logits, temperature: float, top_k: int, top_p: float):
    """Normalized sampling distribution after the same temperature/top-k/
    top-p filtering select_token applies (shared _filter_logits) — the q/p
    distributions of the speculative acceptance test must match what plain
    sampling would use."""
    return jax.nn.softmax(_filter_logits(logits, temperature, top_k, top_p), axis=-1)


def _sample_rows(probs, host_rng):
    """One categorical draw per row of a (B, V) numpy prob matrix —
    vectorized inverse-CDF (no per-row Python loop)."""
    import numpy as np

    u = host_rng.random((probs.shape[0], 1))
    cum = np.cumsum(probs, axis=-1)
    cum /= cum[:, -1:]
    idx = (cum <= u).sum(axis=-1).astype(np.int32)
    return np.minimum(idx, probs.shape[1] - 1)


def _accept_round(drafts, active, lens, max_new_tokens, eos_token_id,
                  tgt=None, pdists=None, qstack=None, host_rng=None):
    """One vectorized speculative accept/correct round (VERDICT r2 weak #6:
    O(1) host work per round — every quantity below is a whole-batch numpy
    expression, no per-row Python).

    Inputs: drafts (B, gamma); active (B,) rows still generating; lens (B,)
    tokens emitted so far. Greedy mode passes ``tgt`` (B, gamma+1) argmax
    tokens; sampling mode passes ``pdists`` (B, gamma+1, V) target dists,
    ``qstack`` (B, gamma, V) draft dists, and the host rng.

    Returns (n_take, bonus, bonus_ok, took_eos):
      n_take   (B,) accepted draft tokens to append this round (0 for
               inactive rows; quota- and eos-truncated),
      bonus    (B,) the correction/extra token per row,
      bonus_ok (B,) whether the bonus token is appended,
      took_eos (B,) whether an accepted draft token was eos (row finishes).
    """
    import numpy as np

    B, gamma = drafts.shape
    greedy = tgt is not None
    if greedy:
        match = drafts == tgt[:, :gamma]
    else:
        p_at = np.take_along_axis(pdists[:, :gamma], drafts[..., None], axis=2)[..., 0]
        q_at = np.take_along_axis(qstack, drafts[..., None], axis=2)[..., 0]
        u = host_rng.random((B, gamma))
        match = u < np.minimum(1.0, p_at / np.maximum(q_at, 1e-20))
    n_acc = np.where(match.all(axis=1), gamma, (~match).argmax(axis=1)).astype(np.int32)

    rem = np.maximum(max_new_tokens - lens, 0)
    n_take = np.minimum(n_acc, rem)
    if eos_token_id is not None:
        iota = np.arange(gamma, dtype=np.int32)[None]
        eos_mask = (drafts == eos_token_id) & (iota < n_take[:, None])
        took_eos = eos_mask.any(axis=1)
        first_eos = np.where(took_eos, eos_mask.argmax(axis=1), gamma)
        n_take = np.minimum(n_take, first_eos + 1).astype(np.int32)
    else:
        took_eos = np.zeros(B, bool)
    took_eos = took_eos & active
    n_take = np.where(active, n_take, 0).astype(np.int32)

    # bonus: the target's correction at the rejection point (n_take < gamma)
    # or an extra draw past a fully-accepted block (n_take == gamma) —
    # appended only for rows not finished by quota or an accepted eos
    bonus_ok = active & ~took_eos & (n_take == n_acc) & (lens + n_take < max_new_tokens)
    if greedy:
        bonus = np.take_along_axis(tgt, n_take[:, None], axis=1)[:, 0].astype(np.int32)
    else:
        p_b = np.take_along_axis(pdists, n_take[:, None, None], axis=1)[:, 0]  # (B, V)
        q_b = np.take_along_axis(
            qstack, np.minimum(n_take, gamma - 1)[:, None, None], axis=1
        )[:, 0]
        residual = np.maximum(p_b - q_b, 0.0)
        dist = np.where((n_take < gamma)[:, None], residual, p_b)
        tot = dist.sum(axis=1, keepdims=True)
        dist = np.where(tot > 0, dist / np.where(tot > 0, tot, 1.0), p_b)
        bonus = _sample_rows(dist, host_rng)
    return n_take, bonus, bonus_ok, took_eos


def speculative_decode_loop(
    t_prefill, t_segment, d_prefill, d_decode,
    params_t, params_d, tokens, cache_t, cache_d,
    max_new_tokens: int, gamma: int, temperature: float, top_k: int,
    top_p: float, rng, eos_token_id: Optional[int] = None,
) -> jnp.ndarray:
    """Draft-model speculative decoding (lossless).

    Each round: the draft proposes ``gamma`` tokens autoregressively, the
    target verifies all of them in ONE (gamma+1)-wide segment forward, and
    the standard accept/resample rule keeps the output distribution exactly
    the target's (greedy mode: token-for-token identical to plain greedy
    decode). Rows advance by their own accepted counts — the per-row
    position generalization in models/transformer.forward_with_cache.

    The reference has no counterpart (v0.9.1 predates spec-decode serving);
    this is a capability the TPU design gets nearly for free from static
    segment shapes. t_segment/d_decode take (B,) position vectors.
    """
    import numpy as np

    if max_new_tokens <= 0:
        return tokens
    B, S = tokens.shape
    greedy = temperature <= 0.0
    host_rng = np.random.default_rng(int(jax.random.randint(rng, (), 0, 2**31 - 1)))

    logits_t, cache_t = t_prefill(params_t, tokens, cache_t)
    _, cache_d = d_prefill(params_d, tokens, cache_d)
    last_logits = logits_t[:, -1]
    if greedy:
        t0 = np.asarray(jnp.argmax(last_logits, axis=-1), np.int32)
    else:
        t0 = _sample_rows(np.asarray(_filtered_probs(last_logits, temperature, top_k, top_p)), host_rng)

    # fixed-width output buffer + per-row lengths (vectorized bookkeeping;
    # rows that finish early are padded with eos below)
    pad = eos_token_id if eos_token_id is not None else 0
    out = np.full((B, max_new_tokens), pad, np.int32)
    out[:, 0] = t0
    lens = np.ones((B,), np.int32)
    last = t0.astype(np.int32)
    pos = np.full((B,), S, np.int32)
    done = (lens >= max_new_tokens) | (
        (t0 == eos_token_id) if eos_token_id is not None else np.zeros(B, bool)
    )

    while not done.all():
        # --- draft gamma proposals; one extra step caches d_gamma's kv so
        # the draft context stays complete when every proposal is accepted
        drafts = np.zeros((B, gamma), np.int32)
        qdists = []
        cur = last
        for i in range(gamma + 1):
            logits_d, cache_d = d_decode(
                params_d, jnp.asarray(cur[:, None]), cache_d, jnp.asarray(pos + i)
            )
            if i == gamma:
                break
            if greedy:
                d = np.asarray(jnp.argmax(logits_d[:, 0], axis=-1), np.int32)
            else:
                q = np.asarray(_filtered_probs(logits_d[:, 0], temperature, top_k, top_p))
                qdists.append(q)
                d = _sample_rows(q, host_rng)
            drafts[:, i] = d
            cur = d.astype(np.int32)

        # --- verify all gamma proposals in one target forward
        seg = np.concatenate([last[:, None], drafts], axis=1)  # (B, gamma+1)
        logits_v, cache_t = t_segment(params_t, jnp.asarray(seg), cache_t, jnp.asarray(pos))
        tgt = pdists = qstack = None
        if greedy:
            tgt = np.asarray(jnp.argmax(logits_v, axis=-1), np.int32)  # (B, gamma+1)
        else:
            V = logits_v.shape[-1]
            pdists = np.asarray(
                _filtered_probs(logits_v.reshape(B * (gamma + 1), V), temperature, top_k, top_p)
            ).reshape(B, gamma + 1, V)
            qstack = np.stack(qdists, axis=1)  # (B, gamma, V)

        # --- whole-batch accept / correct (no per-row Python)
        active = ~done
        n_take, bonus, bonus_ok, took_eos = _accept_round(
            drafts, active, lens, max_new_tokens, eos_token_id,
            tgt=tgt, pdists=pdists, qstack=qstack, host_rng=host_rng,
        )
        cols = lens[:, None] + np.arange(gamma, dtype=np.int32)[None]
        valid = (np.arange(gamma)[None] < n_take[:, None]) & (cols < max_new_tokens)
        br, bi = np.nonzero(valid)
        out[br, cols[br, bi]] = drafts[br, bi]
        lens = lens + n_take
        bb = np.nonzero(bonus_ok)[0]
        out[bb, lens[bb]] = bonus[bb]
        lens = lens + bonus_ok.astype(np.int32)
        last = np.where(bonus_ok, bonus, last).astype(np.int32)
        pos = pos + np.where(active, n_take + 1, 0).astype(np.int32)
        done = done | took_eos | (lens >= max_new_tokens)
        if eos_token_id is not None:
            done = done | (bonus_ok & (bonus == eos_token_id))

    # rows that stopped at eos are already eos-padded past their length
    # (the caller's eos truncation overwrites everything past the first
    # eos with eos anyway, so plain-decode parity is preserved)
    return jnp.concatenate([tokens, jnp.asarray(out)], axis=1)


def fused_generate_fn(holder, mesh, cfg, param_shardings, batch_size: int,
                      cache_len: int, max_new_tokens: int, temperature: float,
                      top_k: int, top_p: float, read_floor: Optional[int] = None):
    """(generate_fn, cache_sharding) for the fused whole-generation program,
    memoized on ``holder`` and keyed by every trace-shaping argument — ONE
    wiring shared by the InferenceEngine and the RLHF hybrid engine so the
    cache key and builder can never drift apart. ``read_floor`` (tight-read
    bucket floor, None = full-length reads) shapes the traced program, so
    it is part of the key."""
    return cached_fn(
        holder, "fused_generate",
        (batch_size, cache_len, max_new_tokens, temperature, top_k, top_p,
         read_floor),
        lambda: compile_generate_fn(mesh, cfg, param_shardings, batch_size,
                                    cache_len, max_new_tokens, temperature,
                                    top_k, top_p, read_floor=read_floor)[:2],
    )


def cached_fn(holder, kind: str, key, builder, slots: int = 4):
    """Bounded per-family memoization of compiled functions on ``holder``
    (InferenceEngine and TpuHybridEngine share this; a long-running server
    alternating shapes must not retain unbounded compiled programs).

    Hit/miss accounting rides along for telemetry: ``holder`` grows
    ``_compile_hits``/``_compile_misses`` ints (request events diff the
    miss count to tag compile-triggering requests), and a holder carrying
    an enabled ``telemetry`` hub gets per-family labeled counters. A miss
    additionally arms the compile flight recorder (telemetry/
    compile_log.py) on the fresh entry: its first dispatch — the one that
    pays tracing + XLA compile — emits a ``compile_event`` keyed
    (family=``kind``, shapes key), flagged ``recompile`` when this hub
    compiled the same key before (LRU eviction churn made visible)."""
    cache = getattr(holder, "_fn_cache", None)
    if cache is None:
        cache = holder._fn_cache = {}
    family = cache.setdefault(kind, {})
    miss = key not in family
    tele = getattr(holder, "telemetry", None)
    if miss:
        if len(family) >= slots:
            family.pop(next(iter(family)))  # evict least-recently-used
        from deepspeed_tpu.telemetry.compile_log import wrap_compiled

        family[key] = wrap_compiled(tele, kind, key, builder())
    else:
        family[key] = family.pop(key)  # refresh recency (LRU, not FIFO)
    attr = "_compile_misses" if miss else "_compile_hits"
    setattr(holder, attr, getattr(holder, attr, 0) + 1)
    if tele is not None and tele.enabled:
        tele.registry.counter(
            "compile_cache", {"kind": kind, "outcome": "miss" if miss else "hit"}
        ).inc()
    return family[key]


def speculative_generate(cfg, params, draft, tokens, max_new_tokens: int,
                         temperature: float, top_k: int, top_p: float, rng,
                         gamma: int, max_out_tokens: Optional[int], get_fns,
                         eos_token_id: Optional[int] = None) -> jnp.ndarray:
    """Shared speculative-decoding orchestration (cache sizing with the
    verify-round overrun slack, fn lookup, cache init, loop) for BOTH the
    InferenceEngine and the RLHF hybrid engine. ``get_fns(B, cache_len) ->
    (t_prefill, t_segment, cache_sh)`` supplies the target programs;
    ``draft`` is an InferenceEngine providing its own via _spec_fns."""
    from deepspeed_tpu.models import transformer as tf

    if draft.cfg.vocab_size != cfg.vocab_size:
        raise ValueError(
            f"draft must share the vocabulary: draft vocab "
            f"{draft.cfg.vocab_size} != target vocab {cfg.vocab_size}")
    if gamma < 1:
        raise ValueError(f"speculative.num_draft_tokens must be >= 1, got {gamma}")
    B, S = tokens.shape
    total = S + max_new_tokens + gamma + 1  # verify-round overrun slack
    cache_len = bounded_cache_len(total, max(cfg.max_seq_len, total), max_out_tokens)
    t_prefill, t_segment, cache_sh = get_fns(B, cache_len)
    d_prefill, d_decode, d_cache_sh = draft._spec_fns(B, cache_len)
    cache_t = jax.device_put(tf.init_cache(cfg, B, cache_len), cache_sh)
    cache_d = jax.device_put(tf.init_cache(draft.cfg, B, cache_len), d_cache_sh)
    return speculative_decode_loop(
        t_prefill, t_segment, d_prefill, d_decode,
        params, draft.params, tokens, cache_t, cache_d,
        max_new_tokens, gamma, temperature, top_k, top_p, rng,
        eos_token_id=eos_token_id,
    )


def bounded_cache_len(total: int, max_seq_len: int, max_out_tokens: Optional[int]) -> int:
    """KV-cache allocation: bounded by max_out_tokens, grown when the request
    needs more, never past max_seq_len."""
    if not max_out_tokens:
        return max_seq_len
    return max(total, min(max_seq_len, max_out_tokens))
