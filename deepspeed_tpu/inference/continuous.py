"""Continuous (in-flight) batching for the inference engine.

Modern serving capability BEYOND the v0.9.1 reference (its inference
engine generates one static batch at a time; continuous batching arrived
in later serving stacks): a fixed pool of ``max_slots`` sequence slots
shares one KV cache, new requests are admitted into free slots while
other slots keep decoding, and finished sequences free their slot
immediately — no head-of-line blocking on the longest sequence.

TPU-shaped design: everything is static-shape. The decode tick is the
existing per-row-position segment program (inference/decoding.py
``compile_segment_fn`` — one jit, any slot occupancy); admission runs a
B=1 ragged prefill into a small bucket-length cache and a compiled
``dynamic_update_slice`` splices that row into the shared cache. Slot
reuse needs no cache clearing: admission overwrites [0..len) and the
causal position mask hides anything staler.

    eng = ContinuousBatchingEngine(model, config={"dtype": "bfloat16"},
                                   max_slots=8)
    rid = eng.submit([12, 7, 99], max_new_tokens=32)
    while eng.has_work():
        eng.step()            # one decode tick for every active slot
    out = eng.result(rid)     # prompt + generated tokens (np.int32)
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.decoding import (
    cached_fn,
    compile_ragged_prefill_fn,
    compile_segment_fn,
    select_token,
)


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # (len,) int32 — full prompt incl. any shared prefix
    max_new_tokens: int
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    done: bool = False
    # snapshot of the registered prefix entry (tokens/cache/bucket), taken
    # at submit time so unregister_prefix cannot strand a queued request
    prefix: Optional[dict] = None


def _bucket(n: int, cap: int, floor: int = 16) -> int:
    b = floor
    while b < n:
        b *= 2
    return min(b, cap)


class ContinuousBatchingEngine:
    """Slot-pool serving loop over the shared-cache decode program."""

    def __init__(self, model, config=None, params=None, mesh=None,
                 max_slots: int = 4, cache_len: Optional[int] = None,
                 eos_token_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0):
        from deepspeed_tpu.inference.engine import InferenceEngine

        self._eng = InferenceEngine(model, config=config, params=params,
                                    mesh=mesh, seed=seed)
        self.cfg = self._eng.cfg
        self.mesh = self._eng.mesh
        self.max_slots = max_slots
        self.cache_len = min(cache_len or self.cfg.max_seq_len, self.cfg.max_seq_len)
        self.eos_token_id = eos_token_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self._rng = jax.random.PRNGKey(seed)

        from deepspeed_tpu.models import transformer as tf

        shardings = self._eng.param_shardings
        self._segment_fn, cache_sh, _ = compile_segment_fn(
            self.mesh, self.cfg, shardings, max_slots, self.cache_len
        )
        self.cache = jax.device_put(
            tf.init_cache(self.cfg, max_slots, self.cache_len), cache_sh
        )
        self._cache_sh = cache_sh

        self._next_rid = 0
        self._next_pid = 0
        self._prefixes: Dict[int, dict] = {}  # prefix caching (register_prefix)
        self._pending: List[_Request] = []
        self._active: Dict[int, _Request] = {}      # slot -> request
        self._results: Dict[int, np.ndarray] = {}
        # per-slot decode state (host side)
        self._pos = np.zeros(max_slots, np.int32)       # next write position
        self._last_tok = np.zeros(max_slots, np.int32)  # last emitted token

    # -- public API -----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        assert prompt.size > 0, "empty prompt"
        assert max_new_tokens >= 1, "max_new_tokens must be >= 1 (admission emits a token)"
        assert prompt.size + max_new_tokens <= self.cache_len, (
            f"prompt {prompt.size} + max_new_tokens {max_new_tokens} exceeds "
            f"cache_len {self.cache_len}"
        )
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append(_Request(rid, prompt, max_new_tokens))
        return rid

    def register_prefix(self, prefix_ids) -> int:
        """Prefix (prompt) caching: prefill a shared prefix ONCE and reuse
        its KV for every request submitted with ``prefix_id`` — the
        system-prompt pattern, where admission then only pays prefill for
        the per-request suffix. Returns a prefix id for submit_with_prefix.
        """
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        assert prefix.size > 0, "empty prefix"
        assert prefix.size < self.cache_len, "prefix does not fit the cache"
        from deepspeed_tpu.models import transformer as tf

        n = prefix.size
        bucket = _bucket(n, self.cache_len)
        prefill_fn, _ = self._fns_for_bucket(bucket)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prefix
        positions = np.full((1, bucket), bucket, np.int32)
        positions[0, :n] = np.arange(n, dtype=np.int32)
        small = tf.init_cache(self.cfg, 1, bucket)
        logits, small = prefill_fn(
            self._eng.params, jnp.asarray(toks), jnp.asarray(positions), small
        )
        pid = self._next_pid  # counter, not len(): eviction must never recycle a live id
        self._next_pid += 1
        # keep the bucket cache on device; admission splices then prefills
        # only the suffix at positions [n..)
        self._prefixes[pid] = {"tokens": prefix, "cache": small, "bucket": bucket}
        return pid

    def _require_prefix(self, prefix_id: int) -> dict:
        try:
            return self._prefixes[prefix_id]
        except KeyError:
            raise KeyError(
                f"unknown prefix id {prefix_id}: never registered or already "
                f"unregistered (live ids: {sorted(self._prefixes)})") from None

    def unregister_prefix(self, prefix_id: int):
        """Release a registered prefix's device-resident KV (a long-running
        server must bound the pinned caches; in-flight requests that
        already spliced it are unaffected)."""
        self._require_prefix(prefix_id)
        self._prefixes.pop(prefix_id)

    def submit_with_prefix(self, prefix_id: int, suffix_ids, max_new_tokens: int = 32) -> int:
        """Queue a request whose prompt is (registered prefix + suffix);
        the prefix KV is reused, only the suffix is prefilled."""
        suffix = np.asarray(suffix_ids, np.int32).reshape(-1)
        assert suffix.size > 0, "empty suffix (use submit for prefix-only prompts)"
        assert max_new_tokens >= 1, "max_new_tokens must be >= 1 (admission emits a token)"
        pre = self._require_prefix(prefix_id)
        total = pre["tokens"].size + suffix.size
        assert total + max_new_tokens <= self.cache_len, (
            f"prefix {pre['tokens'].size} + suffix {suffix.size} + "
            f"max_new_tokens {max_new_tokens} exceeds cache_len {self.cache_len}"
        )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, np.concatenate([pre["tokens"], suffix]), max_new_tokens)
        req.prefix = pre  # snapshot: queued requests survive unregister_prefix
        self._pending.append(req)
        return rid

    def has_work(self) -> bool:
        return bool(self._pending or self._active)

    def result(self, rid: int) -> np.ndarray:
        return self._results.pop(rid)

    def finished(self) -> Dict[int, np.ndarray]:
        out, self._results = self._results, {}
        return out

    def step(self) -> Dict[int, List[int]]:
        """One scheduler tick: admit pending into free slots, then one
        decode step for every active slot. Returns {rid: [tokens]} emitted
        this tick — a just-admitted request emits TWO tokens (its prefill
        token and the same-tick decode token), so the values are lists;
        concatenating them across ticks reproduces the generated stream
        exactly. Finished requests move to ``finished()``/``result()``."""
        emitted: Dict[int, List[int]] = {}
        free = [s for s in range(self.max_slots) if s not in self._active]
        while self._pending and free:
            slot = free.pop(0)
            req = self._pending.pop(0)
            emitted[req.rid] = [self._admit(req, slot)]
        if not self._active:
            return emitted

        toks = jnp.asarray(self._last_tok[:, None])
        pos = jnp.asarray(self._pos)
        self._rng, sub = jax.random.split(self._rng)
        logits, self.cache = self._segment_fn(self._eng.params, toks, self.cache, pos)
        nxt = np.asarray(select_token(
            logits[:, 0], self.temperature, self.top_k, sub, self.top_p
        ))
        for slot, req in list(self._active.items()):
            tok = int(nxt[slot])
            self._record(req, slot, tok)
            emitted.setdefault(req.rid, []).append(tok)
        self._pos[[s for s in self._active]] += 1
        for slot in [s for s, r in self._active.items() if r.done]:
            self._finish(slot)
        return emitted

    # -- internals ------------------------------------------------------
    def _fns_for_bucket(self, bucket: int):
        def build():
            prefill_fn, small_sh, _ = compile_ragged_prefill_fn(
                self.mesh, self.cfg, self._eng.param_shardings, 1, bucket
            )

            def insert(big, small, slot):
                # splice the B=1 bucket cache into the shared cache row:
                # positions [0..bucket) overwritten, staler junk beyond is
                # causally masked until real writes reach it (tree.map:
                # also covers the int8 {"q8","s"} representation)
                return jax.tree.map(
                    lambda b, sm: jax.lax.dynamic_update_slice(
                        b, sm.astype(b.dtype), (0, slot, 0, 0, 0)
                    ),
                    big, small,
                )

            insert_fn = jax.jit(
                insert,
                in_shardings=(self._cache_sh, small_sh, None),
                out_shardings=self._cache_sh,
                donate_argnums=(0,),
            )
            return prefill_fn, insert_fn

        # shared bounded memoization (decoding.cached_fn); 8 slots cover
        # every power-of-2 bucket up to 16 <= b <= 2048 without thrash
        return cached_fn(self, "admit_bucket", bucket, build, slots=8)

    def _admit(self, req: _Request, slot: int) -> Optional[int]:
        from deepspeed_tpu.models import transformer as tf

        n = req.prompt.size
        if req.prefix is not None:
            pre = req.prefix
            n_pre = pre["tokens"].size
            # 1) splice the cached prefix KV into the slot row (the prefix
            #    bucket cache is NOT donated — it serves every request)
            _, insert_fn = self._fns_for_bucket(pre["bucket"])
            self.cache = insert_fn(self.cache, pre["cache"], slot)
            # 2) prefill ONLY the suffix through the shared segment program:
            #    other rows' positions park at cache_len so their KV writes
            #    drop; suffix pad columns land at future positions of THIS
            #    row, each overwritten by a real decode write before it is
            #    ever attended (same argument as slot reuse)
            suffix = req.prompt[n_pre:]
            sb = _bucket(suffix.size, self.cache_len)
            toks = np.zeros((self.max_slots, sb), np.int32)
            toks[slot, :suffix.size] = suffix
            pos = np.full(self.max_slots, self.cache_len, np.int32)
            pos[slot] = n_pre
            logits, self.cache = self._segment_fn(
                self._eng.params, jnp.asarray(toks), self.cache, jnp.asarray(pos)
            )
            last_logits = logits[slot: slot + 1, suffix.size - 1]
        else:
            bucket = _bucket(n, self.cache_len)
            prefill_fn, insert_fn = self._fns_for_bucket(bucket)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = req.prompt
            # pads park at bucket (dropped writes), real tokens pack 0..n-1
            positions = np.full((1, bucket), bucket, np.int32)
            positions[0, :n] = np.arange(n, dtype=np.int32)
            small = tf.init_cache(self.cfg, 1, bucket)
            logits, small = prefill_fn(
                self._eng.params, jnp.asarray(toks), jnp.asarray(positions), small
            )
            self.cache = insert_fn(self.cache, small, slot)
            last_logits = logits[:, n - 1]
        self._rng, sub = jax.random.split(self._rng)
        first = int(np.asarray(select_token(
            last_logits, self.temperature, self.top_k, sub, self.top_p
        ))[0])
        self._active[slot] = req
        req.slot = slot
        # the first generated token's KV is written at position n by the
        # NEXT decode tick (it feeds last_tok at pos, then pos advances) —
        # same protocol as ragged_decode_loop
        self._pos[slot] = n
        self._record(req, slot, first)
        if req.done:
            self._finish(slot)
        return first

    def _record(self, req: _Request, slot: int, tok: int):
        req.generated.append(tok)
        self._last_tok[slot] = tok
        hit_eos = self.eos_token_id is not None and tok == self.eos_token_id
        total = req.prompt.size + len(req.generated)
        if hit_eos or len(req.generated) >= req.max_new_tokens or total >= self.cache_len:
            req.done = True

    def _finish(self, slot: int):
        req = self._active.pop(slot)
        self._results[req.rid] = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]
        )
