"""Continuous (in-flight) batching for the inference engine.

Modern serving capability BEYOND the v0.9.1 reference (its inference
engine generates one static batch at a time; continuous batching arrived
in later serving stacks): a fixed pool of sequence slots shares KV cache,
new requests are admitted into free slots while other slots keep decoding,
and finished sequences free their slot immediately — no head-of-line
blocking on the longest sequence.

TPU-shaped design: everything is static-shape, and — since PERF.md's
central finding is that host-blocked dispatch, not FLOPs, governs decode
throughput — the scheduler tick is built so device compute and host
scheduling OVERLAP instead of alternating:

- **On-device acceptance** (decoding.compile_pool_tick_fn): sampling,
  EOS/quota done detection, position advance, and per-row emission
  masking run inside the compiled tick program. Each tick returns one
  small packed ``(tokens, n_emitted, done)`` int32 buffer, fetched with a
  single coalesced device get — never per-row logits or host-side
  truncation.
- **Dispatch-ahead pipelining** (``pipeline_depth``, default 1): the
  tick program THREADS its decode state (``last_tok``/``done`` and the
  donated KV cache) through device outputs, so tick N+1 is dispatched on
  tick N's output futures BEFORE the host blocks on tick N's packed
  result. While the host parses results, admits requests, and runs the
  serving layer's scheduling, the device is already executing the next
  tick. ``pipeline_depth=0`` is the fully synchronous scheduler; token
  streams are bitwise identical in both modes (per-request rng — see
  decoding.request_keys — makes streams independent of slot/tick
  placement). The visible difference is only WHEN a token is returned:
  ``step()`` reports the results of the tick(s) it retired, which lag
  dispatch by up to ``pipeline_depth`` ticks.
- **Prefill/decode fusion** (``fused_prefill``, default on for
  single-token ticks): admission no longer dispatches a blocking B=1
  ragged prefill + cache splice. Instead one admitting row's next prompt
  chunk (bucketed fixed shapes, ``prefill_chunk`` cap) rides INSIDE the
  same tick program that decodes the active rows — Dynamic-SplitFuse
  style, one more static-shape program per (chunk bucket, read bucket)
  family — so decode ticks proceed during a long prompt's prefill. With
  fusion off (or burst ticks), admission prefills ``prompt[:-1]`` through
  the B=1 bucket program + splice WITHOUT sampling or fetching: the last
  prompt token is re-fed by the first decode tick, whose logits yield the
  first generated token, keeping every admission dispatch-only.
- **Donation**: the pool KV cache and the threaded tick state are
  ``donate_argnums`` operands of every tick program, so per-tick cache
  copies disappear from HBM traffic.

Bucketed KV (VERDICT r4 #9): ``cache_buckets=[(slots, len), ...]``
partitions the slots into pools with different cache lengths; admission
places each request in the smallest-length pool it fits, falling back to
longer pools when full — the static-shape TPU analogue of paged KV.
``kv_cache_bytes()`` reports the footprint for both layouts.

    eng = ContinuousBatchingEngine(model, config={"dtype": "bfloat16"},
                                   cache_buckets=[(6, 256), (2, 2048)])
    rid = eng.submit([12, 7, 99], max_new_tokens=32)
    while eng.has_work():
        eng.step()            # dispatch tick N+1, retire tick N
    out = eng.result(rid)     # prompt + generated tokens (np.int32)

``tokens_per_tick=k`` fuses k decode steps per tick into one compiled
scan (k× fewer host dispatches per token); admission then happens between
bursts. Tokens a burst computes past a row's done flag are wasted work,
counted by the ``burst_wasted_tokens`` telemetry counter.

Tight-read ticks (engine config ``kv_tight_read``, default on): every
tick attends a bucketed ACTIVE length (docs/inference.md "Cache
geometry"). Finished requests emit an ``inference_request`` event with
``kv_bytes_read`` / ``kv_bytes_per_token`` / ``kv_dtype`` /
``cache_utilization``; each ``step()`` additionally records
``tick_dispatch_ms`` / ``tick_block_ms`` / in-flight depth (histograms,
gauge, and a per-step ``serving_tick`` trace event) so the
overlap win is measurable from traces alone — ``tick_stats()`` exposes
the same accounting in-process.
"""

import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference import ngram
from deepspeed_tpu.inference.decoding import (
    cached_fn,
    compile_pool_tick_fn,
    compile_ragged_prefill_fn,
    compile_row_update_fn,
    compile_segment_fn,
    compile_spec_pool_tick_fn,
    compile_spec_row_update_fn,
    read_bucket,
)

# admission/bucket sizing shares the ONE bucketing rule with the tight-read
# geometry (decoding.read_bucket); the old local name stays importable
_bucket = read_bucket

# smallest fused-prefill chunk program width (power-of-2 buckets up to the
# pool's chunk cap bound the static-shape program family)
_CHUNK_FLOOR = 16


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # (len,) int32 — full prompt incl. any shared prefix
    max_new_tokens: int
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    pool: Optional[int] = None
    done: bool = False
    # snapshot of the registered prefix entry (tokens/cache/bucket), taken
    # at submit time so unregister_prefix cannot strand a queued request
    prefix: Optional[dict] = None
    # device-side emission quota (gen_base + max_new_tokens; placement
    # guarantees the pool row holds prompt + max_new_tokens)
    quota: int = 0
    # recovery resume: the device ``gen`` counter starts here instead of
    # 0, so the per-token RNG keys fold_in(fold_in(base, rid), gen)
    # continue the original stream — a request re-admitted after engine
    # loss with prompt = original + emitted and gen_base = len(emitted)
    # draws its next token with the exact key the lost engine would have
    gen_base: int = 0
    # fused prefill: remaining (tokens, pos0, n_real, emits) prompt chunks
    # still to ride a tick; None/empty = decode-active
    chunks: Optional[List[tuple]] = None
    # KV-cache bytes this request's row streamed across its decode ticks
    # (host accounting at the read length each retired tick dispatched)
    kv_bytes_read: int = 0
    # speculative accounting (spec ticks only): drafts proposed for this
    # request vs drafts its verify rounds accepted
    spec_drafted: int = 0
    spec_accepted: int = 0
    # tick-window span accumulation (span_hook): consecutive retired
    # ticks of one kind coalesce into one window span, flushed on kind
    # change / span_window_ticks / finish — host bookkeeping only, the
    # times come from clocks the tick loop already reads
    win_kind: Optional[str] = None
    win_t0: float = 0.0
    win_t1: float = 0.0
    win_ticks: int = 0
    win_tokens: int = 0
    win_drafted: int = 0
    win_accepted: int = 0


class _TickRecord:
    """Host bookkeeping for one DISPATCHED (possibly in-flight) pool tick:
    the packed result future plus everything needed to attribute it when
    the tick is retired."""

    __slots__ = ("packed", "live", "k", "row_bytes", "fused", "spec", "t0")

    def __init__(self, packed, live, k, row_bytes, fused, spec=0):
        self.packed = packed          # device future: (B, k+2) int32
        self.live = live              # slot -> _Request live at dispatch
        self.k = k                    # burst length (1 for plain/fused)
        self.row_bytes = row_bytes    # KV bytes one row streams per step
        self.fused = fused            # carried a prefill chunk
        self.spec = spec              # speculative round: gamma (0 = plain;
        # packed is (B, gamma+4) and row_bytes is the WHOLE round's bytes)
        self.t0 = 0.0                 # dispatch time for window spans
        # (time.monotonic, set by _step_body only when a span_hook is
        # installed — zero otherwise, never read)


class _Pool:
    """One static-shape slot pool: ``n_slots`` rows of ``length`` KV."""

    def __init__(self, engine, n_slots: int, length: int):
        from deepspeed_tpu.models import transformer as tf

        self.n_slots = n_slots
        self.length = length
        # the hub is resolved at FIRST DISPATCH, not here: a serving
        # recovery factory builds replacement engines with telemetry off
        # and injects the shared hub afterwards — jit compiles lazily, so
        # the deferred wrap still journals the rebuild's compiles
        from deepspeed_tpu.telemetry.compile_log import wrap_deferred

        def get_tele(_engine=engine):
            return _engine._eng.telemetry

        self.segment_fn, self.cache_sh, _ = compile_segment_fn(
            engine.mesh, engine.cfg, engine._eng.param_shardings, n_slots, length
        )
        self.segment_fn = wrap_deferred(get_tele, self.segment_fn,
                                        "pool_segment", (n_slots, length))
        self.cache = jax.device_put(
            tf.init_cache(engine.cfg, n_slots, length), self.cache_sh
        )
        self.active: Dict[int, _Request] = {}       # slot -> request
        # device-THREADED tick state: the tick programs return these as
        # outputs that feed the next tick's inputs, so a tick can be
        # dispatched before the previous one's results are fetched. Free
        # slots start done=1 (never emit); admission flips a row live.
        # Placed REPLICATED over the mesh up front (the tick programs'
        # row-state sharding) so the first tick never pays a reshard.
        from jax.sharding import NamedSharding, PartitionSpec

        row_sh = NamedSharding(engine.mesh, PartitionSpec())
        self.last_tok_dev = jax.device_put(jnp.zeros(n_slots, jnp.int32), row_sh)
        self.done_dev = jax.device_put(jnp.ones(n_slots, jnp.int32), row_sh)
        self.set_row_fn = compile_row_update_fn(engine.mesh, engine.cfg,
                                                n_slots,
                                                donate=engine.donate_cache)
        self.set_row_fn = wrap_deferred(get_tele, self.set_row_fn,
                                        "row_update", (n_slots,))
        # speculative tick state (engine.spec_gamma > 0): pos/gen join the
        # device-THREADED arrays — a spec row advances by its own accepted
        # count, which only the device knows at dispatch time — and
        # draft-model mode keeps a second KV cache with the SAME bucket
        # geometry plus its own segment program for draft prefill
        self.draft_cache = None
        if engine.spec_gamma:
            self.pos_dev = jax.device_put(
                jnp.full(n_slots, length, jnp.int32), row_sh)
            self.gen_dev = jax.device_put(jnp.zeros(n_slots, jnp.int32),
                                          row_sh)
            self.spec_set_row_fn = compile_spec_row_update_fn(
                engine.mesh, engine.cfg, n_slots,
                donate=engine.donate_cache)
            self.spec_set_row_fn = wrap_deferred(
                get_tele, self.spec_set_row_fn, "spec_row_update",
                (n_slots,))
            if engine.spec_mode == "draft":
                deng = engine._draft_eng
                self.draft_segment_fn, self.draft_cache_sh, _ = \
                    compile_segment_fn(engine.mesh, engine.draft_cfg,
                                       deng.param_shardings, n_slots, length)
                self.draft_segment_fn = wrap_deferred(
                    get_tele, self.draft_segment_fn, "pool_segment",
                    (n_slots, length, "draft"))
                self.draft_cache = jax.device_put(
                    tf.init_cache(engine.draft_cfg, n_slots, length),
                    self.draft_cache_sh)
        # ds-audit capture of the pool's companion programs (the tick
        # variants notify from _tick_fn as they are built)
        from deepspeed_tpu.analysis.program import capture

        if capture.active():
            def row_args(n=n_slots):
                row = jax.ShapeDtypeStruct((n,), jnp.int32)
                return (row, row, 0, 0, 0)

            def seg_args(n=n_slots, pool=self, eng=engine):
                def sds(a):
                    return jax.ShapeDtypeStruct(a.shape, a.dtype)

                return (jax.tree.map(sds, eng._eng.params),
                        jax.ShapeDtypeStruct((n, 8), jnp.int32),
                        jax.tree.map(sds, pool.cache),
                        jax.ShapeDtypeStruct((n,), jnp.int32))

            capture.notify_program("pool_segment", "", self.segment_fn,
                                   seg_args, meta=engine._audit_meta)
            capture.notify_program("pool_row_update", "", self.set_row_fn,
                                   row_args, meta=engine._audit_meta)
            if engine.spec_gamma:
                def spec_row_args(n=n_slots):
                    row = jax.ShapeDtypeStruct((n,), jnp.int32)
                    return (row, row, row, row, 0, 0, 0, 0, 0)

                capture.notify_program("pool_spec_row_update", "",
                                       self.spec_set_row_fn, spec_row_args,
                                       meta=engine._audit_meta)
                if engine.spec_mode == "draft":
                    def dseg_args(n=n_slots, pool=self, eng=engine):
                        def sds(a):
                            return jax.ShapeDtypeStruct(a.shape, a.dtype)

                        return (jax.tree.map(sds, eng._draft_eng.params),
                                jax.ShapeDtypeStruct((n, 8), jnp.int32),
                                jax.tree.map(sds, pool.draft_cache),
                                jax.ShapeDtypeStruct((n,), jnp.int32))

                    capture.notify_program("pool_segment", "draft",
                                           self.draft_segment_fn, dseg_args,
                                           meta=engine._draft_audit_meta)
        # host DISPATCH mirrors: the position/emission count each row will
        # have reached once every dispatched tick retires. Exact for live
        # rows (a live row advances by exactly k per burst until done);
        # rows whose finish the host has not yet observed are excluded
        # from dispatch, so the mirrors never need reconciliation.
        self.disp_pos = np.zeros(n_slots, np.int32)
        self.disp_gen = np.zeros(n_slots, np.int32)
        # fused prefill: admitted requests whose prompt chunks still need
        # ticks, FIFO — one admitting row rides each tick
        self.prefill_q: "deque[_Request]" = deque()
        self.chunk_cap = min(engine.prefill_chunk, length)
        # tick programs keyed (chunk_width, read_len): shape/sampling are
        # fixed for the engine's lifetime, so they live on the pool —
        # bounded by the (chunk bucket × read bucket) family size, never
        # evicted (an LRU consulted per tick could recompile mid-serve)
        self.tick_fns: Dict[tuple, object] = {}

    def free_slots(self) -> List[int]:
        return [s for s in range(self.n_slots) if s not in self.active]

    def kv_bytes(self) -> int:
        return sum(l.nbytes for l in jax.tree.leaves(self.cache))


class ContinuousBatchingEngine:
    """Slot-pool serving loop over the compiled pool-tick programs."""

    def __init__(self, model, config=None, params=None, mesh=None,
                 max_slots: Optional[int] = None, cache_len: Optional[int] = None,
                 cache_buckets: Optional[List] = None,
                 eos_token_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 tokens_per_tick: int = 1, pipeline_depth: int = 1,
                 fused_prefill: bool = True,
                 prefill_chunk: Optional[int] = None,
                 donate_cache: bool = True,
                 fetch_timeout_s: Optional[float] = None,
                 draft_model=None, draft_params=None):
        from deepspeed_tpu.inference.engine import InferenceEngine

        self._eng = InferenceEngine(model, config=config, params=params,
                                    mesh=mesh, seed=seed)
        # slot caches are written at per-row depths (ragged admission), which
        # the rolling ring's aligned-path math does not cover — the slot
        # pools run plain full/bucket-length caches; bucketing already bounds
        # the footprint (see PERF.md bucketed-KV table)
        self.cfg = self._eng._ring_off_cfg
        self.mesh = self._eng.mesh
        self.eos_token_id = eos_token_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        assert tokens_per_tick >= 1, tokens_per_tick
        assert pipeline_depth >= 0, pipeline_depth
        self.tokens_per_tick = tokens_per_tick
        # dispatch-ahead pipelining: how many ticks may be in flight before
        # the host blocks on the oldest packed result. 0 = fully
        # synchronous (retire every tick before returning from step()).
        self.pipeline_depth = pipeline_depth
        # fused prefill requires single-token ticks: a burst program has no
        # chunk row (admission between bursts uses the separate path)
        self.fused_prefill = fused_prefill and tokens_per_tick == 1
        self.prefill_chunk = (prefill_chunk
                              or self._eng.config.prefill_chunk_size or 128)
        # donate the KV cache + threaded state through the tick programs
        # (no per-tick cache copy in HBM). The jax CPU backend implements
        # donation by blocking at dispatch — which serializes the tick
        # chain — so virtual-mesh overlap measurements pass False here
        # (ds_loadgen --no-donate); on TPU donation and async dispatch
        # compose and this stays on.
        self.donate_cache = donate_cache
        # ONE base key: every sampled token draws from
        # fold_in(fold_in(base, rid), token_index) on device, so streams
        # are identical across pipeline depths / fusion / slot placement
        self._base_key = jax.random.PRNGKey(seed)

        # speculative pooled ticks (config speculative.enabled + .pool):
        # every tick proposes spec_gamma tokens per active row and ONE
        # target forward verifies them (decoding.compile_spec_pool_tick_fn)
        spec = self._eng.config.speculative
        self.spec_gamma = 0
        self.spec_mode = None
        self._draft_eng = None
        self.draft_cfg = None
        if spec.enabled and spec.pool:
            if spec.mode not in ("draft", "ngram"):
                raise ValueError(
                    f"speculative.mode must be 'draft' or 'ngram', "
                    f"got {spec.mode!r}")
            if tokens_per_tick != 1:
                raise ValueError(
                    "speculative pool ticks require tokens_per_tick=1 "
                    "(the gamma-wide verify round IS the burst)")
            if spec.num_draft_tokens < 1:
                raise ValueError(
                    f"speculative.num_draft_tokens must be >= 1, "
                    f"got {spec.num_draft_tokens}")
            if spec.mode == "draft":
                if draft_model is None:
                    raise ValueError(
                        "speculative.mode='draft' needs draft_model= (a "
                        "smaller same-vocabulary model), or set "
                        "speculative.mode='ngram' for draft-free "
                        "self-drafting")
                # the draft shares the cache format (int8 KV must cover
                # both trees) and the mesh — its params are partitioned by
                # the same regex rules / annotations as the target's
                self._draft_eng = InferenceEngine(
                    draft_model,
                    config={"dtype": self._eng.config.dtype,
                            "kv_cache_dtype": self._eng.config.kv_cache_dtype,
                            "kv_tight_read": self._eng.config.kv_tight_read,
                            "kv_read_floor": self._eng.config.kv_read_floor,
                            "mesh": self._eng.config.mesh},
                    params=draft_params, mesh=self.mesh, seed=seed)
                self.draft_cfg = self._draft_eng._ring_off_cfg
                if self.draft_cfg.vocab_size != self.cfg.vocab_size:
                    raise ValueError(
                        f"draft must share the vocabulary: draft vocab "
                        f"{self.draft_cfg.vocab_size} != target vocab "
                        f"{self.cfg.vocab_size}")
            self.spec_gamma = spec.num_draft_tokens
            self.spec_mode = spec.mode
        elif draft_model is not None:
            raise ValueError(
                "draft_model= given but speculative pool ticks are off: "
                "set speculative={'enabled': True, 'pool': True} "
                "(mode='draft')")

        if cache_buckets is None:
            cache_len = min(cache_len or self.cfg.max_seq_len, self.cfg.max_seq_len)
            cache_buckets = [(max_slots if max_slots is not None else 4, cache_len)]
        else:
            assert cache_len is None, "pass cache_buckets OR cache_len, not both"
            assert max_slots is None, (
                "pass cache_buckets OR max_slots, not both (slot counts come "
                "from the buckets)")
            cache_buckets = sorted(
                ((int(s), int(l)) for s, l in cache_buckets), key=lambda sl: sl[1]
            )
            for s, l in cache_buckets:
                assert s >= 1 and 1 <= l <= self.cfg.max_seq_len, (s, l)
        # pools sorted by length: admission scans for the smallest fit
        self._pools = [_Pool(self, s, l) for s, l in cache_buckets]
        self.max_slots = sum(p.n_slots for p in self._pools)
        self.cache_len = max(p.length for p in self._pools)

        self._next_rid = 0
        self._next_pid = 0
        self._prefixes: Dict[int, dict] = {}  # prefix caching (register_prefix)
        self._pending: List[_Request] = []
        self._results: Dict[int, np.ndarray] = {}
        # dispatched-but-not-retired ticks, oldest first; each entry maps
        # pool index -> _TickRecord for one scheduler tick
        self._inflight: "deque[Dict[int, _TickRecord]]" = deque()
        # host-overhead accounting for the tick loop (tick_stats());
        # telemetry mirrors it into histograms/counters when enabled
        self._tick_stats = {"ticks": 0, "steps": 0, "dispatch_ms": 0.0,
                            "block_ms": 0.0, "tokens": 0, "wasted_tokens": 0,
                            "capacity_tokens": 0, "fused_prefill_ticks": 0,
                            "max_inflight": 0, "spec_drafted": 0,
                            "spec_accepted": 0}
        # cancelled rids, remembered so status()/result() answer precisely
        # instead of "unknown" — BOUNDED (oldest evicted past 4096): a
        # long-running server cancels routinely and must not leak an int
        # per cancellation for its lifetime. Evicted rids age back to
        # "unknown", which is also what collected results report.
        self._cancelled: "OrderedDict[int, None]" = OrderedDict()
        self._cancelled_cap = 4096
        # serving-layer enrichment point: called in _finish with
        # (rid, event dict) and may mutate/replace the event before it is
        # emitted (deepspeed_tpu/serving adds queue_ms/priority/deadline_met
        # and retags path:"serving"). None = emit the event as built.
        self.request_event_hook: Optional[Callable[[int, dict], Optional[dict]]] = None
        # request-scoped tracing (docs/telemetry.md "Request tracing"):
        # called with (rid, span_kind, t0, t1, attrs) when a coalesced
        # tick window retires — prefill_chunk / decode_window /
        # spec_verify_round, times in time.monotonic seconds. The serving
        # layer installs this ONLY when its hub is live, so the default
        # tick loop pays nothing (no clock reads, no window bookkeeping).
        # Windows coalesce up to span_window_ticks consecutive same-kind
        # ticks per request: span volume scales ~tokens/window, not
        # per-tick.
        self.span_hook: Optional[Callable[[int, str, float, float, dict], None]] = None
        self.span_window_ticks = 16
        # fault-injection hook (serving/faults.py FaultInjector): called
        # with (point, info) at "dispatch" (top of step, BEFORE any state
        # mutates), "retire" (before each packed-result fetch) and
        # "set_row" (admission row flip). The hook may raise — that IS the
        # injection; no monkeypatching. None = no injection.
        self.fault_hook: Optional[Callable[[str, dict], None]] = None
        # watchdog: a packed-result fetch in _retire exceeding this many
        # seconds raises TimeoutError (on TPU a preempted device surfaces
        # as a stuck/erroring fetch; detection is post-hoc — the fetch
        # itself cannot be interrupted from this thread). None = off.
        self.fetch_timeout_s = fetch_timeout_s
        # True once an exception escaped mid-tick: device-threaded state,
        # dispatch mirrors and in-flight results can no longer be trusted
        # to agree, so the serving layer must NOT retry step() — it
        # rebuilds instead (bitwise-safe: see docs/serving.md recovery)
        self.poisoned = False
        self._tick_index = 0  # step() calls attempted (fault-plan clock)
        # one memory_snapshot per engine generation: the live ops plane's
        # HBM attribution baseline (serving recovery emits the "rebuild"
        # one after re-injecting its hub into a replacement engine); the
        # enabled guard keeps telemetry-off builds from walking the trees
        if self._eng.telemetry.enabled:
            self.memory_snapshot("build")

    @property
    def telemetry(self):
        """The engine stack's ONE telemetry hub (owned by the inner
        InferenceEngine; serving recovery re-injects it into replacement
        engines so counters and the trace span generations)."""
        return self._eng.telemetry

    # -- single-pool compatibility surface (tests, introspection) --------
    @property
    def cache(self):
        assert len(self._pools) == 1, "cache is per-pool; use _pools[i].cache"
        return self._pools[0].cache

    @cache.setter
    def cache(self, value):
        assert len(self._pools) == 1
        self._pools[0].cache = value

    @property
    def _active(self) -> Dict[int, _Request]:
        """All active requests keyed by (pool-flattened) slot index."""
        out = {}
        base = 0
        for p in self._pools:
            for s, r in p.active.items():
                out[base + s] = r
            base += p.n_slots
        return out

    def kv_cache_bytes(self) -> int:
        """Total device bytes held by the slot-pool KV caches (the number
        the PERF.md bucketed-vs-fixed footprint table reports)."""
        return sum(p.kv_bytes() for p in self._pools)

    def hbm_components(self) -> Dict[str, int]:
        """PER-CHIP HBM attribution of everything this engine keeps
        resident: params, the slot-pool KV caches plus registered prefix
        caches (pinned KV), and the device-threaded tick state.
        Metadata-only byte math (telemetry/memory.py leaf shard shapes —
        a tensor-sharded cache counts 1/tp per chip), exact on the
        virtual mesh and TPU alike; never blocks or fetches."""
        from deepspeed_tpu.telemetry import memory as hbm

        kv = sum(hbm.tree_device_bytes(p.cache) for p in self._pools)
        kv += sum(hbm.tree_device_bytes(pre["cache"])
                  for pre in self._prefixes.values())
        tick = sum(hbm.tree_device_bytes((p.last_tok_dev, p.done_dev))
                   for p in self._pools)
        out = {"params": hbm.tree_device_bytes(self._eng.params),
               "kv_cache": kv, "tick_state": tick}
        if self.spec_gamma:
            out["tick_state"] += sum(
                hbm.tree_device_bytes((p.pos_dev, p.gen_dev))
                for p in self._pools)
            if self._draft_eng is not None:
                out["draft_params"] = hbm.tree_device_bytes(
                    self._draft_eng.params)
                out["kv_cache"] += sum(
                    hbm.tree_device_bytes(p.draft_cache)
                    for p in self._pools)
        return out

    def memory_snapshot(self, reason: str):
        """Export the current HBM attribution (``hbm_bytes{component}``
        gauges + one ``memory_snapshot`` trace event; docs/telemetry.md
        "Live ops plane"). No-op returning None with telemetry off."""
        from deepspeed_tpu.telemetry import memory as hbm

        return hbm.emit_snapshot(self._eng.telemetry, self.hbm_components(),
                                 reason)

    def _tick_arg_structs(self, pool: "_Pool", chunk: Optional[int]):
        """ShapeDtypeStruct argument tuple for one tick program — the
        ONE abstract-args builder shared by the AOT memory diagnostic
        and the ds-audit capture hook, so neither can drift from the
        real dispatch signature."""
        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        params_s = jax.tree.map(sds, self._eng.params)
        cache_s = jax.tree.map(sds, pool.cache)
        row = jax.ShapeDtypeStruct((pool.n_slots,), jnp.int32)
        args = [params_s, cache_s, row, row, row, row, row, row,
                sds(self._base_key)]
        if chunk is not None:
            cvec = jax.ShapeDtypeStruct((chunk,), jnp.int32)
            args += [cvec, cvec, 0, row, row]
        return tuple(args)

    def _spec_tick_arg_structs(self, pool: "_Pool"):
        """:meth:`_tick_arg_structs` for the speculative tick variants."""
        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        params_s = jax.tree.map(sds, self._eng.params)
        cache_s = jax.tree.map(sds, pool.cache)
        row = jax.ShapeDtypeStruct((pool.n_slots,), jnp.int32)
        key_s = sds(self._base_key)
        if self.spec_mode == "draft":
            return (params_s, jax.tree.map(sds, self._draft_eng.params),
                    cache_s, jax.tree.map(sds, pool.draft_cache),
                    row, row, row, row, row, row, row, key_s)
        drafts = jax.ShapeDtypeStruct((pool.n_slots, self.spec_gamma),
                                      jnp.int32)
        return (params_s, cache_s, row, row, row, row, row, row, row,
                drafts, key_s)

    def _audit_meta(self) -> dict:
        """ProgramArtifact meta for ds-audit captures from this engine
        (analysis/program/capture.py) — the inner engine's meta with the
        pool's donation knob (donate_cache gates the tick/row-update
        donations; the CPU overlap A/B runs them off) and sampler mode
        (the tick collective profile splits greedy vs sampled)."""
        return dict(self._eng._audit_meta(), donate=self.donate_cache,
                    sampled=self.temperature > 0.0)

    def _draft_audit_meta(self) -> dict:
        """Audit meta for programs over the DRAFT param tree (the draft
        segment prefill): the param-collective match set must be the
        draft's leaf shapes, not the target's."""
        from deepspeed_tpu.analysis.program.capture import param_leaf_shapes

        return dict(self._audit_meta(),
                    param_shapes=param_leaf_shapes(self._draft_eng.params))

    def _spec_audit_meta(self) -> dict:
        """Audit meta for the speculative tick: draft mode carries BOTH
        param trees, so the param-collective match set is their union."""
        meta = self._audit_meta()
        if self._draft_eng is not None:
            from deepspeed_tpu.analysis.program.capture import param_leaf_shapes

            meta["param_shapes"] = tuple(meta["param_shapes"]) + \
                param_leaf_shapes(self._draft_eng.params)
        return meta

    def analyze_program_memory(self) -> Dict[str, dict]:
        """Per-tick-program-family ``compiled.memory_analysis()`` view
        (temp/argument/output bytes) over every tick program built so
        far. EXPENSIVE — one AOT lower+compile per family (the AOT cache
        is separate from the dispatch cache), so this is an on-demand
        diagnostic (tests, prewarm reports), never the hot path. Returns
        {} per family on backends without the analysis (jax CPU)."""
        from deepspeed_tpu.telemetry import memory as hbm

        out: Dict[str, dict] = {}
        for pi, pool in enumerate(self._pools):
            for (chunk, read_len), fn in pool.tick_fns.items():
                args = (self._spec_tick_arg_structs(pool)
                        if chunk == "spec"
                        else self._tick_arg_structs(pool, chunk))
                try:
                    mem = hbm.program_memory(fn.lower(*args).compile())
                except Exception:  # noqa: BLE001 — strictly best-effort AOT
                    mem = {}
                if mem:
                    out[f"pool{pi}:len{pool.length}:chunk{chunk}:"
                        f"read{read_len}"] = mem
        return out

    # -- public API -----------------------------------------------------
    def validate_request(self, prompt_ids, max_new_tokens: int) -> np.ndarray:
        """Argument checks shared by ``submit`` and the serving layer's
        admission control (which must reject malformed requests BEFORE
        deciding whether capacity exists). Raises ValueError — a real
        error, not an assert that vanishes under ``python -O`` — and
        returns the canonicalized prompt array."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (every request emits a token)")
        if prompt.size + max_new_tokens > self.cache_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds the largest pool cache_len {self.cache_len}"
            )
        return prompt

    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               rid: Optional[int] = None, gen_base: int = 0) -> int:
        """Queue a request. ``rid``/``gen_base`` are the RESUME surface
        (serving-layer recovery): an explicit ``rid`` preserves a lost
        request's RNG identity on a rebuilt engine, and ``gen_base``
        offsets the device generation counter so the per-token keys
        continue the original stream — submit ``prompt + emitted`` with
        ``gen_base=len(emitted)`` and the request picks up mid-stream
        bitwise-identically."""
        prompt = self.validate_request(prompt_ids, max_new_tokens)
        if gen_base < 0:
            raise ValueError("gen_base must be >= 0")
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            if (any(r.rid == rid for r in self._pending)
                    or rid in self._results
                    or any(r.rid == rid for p in self._pools
                           for r in p.active.values())):
                raise ValueError(f"explicit rid {rid} is already in use")
            self._next_rid = max(self._next_rid, rid + 1)
        self._pending.append(_Request(rid, prompt, max_new_tokens,
                                      gen_base=gen_base))
        return rid

    def register_prefix(self, prefix_ids) -> int:
        """Prefix (prompt) caching: prefill a shared prefix ONCE and reuse
        its KV for every request submitted with ``prefix_id`` — the
        system-prompt pattern, where admission then only pays prefill for
        the per-request suffix. Returns a prefix id for submit_with_prefix.
        """
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if prefix.size >= self.cache_len:
            raise ValueError("prefix does not fit the cache")
        from deepspeed_tpu.models import transformer as tf

        n = prefix.size
        bucket = _bucket(n, self.cache_len)
        prefill_fn = self._prefill_for_bucket(bucket)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prefix
        positions = np.full((1, bucket), bucket, np.int32)
        positions[0, :n] = np.arange(n, dtype=np.int32)
        small = tf.init_cache(self.cfg, 1, bucket)
        logits, small = prefill_fn(
            self._eng.params, jnp.asarray(toks), jnp.asarray(positions), small
        )
        pid = self._next_pid  # counter, not len(): eviction must never recycle a live id
        self._next_pid += 1
        # keep the bucket cache on device; admission splices then prefills
        # only the suffix at positions [n..)
        self._prefixes[pid] = {"tokens": prefix, "cache": small, "bucket": bucket}
        return pid

    def _require_prefix(self, prefix_id: int) -> dict:
        try:
            return self._prefixes[prefix_id]
        except KeyError:
            raise KeyError(
                f"unknown prefix id {prefix_id}: never registered or already "
                f"unregistered (live ids: {sorted(self._prefixes)})") from None

    def unregister_prefix(self, prefix_id: int):
        """Release a registered prefix's device-resident KV (a long-running
        server must bound the pinned caches; in-flight requests that
        already spliced it are unaffected)."""
        self._require_prefix(prefix_id)
        self._prefixes.pop(prefix_id)

    def submit_with_prefix(self, prefix_id: int, suffix_ids, max_new_tokens: int = 32) -> int:
        """Queue a request whose prompt is (registered prefix + suffix);
        the prefix KV is reused, only the suffix is prefilled."""
        suffix = np.asarray(suffix_ids, np.int32).reshape(-1)
        if suffix.size == 0:
            raise ValueError("empty suffix (use submit for prefix-only prompts)")
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (every request emits a token)")
        pre = self._require_prefix(prefix_id)
        total = pre["tokens"].size + suffix.size
        if total + max_new_tokens > self.cache_len:
            raise ValueError(
                f"prefix {pre['tokens'].size} + suffix {suffix.size} + "
                f"max_new_tokens {max_new_tokens} exceeds cache_len {self.cache_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, np.concatenate([pre["tokens"], suffix]), max_new_tokens)
        req.prefix = pre  # snapshot: queued requests survive unregister_prefix
        self._pending.append(req)
        return rid

    def has_work(self) -> bool:
        return (bool(self._pending) or bool(self._inflight)
                or any(p.active for p in self._pools))

    def status(self, rid: int) -> str:
        """Non-destructive request state: ``"pending"`` (queued, no slot
        yet), ``"active"`` (decoding in a slot), ``"finished"`` (result
        ready, not yet collected), ``"cancelled"``, or ``"unknown"``
        (never submitted, or result already collected)."""
        if any(r.rid == rid for r in self._pending):
            return "pending"
        if any(r.rid == rid for p in self._pools for r in p.active.values()):
            return "active"
        if rid in self._results:
            return "finished"
        if rid in self._cancelled:
            return "cancelled"
        return "unknown"

    def peek(self, rid: int) -> Optional[np.ndarray]:
        """The finished result for ``rid`` WITHOUT consuming it (``result``
        pops; pollers — the serving layer — must not race the collector).
        None while the request is pending/active or the rid is unknown."""
        return self._results.get(rid)

    def result(self, rid: int) -> np.ndarray:
        try:
            return self._results.pop(rid)
        except KeyError:
            state = self.status(rid)
            detail = {
                "pending": "still queued for a slot (step() until finished)",
                "active": "still decoding (step() until finished)",
                "cancelled": "cancelled before it finished",
                "unknown": "never submitted or its result was already collected",
            }[state]
            raise KeyError(
                f"no result for request {rid}: {state} — {detail}") from None

    def cancel(self, rid: int) -> bool:
        """Cancel a request: a pending one leaves the queue, an active one
        frees its pool slot immediately — even while a tick carrying it is
        still in flight (the retired tick's row is simply not attributed;
        stale KV is position-masked on slot reuse, same as completion).
        Returns False when the rid is already finished/collected/unknown:
        too late to cancel, the caller keeps the result semantics it
        already has."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                self._pending.pop(i)
                self._mark_cancelled(rid)
                return True
        for pool in self._pools:
            for slot, req in pool.active.items():
                if req.rid == rid:
                    pool.active.pop(slot)
                    if req.chunks:
                        try:
                            pool.prefill_q.remove(req)
                        except ValueError:
                            pass
                    self._mark_cancelled(rid)
                    return True
        return False

    def _mark_cancelled(self, rid: int):
        self._cancelled[rid] = None
        while len(self._cancelled) > self._cancelled_cap:
            self._cancelled.popitem(last=False)

    def pool_state(self) -> List[dict]:
        """Per-pool occupancy snapshot (ordered by pool length, the same
        order ``_place`` scans): ``{"length", "slots", "free"}``. The
        serving layer's admission control mirrors placement against this
        without reaching into ``_pools``."""
        return [{"length": p.length, "slots": p.n_slots,
                 "free": p.n_slots - len(p.active)} for p in self._pools]

    def finished(self) -> Dict[int, np.ndarray]:
        out, self._results = self._results, {}
        return out

    def abort_inflight(self) -> int:
        """Drop every dispatched-but-unretired tick WITHOUT fetching:
        the engine-loss path (serving recovery) counts the discarded
        ticks and abandons this engine — the tokens those ticks computed
        are regenerated bitwise by the resume RNG design, never fetched
        from a device that may be gone. Returns the number of ticks
        discarded. The engine stays ``poisoned``-marked territory: only
        call this when the engine is being abandoned."""
        lost = len(self._inflight)
        self._inflight.clear()
        return lost

    def tick_stats(self) -> dict:
        """Host-overhead accounting for the tick loop: dispatch vs blocked
        milliseconds, tokens emitted / wasted past done flags, pipeline
        depth actually reached. ``overlap_frac`` is the fraction of
        host-side tick-loop time NOT spent blocked on device results
        (1.0 = the device never made the host wait); ``block_ms_per_token``
        is the loadgen A/B headline — host-blocked ms per decoded token."""
        s = dict(self._tick_stats)
        s["pipeline_depth"] = self.pipeline_depth
        # NOT the tokens_per_tick knob (the burst width): the observed mean
        s["mean_emitted_per_tick"] = (round(s["tokens"] / s["ticks"], 3)
                                      if s["ticks"] else 0.0)
        s["block_ms_per_token"] = (round(s["block_ms"] / s["tokens"], 4)
                                   if s["tokens"] else None)
        host = s["dispatch_ms"] + s["block_ms"]
        s["overlap_frac"] = (round(1.0 - s["block_ms"] / host, 4)
                             if host > 0 else None)
        s["spec_gamma"] = self.spec_gamma
        s["spec_mode"] = self.spec_mode
        s["spec_acceptance"] = (round(s["spec_accepted"] / s["spec_drafted"], 4)
                                if s["spec_drafted"] else None)
        return s

    def _place(self, req: _Request) -> Optional[tuple]:
        """(pool_index, slot) in the smallest-length pool that fits the
        request's full extent and has a free slot; None if all full."""
        need = req.prompt.size + req.max_new_tokens
        if req.prefix is not None:
            # the prefix KV splice writes a full bucket-length slice; the
            # pool row must hold it (dynamic_update_slice cannot clip)
            need = max(need, req.prefix["bucket"])
        for i, pool in enumerate(self._pools):
            if pool.length < need:
                continue
            free = pool.free_slots()
            if free:
                return i, free[0]
        return None

    def step(self) -> Dict[int, List[int]]:
        """One scheduler tick: admit pending into free slots (dispatch
        their prefill), dispatch one tick per pool with dispatchable rows,
        then retire in-flight ticks down to ``pipeline_depth``. Returns
        {rid: [tokens]} emitted by the RETIRED tick(s) — with
        ``pipeline_depth > 0`` a request's tokens surface up to that many
        steps after the tick that computed them; concatenating the lists
        across steps reproduces the generated stream exactly. Finished
        requests move to ``finished()``/``result()``.

        Fault surface: the ``dispatch`` fault hook fires FIRST, before
        any state mutates — an exception there leaves the engine fully
        consistent (``poisoned`` stays False, the caller may simply call
        ``step()`` again). Any exception past that point — injected or
        real, including the ``_retire`` fetch watchdog — sets
        ``poisoned``: in-flight results may be lost and the serving
        layer must rebuild rather than retry."""
        if self.fault_hook is not None:
            self.fault_hook("dispatch", {"tick": self._tick_index})
        self._tick_index += 1
        try:
            return self._step_body()
        except BaseException:
            self.poisoned = True
            raise

    def _step_body(self) -> Dict[int, List[int]]:
        emitted: Dict[int, List[int]] = {}
        t0 = time.perf_counter()
        # FIFO with skip: a request that only fits the (full) long pool
        # must not block shorter requests behind it
        still_pending = []
        for req in self._pending:
            placed = self._place(req)
            if placed is None:
                still_pending.append(req)
                continue
            self._admit(req, *placed)
        self._pending = still_pending

        recs: Dict[int, _TickRecord] = {}
        for pi, pool in enumerate(self._pools):
            rec = (self._dispatch_spec_tick(pool) if self.spec_gamma
                   else self._dispatch_tick(pool))
            if rec is not None:
                recs[pi] = rec
        # the dispatch span is INTENTIONALLY unsynced: it measures host
        # enqueue work while the device runs ahead (the whole point of the
        # overlap); the block span in _retire ends at a real host fetch
        dispatch_ms = (time.perf_counter() - t0) * 1000.0  # ds-lint: disable=unsynced-timing
        if recs:
            if self.span_hook is not None:
                # window-span clock zero for this tick's records: one
                # host clock read per step, no device traffic
                t_disp = time.monotonic()
                for r in recs.values():
                    r.t0 = t_disp
            self._inflight.append(recs)
        stats = self._tick_stats
        stats["steps"] += 1
        stats["ticks"] += len(recs)
        # emission capacity this step actually dispatched: every slot of a
        # ticked pool could emit k tokens (utilization denominators must
        # not assume one tick covers ALL pools)
        stats["capacity_tokens"] += sum(
            self._pools[pi].n_slots * r.k for pi, r in recs.items())
        stats["fused_prefill_ticks"] += sum(1 for r in recs.values() if r.fused)
        stats["dispatch_ms"] += dispatch_ms
        stats["max_inflight"] = max(stats["max_inflight"], len(self._inflight))

        # retire down to the pipeline depth; when nothing new was
        # dispatched, the remaining in-flight ticks are the drain tail
        block_ms = 0.0
        tokens0, wasted0 = stats["tokens"], stats["wasted_tokens"]
        drafted0, accepted0 = stats["spec_drafted"], stats["spec_accepted"]
        while self._inflight and (len(self._inflight) > self.pipeline_depth
                                  or not recs):
            block_ms += self._retire(self._inflight.popleft(), emitted)
        stats["block_ms"] += block_ms

        tele = self._eng.telemetry
        if tele.enabled:
            # tick-indexed jax.profiler window: profile_start_step counts
            # SCHEDULER TICKS here (not train steps), so a device-trace
            # capture can be pointed at the pooled-tick hot path
            tele.maybe_capture(self._tick_index)
            reg = tele.registry
            # serving dashboards read pool pressure off this gauge: cached
            # tokens across live slots / total reserved slot capacity
            reg.gauge("cache_utilization").set(self.cache_utilization())
            reg.gauge("tick_inflight_depth").set(len(self._inflight))
            n_tokens = stats["tokens"] - tokens0
            n_wasted = stats["wasted_tokens"] - wasted0
            if recs or block_ms:
                reg.histogram("tick_dispatch_ms").observe(dispatch_ms)
                reg.histogram("tick_block_ms").observe(block_ms)
                if n_wasted:
                    reg.counter("burst_wasted_tokens").inc(n_wasted)
                event = {
                    "dispatch_ms": round(dispatch_ms, 4),
                    "block_ms": round(block_ms, 4),
                    "inflight": len(self._inflight),
                    "emitted": n_tokens,
                    "wasted": n_wasted,
                    "fused_prefill": any(r.fused for r in recs.values()),
                }
                if self.spec_gamma:
                    event["spec_gamma"] = self.spec_gamma
                    event["spec_drafted"] = stats["spec_drafted"] - drafted0
                    event["spec_accepted"] = stats["spec_accepted"] - accepted0
                tele.emit("serving_tick", event)
        return emitted

    def cache_utilization(self) -> float:
        """Fraction of the reserved slot-pool KV capacity holding live
        tokens (active rows' observed extents / sum of slots × length)."""
        used = sum(min(r.prompt.size + len(r.generated), p.length)
                   for p in self._pools for r in p.active.values())
        cap = sum(p.n_slots * p.length for p in self._pools)
        return used / cap if cap else 0.0

    # -- tick dispatch / retire ------------------------------------------
    def _read_len(self, pool: _Pool, extent: int) -> Optional[int]:
        """Tight-read length covering ``extent`` cached slots (None = read
        the full pool length: tight reads off, or the bucket reached it)."""
        if not self._eng.config.kv_tight_read or extent <= 0:
            return None
        r = read_bucket(extent, pool.length,
                        self._eng.config.kv_read_floor)
        return None if r >= pool.length else r

    def _row_read_bytes(self, pool: _Pool, read_len: Optional[int]) -> int:
        from deepspeed_tpu.models.transformer import kv_read_bytes_per_row
        from deepspeed_tpu.parallel.partition import kv_shard_width

        # per-chip: the pool cache shards its heads axis over the mesh's
        # tensor width, so each chip streams 1/tp of the row's window
        return kv_read_bytes_per_row(
            self.cfg, read_len if read_len is not None else pool.length,
            tp=kv_shard_width(self.mesh, self.cfg))

    def _tick_fn(self, pool: _Pool, read_len: Optional[int],
                 chunk: Optional[int] = None):
        """The pool's compiled tick program at (chunk width, tight-read
        length). Pool-resident — bounded by the bucket family, never
        evicted."""
        key = (chunk, read_len)
        if key not in pool.tick_fns:
            fn = compile_pool_tick_fn(
                self.mesh, self.cfg, self._eng.param_shardings, pool.n_slots,
                pool.length, 1 if chunk is not None else self.tokens_per_tick,
                self.temperature, self.top_k, self.top_p,
                eos_token_id=self.eos_token_id, read_len=read_len,
                chunk=chunk, donate=self.donate_cache)[0]
            tele = self._eng.telemetry
            if tele.enabled:
                # compile flight recorder: the program's first dispatch
                # journals a compile_event keyed by the full shapes key —
                # a rebuilt engine re-compiling the family through the
                # shared hub is flagged recompile (the runtime view of
                # ds-lint's static recompile-hazard rule)
                fn = tele.compile_recorder().wrap(
                    fn, "pool_tick",
                    (pool.length, pool.n_slots,
                     1 if chunk is not None else self.tokens_per_tick,
                     chunk, read_len))
            pool.tick_fns[key] = fn
            # ds-audit capture (zero cost without a hook): the contract
            # auditor sees every tick variant a serve actually compiles
            from deepspeed_tpu.analysis.program import capture

            if capture.active():
                variant = ("fused" if chunk is not None
                           else "burst" if self.tokens_per_tick > 1
                           else "plain")
                capture.notify_program(
                    "pool_tick", variant, fn,
                    lambda: self._tick_arg_structs(pool, chunk),
                    meta=self._audit_meta)
        return pool.tick_fns[key]

    def _dispatch_tick(self, pool: _Pool) -> Optional[_TickRecord]:
        """Dispatch one tick for ``pool`` WITHOUT waiting for anything:
        inputs come from the host dispatch mirrors plus the device-threaded
        state futures. Returns None when the pool has nothing to run."""
        n, k = pool.n_slots, self.tokens_per_tick
        pos = np.full(n, pool.length, np.int32)   # parked rows: writes drop
        gen = np.zeros(n, np.int32)
        quota = np.zeros(n, np.int32)
        rids = np.zeros(n, np.int32)
        emit_mask = np.zeros(n, np.int32)
        live: Dict[int, _Request] = {}
        extent = 0
        for slot, req in pool.active.items():
            if req.chunks:
                continue  # mid-prefill: parked unless it rides this tick
            if pool.disp_gen[slot] >= req.quota:
                continue  # quota exhausted: result still in flight, no work
            live[slot] = req
            pos[slot] = pool.disp_pos[slot]
            gen[slot] = pool.disp_gen[slot]
            quota[slot] = req.quota
            rids[slot] = req.rid
            emit_mask[slot] = 1
            extent = max(extent, int(pool.disp_pos[slot]) + k)
        admit = pool.prefill_q[0] if (self.fused_prefill and pool.prefill_q) else None
        if not live and admit is None:
            return None

        params = self._eng.params
        if admit is not None:
            ctoks, cpos0, nreal, emits = admit.chunks[0]
            aslot = admit.slot
            W = _bucket(nreal, pool.chunk_cap, _CHUNK_FLOOR)
            extent = max(extent, cpos0 + nreal)
            read_len = self._read_len(pool, extent)
            fn = self._tick_fn(pool, read_len, chunk=W)
            chunk_toks = np.zeros(W, np.int32)
            chunk_toks[:nreal] = ctoks
            chunk_pos = np.full(W, pool.length, np.int32)
            chunk_pos[:nreal] = np.arange(cpos0, cpos0 + nreal, dtype=np.int32)
            emit_col = np.zeros(n, np.int32)
            if emits:
                emit_col[aslot] = nreal - 1
                emit_mask[aslot] = 1
                quota[aslot] = admit.quota
                # resume support: the sampled first token's RNG key is
                # fold_in(rid, gen) — gen_base continues a recovered
                # request's stream at its next token index
                gen[aslot] = admit.gen_base
                rids[aslot] = admit.rid
                live[aslot] = admit
            packed, pool.cache, pool.last_tok_dev, pool.done_dev = fn(
                params, pool.cache, pool.last_tok_dev, pool.done_dev,
                jnp.asarray(pos), jnp.asarray(gen), jnp.asarray(quota),
                jnp.asarray(rids), self._base_key, jnp.asarray(chunk_toks),
                jnp.asarray(chunk_pos), aslot, jnp.asarray(emit_col),
                jnp.asarray(emit_mask))
            admit.chunks.pop(0)
            if not admit.chunks:
                pool.prefill_q.popleft()
                admit.chunks = None
                pool.disp_pos[aslot] = cpos0 + nreal  # full prompt cached
                pool.disp_gen[aslot] = admit.gen_base + 1  # the emitted first token
            rec = _TickRecord(packed, live, 1,
                              self._row_read_bytes(pool, read_len), True)
            advance = 1
        else:
            read_len = self._read_len(pool, extent)
            fn = self._tick_fn(pool, read_len)
            packed, pool.cache, pool.last_tok_dev, pool.done_dev = fn(
                params, pool.cache, pool.last_tok_dev, pool.done_dev,
                jnp.asarray(pos), jnp.asarray(gen), jnp.asarray(quota),
                jnp.asarray(rids), self._base_key)
            rec = _TickRecord(packed, live, k,
                              self._row_read_bytes(pool, read_len), False)
            advance = k
        # advance the dispatch mirrors for the decode rows (the admitting
        # row's were set above); quota-clamped so a burst tail never
        # over-advances a row the host can predict finishing
        for slot, req in live.items():
            if admit is not None and slot == admit.slot:
                continue
            adv = min(advance, int(req.quota) - int(pool.disp_gen[slot]))
            pool.disp_pos[slot] += adv
            pool.disp_gen[slot] += adv
        return rec

    def _spec_round_bytes(self, pool: _Pool, read_len: Optional[int]) -> int:
        """KV bytes ONE row streams per speculative round: the target
        verify reads its window once (the (gamma+1)-wide queries share a
        single cache read), plus gamma+1 draft steps each streaming the
        draft-cache window (0 extra for ngram — drafting is host-side)."""
        total = self._row_read_bytes(pool, read_len)
        if self.spec_mode == "draft":
            from deepspeed_tpu.models.transformer import kv_read_bytes_per_row
            from deepspeed_tpu.parallel.partition import kv_shard_width

            total += (self.spec_gamma + 1) * kv_read_bytes_per_row(
                self.draft_cfg,
                read_len if read_len is not None else pool.length,
                tp=kv_shard_width(self.mesh, self.draft_cfg))
        return total

    def _spec_tick_fn(self, pool: _Pool, read_len: Optional[int]):
        """The pool's compiled SPECULATIVE tick at tight-read length
        ``read_len`` — keyed ``("spec", read_len)`` in the same
        pool-resident table as the plain variants (same no-eviction
        rationale)."""
        key = ("spec", read_len)
        if key not in pool.tick_fns:
            kw = {}
            if self.spec_mode == "draft":
                kw = dict(
                    draft_cfg=self.draft_cfg,
                    draft_param_shardings=self._draft_eng.param_shardings)
            fn = compile_spec_pool_tick_fn(
                self.mesh, self.cfg, self._eng.param_shardings, pool.n_slots,
                pool.length, self.spec_gamma, self.temperature, self.top_k,
                self.top_p, eos_token_id=self.eos_token_id,
                read_len=read_len, donate=self.donate_cache, **kw)[0]
            tele = self._eng.telemetry
            if tele.enabled:
                fn = tele.compile_recorder().wrap(
                    fn, "pool_spec_tick",
                    (pool.length, pool.n_slots, self.spec_gamma,
                     self.spec_mode, read_len))
            pool.tick_fns[key] = fn
            from deepspeed_tpu.analysis.program import capture

            if capture.active():
                capture.notify_program(
                    f"pool_spec_tick_{self.spec_mode}", "", fn,
                    lambda: self._spec_tick_arg_structs(pool),
                    meta=self._spec_audit_meta)
        return pool.tick_fns[key]

    def _dispatch_spec_tick(self, pool: _Pool) -> Optional[_TickRecord]:
        """Speculative counterpart of :meth:`_dispatch_tick`: one
        gamma-verify round per pool per step, enqueue-only like the plain
        path. Fused admission rides a SEPARATE segment dispatch on the
        same step (prompt chunks never enter the spec tick program; the
        admitting row joins the decode round the step its last chunk
        dispatches), so decode rows keep speculating through a long
        prompt's prefill."""
        g, n = self.spec_gamma, pool.n_slots
        fused = False
        if self.fused_prefill and pool.prefill_q:
            admit = pool.prefill_q[0]
            ctoks, cpos0, nreal, _ = admit.chunks.pop(0)
            W = _bucket(nreal, pool.chunk_cap, _CHUNK_FLOOR)
            seg_toks = np.zeros((n, W), np.int32)
            seg_toks[admit.slot, :nreal] = ctoks
            seg_pos = np.full(n, pool.length, np.int32)
            seg_pos[admit.slot] = cpos0
            _, pool.cache = pool.segment_fn(
                self._eng.params, jnp.asarray(seg_toks), pool.cache,
                jnp.asarray(seg_pos))
            fused = True
            if not admit.chunks:
                pool.prefill_q.popleft()
                admit.chunks = None  # joins the decode round below
        run_mask = np.zeros(n, np.int32)
        quota = np.zeros(n, np.int32)
        rids = np.zeros(n, np.int32)
        live: Dict[int, _Request] = {}
        extent = 0
        for slot, req in pool.active.items():
            if req.chunks:
                continue  # mid-prefill: device run_mask parks the row
            if pool.disp_gen[slot] >= req.quota:
                continue  # quota covered by in-flight rounds (lower bound
                # — the device's threaded done flag is authoritative)
            live[slot] = req
            run_mask[slot] = 1
            quota[slot] = req.quota
            rids[slot] = req.rid
            extent = max(extent, int(pool.disp_pos[slot]) + g + 1)
        if not live:
            return None
        read_len = self._read_len(pool, min(extent, pool.length))
        fn = self._spec_tick_fn(pool, read_len)
        if self.spec_mode == "draft":
            (packed, pool.cache, pool.draft_cache, pool.last_tok_dev,
             pool.done_dev, pool.pos_dev, pool.gen_dev) = fn(
                self._eng.params, self._draft_eng.params, pool.cache,
                pool.draft_cache, pool.last_tok_dev, pool.done_dev,
                pool.pos_dev, pool.gen_dev, jnp.asarray(quota),
                jnp.asarray(rids), jnp.asarray(run_mask), self._base_key)
        else:
            drafts = np.zeros((n, g), np.int32)
            order = self._eng.config.speculative.ngram_max_order
            for slot, req in live.items():
                # under dispatch-ahead the host context LAGS the device by
                # up to pipeline_depth rounds — that only lowers the
                # acceptance rate, never correctness (point-mass q)
                ctx = (np.concatenate([req.prompt,
                                       np.asarray(req.generated, np.int32)])
                       if req.generated else req.prompt)
                drafts[slot] = ngram.propose(ctx, g, order)
            (packed, pool.cache, pool.last_tok_dev, pool.done_dev,
             pool.pos_dev, pool.gen_dev) = fn(
                self._eng.params, pool.cache, pool.last_tok_dev,
                pool.done_dev, pool.pos_dev, pool.gen_dev,
                jnp.asarray(quota), jnp.asarray(rids),
                jnp.asarray(run_mask), jnp.asarray(drafts), self._base_key)
        # dispatch mirrors: pos becomes an UPPER bound (the device advances
        # by accepted+1 <= gamma+1, used only for read-geometry selection)
        # and gen a LOWER bound (every active round emits >= 1); _retire
        # reconciles both from the packed counts
        for slot in live:
            pool.disp_pos[slot] += g + 1
            pool.disp_gen[slot] += 1
        return _TickRecord(packed, live, g + 1,
                           self._spec_round_bytes(pool, read_len), fused,
                           spec=g)

    def _retire(self, recs: Dict[int, _TickRecord],
                emitted: Dict[int, List[int]]) -> float:
        """Retire one in-flight tick: ONE coalesced packed-buffer fetch per
        pool, then pure host attribution (no further device traffic).
        Returns the milliseconds spent blocked on the device."""
        block_ms = 0.0
        stats = self._tick_stats
        for pi, rec in recs.items():
            pool = self._pools[pi]
            if self.fault_hook is not None:
                self.fault_hook("retire", {"tick": self._tick_index,
                                           "pool": pi})
            t0 = time.perf_counter()
            arr = np.asarray(rec.packed)  # the single device get per tick
            dt = time.perf_counter() - t0
            if self.fetch_timeout_s is not None and dt > self.fetch_timeout_s:
                # post-hoc watchdog: the fetch DID return, but far past
                # budget — on a preempted/unhealthy device the next one
                # may not. Poison (via step()'s wrapper) and let the
                # serving layer rebuild; the unattributed tokens are
                # regenerated bitwise on resume.
                raise TimeoutError(
                    f"tick result fetch took {dt:.3f}s "
                    f"(> fetch_timeout_s={self.fetch_timeout_s}) — device "
                    f"unhealthy, tick pipeline abandoned")
            block_ms += dt * 1000.0
            k = rec.k
            g = rec.spec
            hook = self.span_hook
            if hook is not None:
                t_ret = time.monotonic()
                tick_kind = ("spec_verify_round" if g else
                             "prefill_chunk" if rec.fused else "decode_window")
            for slot, req in rec.live.items():
                if pool.active.get(slot) is not req:
                    # cancelled / already finished while this tick was in
                    # flight: the whole row-tick computed past the done
                    # flag — that IS the pipelining waste, count it
                    stats["wasted_tokens"] += k
                    continue
                n = int(arr[slot, k])
                stats["tokens"] += n
                stats["wasted_tokens"] += k - n
                if g:
                    accepted = int(arr[slot, g + 3])
                    stats["spec_drafted"] += g
                    stats["spec_accepted"] += accepted
                    req.spec_drafted += g
                    req.spec_accepted += accepted
                    # reconcile the dispatch mirrors: the round really
                    # advanced pos by accepted+1 (the mirror assumed
                    # gamma+1) and emitted n (the mirror assumed 1)
                    pool.disp_pos[slot] -= g - accepted
                    pool.disp_gen[slot] += n - 1
                    # rec.row_bytes is the WHOLE round's streamed bytes
                    # (one gamma+1-wide target window + the draft steps)
                    req.kv_bytes_read += rec.row_bytes
                else:
                    # the row STREAMED k read windows whether or not it
                    # accepted all k tokens (burst tails past done are
                    # wasted work, not free work) — kv_bytes_read reports
                    # physical HBM traffic
                    req.kv_bytes_read += k * rec.row_bytes
                if hook is not None:
                    # coalesce this retired tick into the request's open
                    # window (flush on kind change / window cap; _finish
                    # flushes the tail) — pure host arithmetic on values
                    # the attribution above already fetched
                    if req.win_kind is not None and req.win_kind != tick_kind:
                        self._flush_window(req)
                    if req.win_kind is None:
                        req.win_kind = tick_kind
                        req.win_t0 = rec.t0
                    req.win_t1 = t_ret
                    req.win_ticks += 1
                    req.win_tokens += n
                    if g:
                        req.win_drafted += g
                        req.win_accepted += accepted
                    if req.win_ticks >= self.span_window_ticks:
                        self._flush_window(req)
                if n:
                    toks = [int(t) for t in arr[slot, :n]]
                    req.generated.extend(toks)
                    emitted.setdefault(req.rid, []).extend(toks)
                if arr[slot, k + 1]:
                    req.done = True
                    self._finish(pool, slot)
        return block_ms

    def _flush_window(self, req: "_Request"):
        """Emit the request's open tick window through ``span_hook`` and
        reset the accumulator. No-op when no window is open (or the hook
        was uninstalled mid-flight)."""
        if req.win_kind is None or self.span_hook is None:
            req.win_kind = None
            return
        attrs = {"ticks": req.win_ticks, "tokens": req.win_tokens}
        if req.win_kind == "spec_verify_round":
            attrs["drafted"] = req.win_drafted
            attrs["accepted"] = req.win_accepted
        self.span_hook(req.rid, req.win_kind, req.win_t0, req.win_t1, attrs)
        req.win_kind = None
        req.win_ticks = req.win_tokens = 0
        req.win_drafted = req.win_accepted = 0

    # -- internals ------------------------------------------------------
    def _prefill_for_bucket(self, bucket: int):
        """B=1 ragged prefill into a bucket-length cache (pool-agnostic)."""
        def build():
            return compile_ragged_prefill_fn(
                self.mesh, self.cfg, self._eng.param_shardings, 1, bucket
            )[0]

        return cached_fn(self, "prefill_bucket", bucket, build, slots=8)

    def _insert_for_bucket(self, bucket: int, pi: int):
        """Splice a B=1 bucket cache into pool ``pi``'s shared cache row."""
        pool = self._pools[pi]

        def build():
            from deepspeed_tpu.inference.decoding import _decode_shardings

            _, small_sh = _decode_shardings(self.mesh, self.cfg, 1)

            def insert(big, small, slot):
                # positions [0..bucket) overwritten, staler junk beyond is
                # causally masked until real writes reach it (tree.map:
                # also covers the int8 {"q8","s"} representation)
                return jax.tree.map(
                    lambda b, sm: jax.lax.dynamic_update_slice(
                        b, sm.astype(b.dtype), (0, slot, 0, 0, 0)
                    ),
                    big, small,
                )

            return jax.jit(
                insert,
                in_shardings=(pool.cache_sh, small_sh, None),
                out_shardings=pool.cache_sh,
                donate_argnums=(0,),
            )

        # bounded memoization keyed (bucket, pool): 8 power-of-2 buckets
        # (16 <= b <= 2048) per pool, so capacity scales with pool count
        return cached_fn(self, "insert_bucket", (bucket, pi), build,
                         slots=8 * len(self._pools))

    def _chunk_schedule(self, pool: _Pool, toks: np.ndarray,
                        start: int) -> List[tuple]:
        """Split a prompt (or prefix suffix) into the fused-prefill chunk
        stream: [(tokens, pos0, n_real, emits)] — one tick each, the final
        chunk carries the last prompt token and samples the first generated
        token from its column."""
        cap = pool.chunk_cap
        out, off, m = [], 0, int(toks.size)
        while off < m:
            take = min(cap, m - off)
            out.append((np.asarray(toks[off:off + take], np.int32),
                        start + off, take, off + take == m))
            off += take
        return out

    def _set_row(self, pool: _Pool, slot: int, tok: int, flag: int):
        """Admission-time update of one row of the device-threaded tick
        state — dispatched against the current futures, never fetched."""
        if self.fault_hook is not None:
            self.fault_hook("set_row", {"tick": self._tick_index,
                                        "slot": slot})
        pool.last_tok_dev, pool.done_dev = pool.set_row_fn(
            pool.last_tok_dev, pool.done_dev, slot, tok, flag)

    def _admit(self, req: _Request, pi: int, slot: int):
        """Place ``req`` into a slot and dispatch its prefill — NOTHING
        here blocks or fetches. Fused mode queues the prompt as chunk(s)
        riding the next tick(s); separate mode prefills ``prompt[:-1]``
        through the B=1 bucket program + splice and re-feeds the last
        prompt token on the first decode tick (whose logits produce the
        first generated token — same stream, no admission-time sample)."""
        from deepspeed_tpu.models import transformer as tf

        pool = self._pools[pi]
        req.slot, req.pool = slot, pi
        # placement guarantees prompt + max_new_tokens fits the pool row;
        # the device stops at gen >= quota, and gen starts at gen_base
        # (0 for fresh requests, len(emitted) for recovery resumes) so
        # the emission budget is exactly max_new_tokens either way
        req.quota = req.gen_base + req.max_new_tokens
        pool.active[slot] = req
        start = 0
        toks = req.prompt
        if req.prefix is not None:
            pre = req.prefix
            # splice the cached prefix KV into the slot row (the prefix
            # bucket cache is NOT donated — it serves every request)
            insert_fn = self._insert_for_bucket(pre["bucket"], pi)
            pool.cache = insert_fn(pool.cache, pre["cache"], slot)
            start = pre["tokens"].size
            toks = req.prompt[start:]
        if self.spec_gamma:
            self._admit_spec(req, pool, pi, slot, toks, start)
            return
        if self.fused_prefill:
            req.chunks = self._chunk_schedule(pool, toks, start)
            pool.prefill_q.append(req)
            # flip the row live on device; last_tok is set by the emitting
            # chunk tick itself (the sampled first token)
            self._set_row(pool, slot, int(toks[-1]), 0)
            return
        m = int(toks.size)
        self._separate_prefill(pool, pi, slot, req, toks, start)
        # the first tick re-feeds the last prompt token at its own
        # position (writing its KV there — the position was not prefilled)
        # and samples the first generated token from the resulting logits
        self._set_row(pool, slot, int(toks[-1]), 0)
        pool.disp_pos[slot] = start + m - 1
        pool.disp_gen[slot] = req.gen_base

    def _separate_prefill(self, pool: _Pool, pi: int, slot: int,
                          req: _Request, toks: np.ndarray, start: int):
        """Admission-time prefill of ``toks[:-1]`` into the slot row: the
        B=1 bucket program + splice, or the shared segment program for
        prefix suffixes. Shared by the plain separate path and every
        speculative non-fused admission."""
        from deepspeed_tpu.models import transformer as tf

        m = int(toks.size)
        if m <= 1:
            return
        if req.prefix is not None:
            # prefill the suffix MINUS its last token through the shared
            # segment program: other rows' positions park at the pool
            # length so their KV writes drop; pad columns land at future
            # positions of THIS row, each overwritten by a real decode
            # write before it is ever attended (slot-reuse argument)
            sb = _bucket(m - 1, pool.length)
            seg_toks = np.zeros((pool.n_slots, sb), np.int32)
            seg_toks[slot, :m - 1] = toks[:m - 1]
            seg_pos = np.full(pool.n_slots, pool.length, np.int32)
            seg_pos[slot] = start
            _, pool.cache = pool.segment_fn(
                self._eng.params, jnp.asarray(seg_toks), pool.cache,
                jnp.asarray(seg_pos))
        else:
            b = _bucket(m - 1, pool.length)
            prefill_fn = self._prefill_for_bucket(b)
            insert_fn = self._insert_for_bucket(b, pi)
            ptoks = np.zeros((1, b), np.int32)
            ptoks[0, :m - 1] = toks[:m - 1]
            # pads park at bucket (dropped writes), real tokens 0..m-2
            positions = np.full((1, b), b, np.int32)
            positions[0, :m - 1] = np.arange(m - 1, dtype=np.int32)
            small = tf.init_cache(self.cfg, 1, b)
            _, small = prefill_fn(
                self._eng.params, jnp.asarray(ptoks),
                jnp.asarray(positions), small)
            pool.cache = insert_fn(pool.cache, small, slot)

    def _admit_spec(self, req: _Request, pool: _Pool, pi: int, slot: int,
                    toks: np.ndarray, start: int):
        """Speculative admission. The row ALWAYS prefills its tokens minus
        the last one (fused mode chunks them through the shared segment
        program, one enqueue-only chunk per step; separate mode uses the
        bucket prefill + splice) — the row's first spec round feeds the
        last prompt token and its verify logits yield the first generated
        token, so fused and separate admission produce the same stream.
        Draft mode additionally prefills the FULL prompt minus its last
        token through the draft segment program in one dispatch (prefix
        caching is target-only — the draft cache starts cold)."""
        m = int(toks.size)
        first_pos = start + m - 1
        if self.spec_mode == "draft":
            mfull = int(req.prompt.size)
            if mfull > 1:
                db = _bucket(mfull - 1, pool.length)
                dtoks = np.zeros((pool.n_slots, db), np.int32)
                dtoks[slot, :mfull - 1] = req.prompt[:mfull - 1]
                dpos = np.full(pool.n_slots, pool.length, np.int32)
                dpos[slot] = 0
                _, pool.draft_cache = pool.draft_segment_fn(
                    self._draft_eng.params, jnp.asarray(dtoks),
                    pool.draft_cache, jnp.asarray(dpos))
        if self.fused_prefill and m > 1:
            req.chunks = self._chunk_schedule(pool, toks[:-1], start)
            pool.prefill_q.append(req)
        else:
            self._separate_prefill(pool, pi, slot, req, toks, start)
        if self.fault_hook is not None:
            self.fault_hook("set_row", {"tick": self._tick_index,
                                        "slot": slot})
        (pool.last_tok_dev, pool.done_dev, pool.pos_dev,
         pool.gen_dev) = pool.spec_set_row_fn(
            pool.last_tok_dev, pool.done_dev, pool.pos_dev, pool.gen_dev,
            slot, int(toks[-1]), 0, first_pos, int(req.gen_base))
        pool.disp_pos[slot] = first_pos
        pool.disp_gen[slot] = req.gen_base

    def precompile_tick_programs(self, progress: Optional[Callable] = None) -> int:
        """Compile (and block on) the FULL tick-program family — every
        (pool, read bucket, {plain/burst, fused chunk widths}) variant a
        serve could dispatch — so first serve-time requests don't pay the
        20-40 s remote compile per variant (dstpu_prewarm --continuous).
        Runs each program once on throwaway state. Returns the count."""
        from deepspeed_tpu.models import transformer as tf

        count = 0
        for pool in self._pools:
            # enumerate the families through the SAME functions the serve
            # dispatch uses (_read_len over every reachable extent, the
            # chunk bucket over every real chunk size) — the warmed set can
            # never drift from what a live tick will request
            read_lens = sorted(
                {self._read_len(pool, e) for e in range(1, pool.length + 1)},
                key=lambda r: (r is None, r))
            if self.spec_gamma:
                count += self._precompile_spec(pool, read_lens, progress)
                continue
            chunks: List[Optional[int]] = [None]
            if self.fused_prefill:
                chunks += sorted({_bucket(m, pool.chunk_cap, _CHUNK_FLOOR)
                                  for m in range(1, pool.chunk_cap + 1)})
            for rl in read_lens:
                for ch in chunks:
                    t0 = time.time()
                    fn = self._tick_fn(pool, rl, chunk=ch)
                    cache = jax.device_put(
                        tf.init_cache(self.cfg, pool.n_slots, pool.length),
                        pool.cache_sh)

                    def zeros():
                        # donated operands must not alias the plain ones —
                        # fresh buffers per argument
                        return jnp.zeros(pool.n_slots, jnp.int32)

                    parked = jnp.full(pool.n_slots, pool.length, jnp.int32)
                    args = (self._eng.params, cache, zeros(),
                            jnp.ones(pool.n_slots, jnp.int32), parked,
                            zeros(), zeros(), zeros(), self._base_key)
                    if ch is not None:
                        args += (jnp.zeros(ch, jnp.int32),
                                 jnp.full(ch, pool.length, jnp.int32), 0,
                                 zeros(), zeros())
                    jax.block_until_ready(fn(*args)[0])
                    count += 1
                    if progress is not None:
                        progress(f"tick(pool={pool.length}, read_len={rl}, "
                                 f"chunk={ch}) in {time.time() - t0:.1f}s")
        return count

    def _precompile_spec(self, pool: _Pool, read_lens, progress) -> int:
        """Speculative arm of :meth:`precompile_tick_programs`: the spec
        tick per read bucket (chunks never enter it — fused admission
        rides the segment program, warmed per chunk width below)."""
        from deepspeed_tpu.models import transformer as tf

        count, g, n = 0, self.spec_gamma, pool.n_slots
        for rl in read_lens:
            t0 = time.time()
            fn = self._spec_tick_fn(pool, rl)
            cache = jax.device_put(
                tf.init_cache(self.cfg, n, pool.length), pool.cache_sh)

            def zeros():
                # donated operands must not alias — fresh buffers each
                return jnp.zeros(n, jnp.int32)

            parked = jnp.full(n, pool.length, jnp.int32)
            if self.spec_mode == "draft":
                dcache = jax.device_put(
                    tf.init_cache(self.draft_cfg, n, pool.length),
                    pool.draft_cache_sh)
                args = (self._eng.params, self._draft_eng.params, cache,
                        dcache, zeros(), jnp.ones(n, jnp.int32), parked,
                        zeros(), zeros(), zeros(), zeros(), self._base_key)
            else:
                args = (self._eng.params, cache, zeros(),
                        jnp.ones(n, jnp.int32), parked, zeros(), zeros(),
                        zeros(), zeros(), jnp.zeros((n, g), jnp.int32),
                        self._base_key)
            jax.block_until_ready(fn(*args)[0])
            count += 1
            if progress is not None:
                progress(f"spec_tick(pool={pool.length}, read_len={rl}, "
                         f"mode={self.spec_mode}, gamma={g}) "
                         f"in {time.time() - t0:.1f}s")
        if self.fused_prefill:
            # fused spec admission dispatches prompt chunks through the
            # shared segment program — retraces per chunk width
            for W in sorted({_bucket(m, pool.chunk_cap, _CHUNK_FLOOR)
                             for m in range(1, pool.chunk_cap + 1)}):
                t0 = time.time()
                cache = jax.device_put(
                    tf.init_cache(self.cfg, n, pool.length), pool.cache_sh)
                _, c2 = pool.segment_fn(
                    self._eng.params, jnp.zeros((n, W), jnp.int32), cache,
                    jnp.full(n, pool.length, jnp.int32))
                jax.block_until_ready(c2)
                count += 1
                if progress is not None:
                    progress(f"spec_segment(pool={pool.length}, chunk={W}) "
                             f"in {time.time() - t0:.1f}s")
        return count

    def _finish(self, pool: _Pool, slot: int):
        # pool pressure BEFORE the pop: the event describes the state this
        # request served under (popping first reads 0.0 for the last one)
        util = self.cache_utilization()
        req = pool.active.pop(slot)
        self._flush_window(req)  # tail window span BEFORE the request
        # leaves the serving layer's engine-rid table (the hook resolves
        # the trace through it)
        self._results[req.rid] = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]
        )
        tele = self._eng.telemetry
        if tele.enabled:
            new = len(req.generated)
            event = {
                "request": int(req.rid),
                "path": "continuous",
                "batch": 1,
                "prompt_tokens": int(req.prompt.size),
                "new_tokens": new,
                "cache_len": pool.length,
                "kv_dtype": ("int8" if self.cfg.kv_cache_dtype == "int8"
                             else self.cfg.dtype),
                "kv_bytes_read": int(req.kv_bytes_read),
                "cache_utilization": round(util, 4),
            }
            if new:  # every token rides a pool-tick read now
                event["kv_bytes_per_token"] = round(req.kv_bytes_read / new, 1)
            if self.spec_gamma:
                event["spec_gamma"] = self.spec_gamma
                event["spec_drafted"] = int(req.spec_drafted)
                event["spec_accepted"] = int(req.spec_accepted)
            if self.request_event_hook is not None:
                event = self.request_event_hook(req.rid, event) or event
            tele.emit("inference_request", event)
