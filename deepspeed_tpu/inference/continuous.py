"""Continuous (in-flight) batching for the inference engine.

Modern serving capability BEYOND the v0.9.1 reference (its inference
engine generates one static batch at a time; continuous batching arrived
in later serving stacks): a fixed pool of sequence slots shares KV cache,
new requests are admitted into free slots while other slots keep decoding,
and finished sequences free their slot immediately — no head-of-line
blocking on the longest sequence.

TPU-shaped design: everything is static-shape. The decode tick is the
existing per-row-position segment program (inference/decoding.py
``compile_segment_fn`` — one jit, any slot occupancy); admission runs a
B=1 ragged prefill into a small bucket-length cache and a compiled
``dynamic_update_slice`` splices that row into the shared cache. Slot
reuse needs no cache clearing: admission overwrites [0..len) and the
causal position mask hides anything staler.

Bucketed KV (VERDICT r4 #9): a single pool reserves ``cache_len`` for
every slot — at long contexts most of that HBM idles under short requests.
``cache_buckets=[(slots, len), ...]`` instead partitions the slots into
pools with different cache lengths; admission places each request in the
smallest-length pool it fits (prompt + max_new_tokens), falling back to
longer pools when full. Each pool keeps its own static-shape segment
program and cache, so this is the static-shape TPU analogue of paged KV:
footprint sum(slots_i * len_i) instead of max_slots * max_len, no
page-table gather in the attention kernel. ``kv_cache_bytes()`` reports
the footprint for both layouts.

    eng = ContinuousBatchingEngine(model, config={"dtype": "bfloat16"},
                                   cache_buckets=[(6, 256), (2, 2048)])
    rid = eng.submit([12, 7, 99], max_new_tokens=32)
    while eng.has_work():
        eng.step()            # one decode tick per non-empty pool
    out = eng.result(rid)     # prompt + generated tokens (np.int32)

``tokens_per_tick=k`` fuses k decode steps per tick into one compiled
scan (k× fewer host dispatches per token — the dominant serving cost on
remote-dispatch links); admission then happens between bursts, adding up
to k tokens of admission latency. Greedy output is identical to k=1.

Tight-read ticks (engine config ``kv_tight_read``, default on): every
decode tick attends a bucketed ACTIVE length — the power-of-2 window
covering the live rows' cached extents — instead of the full pool length,
so young requests in a long pool stream a fraction of the cache bytes
(decode is an HBM roofline; docs/inference.md "Cache geometry"). Finished
requests emit an ``inference_request`` event with ``kv_bytes_read`` /
``kv_bytes_per_token`` / ``kv_dtype`` / ``cache_utilization``, and
``step()`` maintains a ``cache_utilization`` gauge for dashboards.
"""

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.decoding import (
    cached_fn,
    compile_ragged_prefill_fn,
    compile_segment_fn,
    read_bucket,
    select_token,
)

# admission/bucket sizing shares the ONE bucketing rule with the tight-read
# geometry (decoding.read_bucket); the old local name stays importable
_bucket = read_bucket


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # (len,) int32 — full prompt incl. any shared prefix
    max_new_tokens: int
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    pool: Optional[int] = None
    done: bool = False
    # snapshot of the registered prefix entry (tokens/cache/bucket), taken
    # at submit time so unregister_prefix cannot strand a queued request
    prefix: Optional[dict] = None
    # KV-cache bytes this request's row streamed across its decode ticks
    # (deterministic host accounting — models.transformer.
    # kv_read_bytes_per_row at each tick's read length)
    kv_bytes_read: int = 0


class _Pool:
    """One static-shape slot pool: ``n_slots`` rows of ``length`` KV."""

    def __init__(self, engine, n_slots: int, length: int):
        from deepspeed_tpu.models import transformer as tf

        self.n_slots = n_slots
        self.length = length
        self.segment_fn, self.cache_sh, _ = compile_segment_fn(
            engine.mesh, engine.cfg, engine._eng.param_shardings, n_slots, length
        )
        self.cache = jax.device_put(
            tf.init_cache(engine.cfg, n_slots, length), self.cache_sh
        )
        self.active: Dict[int, _Request] = {}       # slot -> request
        self.pos = np.zeros(n_slots, np.int32)      # next write position
        self.last_tok = np.zeros(n_slots, np.int32)
        # tick programs keyed by tight-read length (None = full pool
        # length): shape/sampling are fixed for the engine's lifetime, so
        # they live on the pool — bounded by the power-of-2 bucket count,
        # never evicted (an LRU consulted per tick could recompile, and a
        # shared-cache lookup per tick would churn its recency bookkeeping)
        self.segment_fns: Dict[Optional[int], object] = {None: self.segment_fn}
        self.burst_fns: Dict[Optional[int], object] = {}

    def free_slots(self) -> List[int]:
        return [s for s in range(self.n_slots) if s not in self.active]

    def kv_bytes(self) -> int:
        return sum(l.nbytes for l in jax.tree.leaves(self.cache))


class ContinuousBatchingEngine:
    """Slot-pool serving loop over the shared-cache decode program."""

    def __init__(self, model, config=None, params=None, mesh=None,
                 max_slots: Optional[int] = None, cache_len: Optional[int] = None,
                 cache_buckets: Optional[List] = None,
                 eos_token_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 tokens_per_tick: int = 1):
        from deepspeed_tpu.inference.engine import InferenceEngine

        self._eng = InferenceEngine(model, config=config, params=params,
                                    mesh=mesh, seed=seed)
        # slot caches are written at per-row depths (ragged admission), which
        # the rolling ring's aligned-path math does not cover — the slot
        # pools run plain full/bucket-length caches; bucketing already bounds
        # the footprint (see PERF.md bucketed-KV table)
        self.cfg = self._eng._ring_off_cfg
        self.mesh = self._eng.mesh
        self.eos_token_id = eos_token_id
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        # burst decoding: k decode steps per scheduler tick in ONE compiled
        # program (decoding.compile_burst_segment_fn) — k× fewer host
        # dispatches per token; new requests admit only between bursts, and
        # a request finishing mid-burst wastes the rest of its burst row
        # (the freed slot's stale cache is position-masked on reuse)
        assert tokens_per_tick >= 1, tokens_per_tick
        self.tokens_per_tick = tokens_per_tick
        self._rng = jax.random.PRNGKey(seed)

        if cache_buckets is None:
            cache_len = min(cache_len or self.cfg.max_seq_len, self.cfg.max_seq_len)
            cache_buckets = [(max_slots if max_slots is not None else 4, cache_len)]
        else:
            assert cache_len is None, "pass cache_buckets OR cache_len, not both"
            assert max_slots is None, (
                "pass cache_buckets OR max_slots, not both (slot counts come "
                "from the buckets)")
            cache_buckets = sorted(
                ((int(s), int(l)) for s, l in cache_buckets), key=lambda sl: sl[1]
            )
            for s, l in cache_buckets:
                assert s >= 1 and 1 <= l <= self.cfg.max_seq_len, (s, l)
        # pools sorted by length: admission scans for the smallest fit
        self._pools = [_Pool(self, s, l) for s, l in cache_buckets]
        self.max_slots = sum(p.n_slots for p in self._pools)
        self.cache_len = max(p.length for p in self._pools)

        self._next_rid = 0
        self._next_pid = 0
        self._prefixes: Dict[int, dict] = {}  # prefix caching (register_prefix)
        self._pending: List[_Request] = []
        self._results: Dict[int, np.ndarray] = {}
        # cancelled rids, remembered so status()/result() answer precisely
        # instead of "unknown" — BOUNDED (oldest evicted past 4096): a
        # long-running server cancels routinely and must not leak an int
        # per cancellation for its lifetime. Evicted rids age back to
        # "unknown", which is also what collected results report.
        self._cancelled: "OrderedDict[int, None]" = OrderedDict()
        self._cancelled_cap = 4096
        # serving-layer enrichment point: called in _finish with
        # (rid, event dict) and may mutate/replace the event before it is
        # emitted (deepspeed_tpu/serving adds queue_ms/priority/deadline_met
        # and retags path:"serving"). None = emit the event as built.
        self.request_event_hook: Optional[Callable[[int, dict], Optional[dict]]] = None

    # -- single-pool compatibility surface (tests, introspection) --------
    @property
    def cache(self):
        assert len(self._pools) == 1, "cache is per-pool; use _pools[i].cache"
        return self._pools[0].cache

    @cache.setter
    def cache(self, value):
        assert len(self._pools) == 1
        self._pools[0].cache = value

    @property
    def _active(self) -> Dict[int, _Request]:
        """All active requests keyed by (pool-flattened) slot index."""
        out = {}
        base = 0
        for p in self._pools:
            for s, r in p.active.items():
                out[base + s] = r
            base += p.n_slots
        return out

    def kv_cache_bytes(self) -> int:
        """Total device bytes held by the slot-pool KV caches (the number
        the PERF.md bucketed-vs-fixed footprint table reports)."""
        return sum(p.kv_bytes() for p in self._pools)

    # -- public API -----------------------------------------------------
    def validate_request(self, prompt_ids, max_new_tokens: int) -> np.ndarray:
        """Argument checks shared by ``submit`` and the serving layer's
        admission control (which must reject malformed requests BEFORE
        deciding whether capacity exists). Raises ValueError — a real
        error, not an assert that vanishes under ``python -O`` — and
        returns the canonicalized prompt array."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (admission emits a token)")
        if prompt.size + max_new_tokens > self.cache_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new_tokens {max_new_tokens} "
                f"exceeds the largest pool cache_len {self.cache_len}"
            )
        return prompt

    def submit(self, prompt_ids, max_new_tokens: int = 32) -> int:
        prompt = self.validate_request(prompt_ids, max_new_tokens)
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append(_Request(rid, prompt, max_new_tokens))
        return rid

    def register_prefix(self, prefix_ids) -> int:
        """Prefix (prompt) caching: prefill a shared prefix ONCE and reuse
        its KV for every request submitted with ``prefix_id`` — the
        system-prompt pattern, where admission then only pays prefill for
        the per-request suffix. Returns a prefix id for submit_with_prefix.
        """
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if prefix.size >= self.cache_len:
            raise ValueError("prefix does not fit the cache")
        from deepspeed_tpu.models import transformer as tf

        n = prefix.size
        bucket = _bucket(n, self.cache_len)
        prefill_fn = self._prefill_for_bucket(bucket)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prefix
        positions = np.full((1, bucket), bucket, np.int32)
        positions[0, :n] = np.arange(n, dtype=np.int32)
        small = tf.init_cache(self.cfg, 1, bucket)
        logits, small = prefill_fn(
            self._eng.params, jnp.asarray(toks), jnp.asarray(positions), small
        )
        pid = self._next_pid  # counter, not len(): eviction must never recycle a live id
        self._next_pid += 1
        # keep the bucket cache on device; admission splices then prefills
        # only the suffix at positions [n..)
        self._prefixes[pid] = {"tokens": prefix, "cache": small, "bucket": bucket}
        return pid

    def _require_prefix(self, prefix_id: int) -> dict:
        try:
            return self._prefixes[prefix_id]
        except KeyError:
            raise KeyError(
                f"unknown prefix id {prefix_id}: never registered or already "
                f"unregistered (live ids: {sorted(self._prefixes)})") from None

    def unregister_prefix(self, prefix_id: int):
        """Release a registered prefix's device-resident KV (a long-running
        server must bound the pinned caches; in-flight requests that
        already spliced it are unaffected)."""
        self._require_prefix(prefix_id)
        self._prefixes.pop(prefix_id)

    def submit_with_prefix(self, prefix_id: int, suffix_ids, max_new_tokens: int = 32) -> int:
        """Queue a request whose prompt is (registered prefix + suffix);
        the prefix KV is reused, only the suffix is prefilled."""
        suffix = np.asarray(suffix_ids, np.int32).reshape(-1)
        if suffix.size == 0:
            raise ValueError("empty suffix (use submit for prefix-only prompts)")
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (admission emits a token)")
        pre = self._require_prefix(prefix_id)
        total = pre["tokens"].size + suffix.size
        if total + max_new_tokens > self.cache_len:
            raise ValueError(
                f"prefix {pre['tokens'].size} + suffix {suffix.size} + "
                f"max_new_tokens {max_new_tokens} exceeds cache_len {self.cache_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, np.concatenate([pre["tokens"], suffix]), max_new_tokens)
        req.prefix = pre  # snapshot: queued requests survive unregister_prefix
        self._pending.append(req)
        return rid

    def has_work(self) -> bool:
        return bool(self._pending) or any(p.active for p in self._pools)

    def status(self, rid: int) -> str:
        """Non-destructive request state: ``"pending"`` (queued, no slot
        yet), ``"active"`` (decoding in a slot), ``"finished"`` (result
        ready, not yet collected), ``"cancelled"``, or ``"unknown"``
        (never submitted, or result already collected)."""
        if any(r.rid == rid for r in self._pending):
            return "pending"
        if any(r.rid == rid for p in self._pools for r in p.active.values()):
            return "active"
        if rid in self._results:
            return "finished"
        if rid in self._cancelled:
            return "cancelled"
        return "unknown"

    def peek(self, rid: int) -> Optional[np.ndarray]:
        """The finished result for ``rid`` WITHOUT consuming it (``result``
        pops; pollers — the serving layer — must not race the collector).
        None while the request is pending/active or the rid is unknown."""
        return self._results.get(rid)

    def result(self, rid: int) -> np.ndarray:
        try:
            return self._results.pop(rid)
        except KeyError:
            state = self.status(rid)
            detail = {
                "pending": "still queued for a slot (step() until finished)",
                "active": "still decoding (step() until finished)",
                "cancelled": "cancelled before it finished",
                "unknown": "never submitted or its result was already collected",
            }[state]
            raise KeyError(
                f"no result for request {rid}: {state} — {detail}") from None

    def cancel(self, rid: int) -> bool:
        """Cancel a request: a pending one leaves the queue, an active one
        frees its pool slot immediately (no cache clearing needed — slot
        reuse position-masks stale KV, same as normal completion). Returns
        False when the rid is already finished/collected/unknown: too late
        to cancel, the caller keeps the result semantics it already has."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                self._pending.pop(i)
                self._mark_cancelled(rid)
                return True
        for pool in self._pools:
            for slot, req in pool.active.items():
                if req.rid == rid:
                    pool.active.pop(slot)
                    self._mark_cancelled(rid)
                    return True
        return False

    def _mark_cancelled(self, rid: int):
        self._cancelled[rid] = None
        while len(self._cancelled) > self._cancelled_cap:
            self._cancelled.popitem(last=False)

    def pool_state(self) -> List[dict]:
        """Per-pool occupancy snapshot (ordered by pool length, the same
        order ``_place`` scans): ``{"length", "slots", "free"}``. The
        serving layer's admission control mirrors placement against this
        without reaching into ``_pools``."""
        return [{"length": p.length, "slots": p.n_slots,
                 "free": p.n_slots - len(p.active)} for p in self._pools]

    def finished(self) -> Dict[int, np.ndarray]:
        out, self._results = self._results, {}
        return out

    def _place(self, req: _Request) -> Optional[tuple]:
        """(pool_index, slot) in the smallest-length pool that fits the
        request's full extent and has a free slot; None if all full."""
        need = req.prompt.size + req.max_new_tokens
        if req.prefix is not None:
            # the prefix KV splice writes a full bucket-length slice; the
            # pool row must hold it (dynamic_update_slice cannot clip)
            need = max(need, req.prefix["bucket"])
        for i, pool in enumerate(self._pools):
            if pool.length < need:
                continue
            free = pool.free_slots()
            if free:
                return i, free[0]
        return None

    def step(self) -> Dict[int, List[int]]:
        """One scheduler tick: admit pending into free slots, then one
        decode step (or a ``tokens_per_tick``-token burst) for every pool
        with active slots. Returns {rid: [tokens]} emitted this tick: an
        active request emits up to ``tokens_per_tick`` tokens, a
        just-admitted one additionally leads with its prefill token.
        Concatenating the lists across ticks reproduces the generated
        stream exactly. Finished requests move to
        ``finished()``/``result()``."""
        emitted: Dict[int, List[int]] = {}
        # FIFO with skip: a request that only fits the (full) long pool
        # must not block shorter requests behind it
        still_pending = []
        for req in self._pending:
            placed = self._place(req)
            if placed is None:
                still_pending.append(req)
                continue
            pi, slot = placed
            emitted[req.rid] = [self._admit(req, pi, slot)]
        self._pending = still_pending

        for pool in self._pools:
            if not pool.active:
                continue
            if self.tokens_per_tick > 1:
                self._burst_tick(pool, emitted)
                continue
            read_len = self._tick_read_len(pool, 1)
            toks = jnp.asarray(pool.last_tok[:, None])
            pos = jnp.asarray(pool.pos)
            self._rng, sub = jax.random.split(self._rng)
            logits, pool.cache = self._segment_for(pool, read_len)(
                self._eng.params, toks, pool.cache, pos
            )
            row_bytes = self._row_read_bytes(pool, read_len)
            nxt = np.asarray(select_token(
                logits[:, 0], self.temperature, self.top_k, sub, self.top_p
            ))
            for slot, req in list(pool.active.items()):
                req.kv_bytes_read += row_bytes
                tok = int(nxt[slot])
                self._record(req, pool, slot, tok)
                emitted.setdefault(req.rid, []).append(tok)
            pool.pos[[s for s in pool.active]] += 1
            for slot in [s for s, r in pool.active.items() if r.done]:
                self._finish(pool, slot)
        if self._eng.telemetry.enabled:
            # serving dashboards read pool pressure off this gauge: cached
            # tokens across live slots / total reserved slot capacity
            self._eng.telemetry.registry.gauge("cache_utilization").set(
                self.cache_utilization())
        return emitted

    def cache_utilization(self) -> float:
        """Fraction of the reserved slot-pool KV capacity holding live
        tokens (active rows' cached extents / sum of slots × length)."""
        used = sum(int(p.pos[s]) for p in self._pools for s in p.active)
        cap = sum(p.n_slots * p.length for p in self._pools)
        return used / cap if cap else 0.0

    def _tick_read_len(self, pool: _Pool, n_tokens: int) -> Optional[int]:
        """Tight-read length for a decode tick over ``pool``: the bucket
        covering every ACTIVE row's extent after ``n_tokens`` more steps
        (inactive rows compute garbage that is discarded either way).
        None = read the full pool length (tight reads off, or the bucket
        reached it)."""
        if not self._eng.config.kv_tight_read or not pool.active:
            return None
        floor = self._eng.config.kv_read_floor
        extent = max(int(pool.pos[s]) for s in pool.active) + n_tokens
        r = read_bucket(extent, pool.length, floor)
        return None if r >= pool.length else r

    def _row_read_bytes(self, pool: _Pool, read_len: Optional[int]) -> int:
        from deepspeed_tpu.models.transformer import kv_read_bytes_per_row

        return kv_read_bytes_per_row(
            self.cfg, read_len if read_len is not None else pool.length)

    def _segment_for(self, pool: _Pool, read_len: Optional[int]):
        """The pool's decode-tick segment program at a tight-read length
        (None = the full-length program the pool was built with). Pool-
        resident, like the burst programs — bounded by the bucket count."""
        if read_len not in pool.segment_fns:
            pool.segment_fns[read_len] = compile_segment_fn(
                self.mesh, self.cfg, self._eng.param_shardings, pool.n_slots,
                pool.length, read_len=read_len)[0]
        return pool.segment_fns[read_len]

    def _burst_tick(self, pool: _Pool, emitted: Dict[int, List[int]]):
        """One k-token burst for a pool: a single dispatch of the compiled
        burst program, then host-side acceptance (truncate each row at
        done). Greedy streams are identical to tokens_per_tick=1; sampled
        streams are equally-distributed but consume the rng in a different
        order. The whole burst reads one tight-read bucket sized to cover
        max(active pos) + k."""
        from deepspeed_tpu.inference.decoding import compile_burst_segment_fn

        k = self.tokens_per_tick
        read_len = self._tick_read_len(pool, k)
        if read_len not in pool.burst_fns:
            pool.burst_fns[read_len] = compile_burst_segment_fn(
                self.mesh, self.cfg, self._eng.param_shardings, pool.n_slots,
                pool.length, k, self.temperature, self.top_k, self.top_p,
                read_len=read_len)[0]
        burst_fn = pool.burst_fns[read_len]
        toks = jnp.asarray(pool.last_tok[:, None])
        pos = jnp.asarray(pool.pos)
        self._rng, sub = jax.random.split(self._rng)
        out, pool.cache = burst_fn(self._eng.params, toks, pool.cache, pos, sub)
        row_bytes = k * self._row_read_bytes(pool, read_len)
        out = np.asarray(out)  # (n_slots, k)
        for slot, req in list(pool.active.items()):
            # the burst streams k read windows for every row it carries,
            # whether or not the request accepts all k tokens
            req.kv_bytes_read += row_bytes
            accepted = 0
            for j in range(k):
                if req.done:
                    break  # rest of the burst row is wasted work, not state
                self._record(req, pool, slot, int(out[slot, j]))
                emitted.setdefault(req.rid, []).append(int(out[slot, j]))
                accepted += 1
            pool.pos[slot] += accepted
        for slot in [s for s, r in pool.active.items() if r.done]:
            self._finish(pool, slot)

    # -- internals ------------------------------------------------------
    def _prefill_for_bucket(self, bucket: int):
        """B=1 ragged prefill into a bucket-length cache (pool-agnostic)."""
        def build():
            return compile_ragged_prefill_fn(
                self.mesh, self.cfg, self._eng.param_shardings, 1, bucket
            )[0]

        return cached_fn(self, "prefill_bucket", bucket, build, slots=8)

    def _insert_for_bucket(self, bucket: int, pi: int):
        """Splice a B=1 bucket cache into pool ``pi``'s shared cache row."""
        pool = self._pools[pi]

        def build():
            from deepspeed_tpu.inference.decoding import _decode_shardings

            _, small_sh = _decode_shardings(self.mesh, self.cfg, 1)

            def insert(big, small, slot):
                # positions [0..bucket) overwritten, staler junk beyond is
                # causally masked until real writes reach it (tree.map:
                # also covers the int8 {"q8","s"} representation)
                return jax.tree.map(
                    lambda b, sm: jax.lax.dynamic_update_slice(
                        b, sm.astype(b.dtype), (0, slot, 0, 0, 0)
                    ),
                    big, small,
                )

            return jax.jit(
                insert,
                in_shardings=(pool.cache_sh, small_sh, None),
                out_shardings=pool.cache_sh,
                donate_argnums=(0,),
            )

        # bounded memoization keyed (bucket, pool): 8 power-of-2 buckets
        # (16 <= b <= 2048) per pool, so capacity scales with pool count
        return cached_fn(self, "insert_bucket", (bucket, pi), build,
                         slots=8 * len(self._pools))

    def _admit(self, req: _Request, pi: int, slot: int) -> Optional[int]:
        from deepspeed_tpu.models import transformer as tf

        pool = self._pools[pi]
        n = req.prompt.size
        if req.prefix is not None:
            pre = req.prefix
            n_pre = pre["tokens"].size
            # 1) splice the cached prefix KV into the slot row (the prefix
            #    bucket cache is NOT donated — it serves every request)
            insert_fn = self._insert_for_bucket(pre["bucket"], pi)
            pool.cache = insert_fn(pool.cache, pre["cache"], slot)
            # 2) prefill ONLY the suffix through the shared segment program:
            #    other rows' positions park at the pool length so their KV
            #    writes drop; suffix pad columns land at future positions of
            #    THIS row, each overwritten by a real decode write before it
            #    is ever attended (same argument as slot reuse)
            suffix = req.prompt[n_pre:]
            sb = _bucket(suffix.size, pool.length)
            toks = np.zeros((pool.n_slots, sb), np.int32)
            toks[slot, :suffix.size] = suffix
            pos = np.full(pool.n_slots, pool.length, np.int32)
            pos[slot] = n_pre
            logits, pool.cache = pool.segment_fn(
                self._eng.params, jnp.asarray(toks), pool.cache, jnp.asarray(pos)
            )
            last_logits = logits[slot: slot + 1, suffix.size - 1]
        else:
            bucket = _bucket(n, pool.length)
            prefill_fn = self._prefill_for_bucket(bucket)
            insert_fn = self._insert_for_bucket(bucket, pi)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = req.prompt
            # pads park at bucket (dropped writes), real tokens pack 0..n-1
            positions = np.full((1, bucket), bucket, np.int32)
            positions[0, :n] = np.arange(n, dtype=np.int32)
            small = tf.init_cache(self.cfg, 1, bucket)
            logits, small = prefill_fn(
                self._eng.params, jnp.asarray(toks), jnp.asarray(positions), small
            )
            pool.cache = insert_fn(pool.cache, small, slot)
            last_logits = logits[:, n - 1]
        self._rng, sub = jax.random.split(self._rng)
        first = int(np.asarray(select_token(
            last_logits, self.temperature, self.top_k, sub, self.top_p
        ))[0])
        pool.active[slot] = req
        req.slot = slot
        req.pool = pi
        # the first generated token's KV is written at position n by the
        # NEXT decode tick (it feeds last_tok at pos, then pos advances) —
        # same protocol as ragged_decode_loop
        pool.pos[slot] = n
        self._record(req, pool, slot, first)
        if req.done:
            self._finish(pool, slot)
        return first

    def _record(self, req: _Request, pool: _Pool, slot: int, tok: int):
        req.generated.append(tok)
        pool.last_tok[slot] = tok
        hit_eos = self.eos_token_id is not None and tok == self.eos_token_id
        total = req.prompt.size + len(req.generated)
        if hit_eos or len(req.generated) >= req.max_new_tokens or total >= pool.length:
            req.done = True

    def _finish(self, pool: _Pool, slot: int):
        # pool pressure BEFORE the pop: the event describes the state this
        # request served under (popping first reads 0.0 for the last one)
        util = self.cache_utilization()
        req = pool.active.pop(slot)
        self._results[req.rid] = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]
        )
        tele = self._eng.telemetry
        if tele.enabled:
            new = len(req.generated)
            event = {
                "request": int(req.rid),
                "path": "continuous",
                "batch": 1,
                "prompt_tokens": int(req.prompt.size),
                "new_tokens": new,
                "cache_len": pool.length,
                "kv_dtype": ("int8" if self.cfg.kv_cache_dtype == "int8"
                             else self.cfg.dtype),
                "kv_bytes_read": int(req.kv_bytes_read),
                "cache_utilization": round(util, 4),
            }
            if new > 1:  # admission emits the first token without a pool read
                event["kv_bytes_per_token"] = round(req.kv_bytes_read / (new - 1), 1)
            if self.request_event_hook is not None:
                event = self.request_event_hook(req.rid, event) or event
            tele.emit("inference_request", event)
