from deepspeed_tpu.inference.continuous import ContinuousBatchingEngine
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference

__all__ = ["ContinuousBatchingEngine", "InferenceEngine", "init_inference"]
