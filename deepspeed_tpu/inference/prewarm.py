"""``dstpu_prewarm`` — precompile a serving program set into the persistent
XLA compile cache, so servers cold-start warm.

On TPU every distinct compiled program costs tens of seconds (20-40 s each
through a remote-compile link); a serving stack touches several per
configuration: the fused generate (per prompt-length/new-tokens combo), or
the chunked-prefill + per-token decode pair, plus the continuous engine's
per-bucket prefill/insert and burst programs. Run this once per model
configuration with ``JAX_COMPILATION_CACHE_DIR`` pointing at a shared
directory (the engine honours ``jax_compilation_cache_dir`` config too) and
every later process reuses the executables.

The reference has no analogue (CUDA kernels load from prebuilt .so); this
is the XLA-world equivalent of shipping compiled kernels.

Usage:
  dstpu_prewarm --preset gpt2-125m --batch 8 --prompt 128 --new 128 \\
                --cache-dir /path/to/xla_cache [--chunk 128] \\
                [--continuous --slots 8 --cache-len 512 --burst 4] \\
                [--dtype bfloat16] [--kv-int8]
"""

import argparse
import sys
import time


def _parse_value(val: str):
    """KEY=VALUE override values: int, float, bool, None, or string."""
    low = val.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    for cast in (int, float):
        try:
            return cast(val)
        except ValueError:
            continue
    return val


def main(argv=None):
    p = argparse.ArgumentParser(
        description="precompile serving programs into the persistent XLA cache")
    p.add_argument("--preset", default="gpt2-125m",
                   help="model preset name (models/transformer.py PRESETS)")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--prompt", type=int, default=128,
                   help="prompt length to compile for (fused generate is "
                        "shape-specialized; pass several runs for several "
                        "lengths, or --chunk for length-agnostic prefill)")
    p.add_argument("--new", type=int, default=128)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--kv-int8", action="store_true")
    p.add_argument("--no-tight-read", action="store_true",
                   help="warm the full-length-read program set instead of "
                        "the (default) tight-read bucket stages")
    p.add_argument("--kv-floor", type=int, default=0,
                   help="tight-read bucket floor override (0 = config "
                        "default); must match the serving config or the "
                        "warmed executables miss")
    p.add_argument("--chunk", type=int, default=0,
                   help="also warm the chunked-prefill program set")
    p.add_argument("--continuous", action="store_true",
                   help="warm the continuous-batching pool programs — the "
                        "FULL tick family: every (read bucket x {plain/"
                        "burst, fused-prefill chunk width}) variant a serve "
                        "could dispatch, so serve-time requests never pay "
                        "the 20-40s remote compile per variant")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--cache-len", type=int, default=512)
    p.add_argument("--burst", type=int, default=1)
    p.add_argument("--speculative", default=None, metavar="GAMMA[:MODE]",
                   help="warm the SPECULATIVE tick family (--continuous): "
                        "gamma draft tokens verified per round, mode "
                        "'ngram' (default, draft-free) or 'draft' (a "
                        "second model on the same mesh — needs "
                        "--draft-preset). Implies single-token ticks "
                        "(--burst ignored); docs/inference.md "
                        "'Speculative decoding'")
    p.add_argument("--draft-preset", default=None,
                   help="draft-model preset for --speculative GAMMA:draft "
                        "(must share the target's vocabulary)")
    p.add_argument("--pipeline-depth", type=int, default=1,
                   help="pipeline depth the warmed serve will run at (a "
                        "host-loop knob: it does not change the compiled "
                        "program set, recorded for the drive-through warm)")
    p.add_argument("--no-fused-prefill", action="store_true",
                   help="skip the fused-prefill tick variants (warm the "
                        "separate B=1 prefill + splice programs instead)")
    p.add_argument("--mesh", default=None, metavar="DATA:TENSOR[,..]",
                   help="serving mesh shape(s) to warm under, e.g. 1:2 or "
                        "1:1,1:2 — the tick-program family is compiled PER "
                        "tensor width (sharded programs are distinct "
                        "executables), so warm every width the serve will "
                        "run or the first sharded request pays the compile")
    p.add_argument("--cache-dir", default=None,
                   help="persistent XLA cache dir (defaults to jax config / "
                        "JAX_COMPILATION_CACHE_DIR)")
    p.add_argument("--audit", action="store_true",
                   help="run ds-audit over every program this warm "
                        "compiles (the REAL serving configuration, not "
                        "the tiny-config table) and fail the warm on "
                        "contract findings — docs/static_analysis.md "
                        "'Program audit'")
    p.add_argument("--override", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="TransformerConfig field override (repeatable), e.g. "
                        "--override num_layers=2 to prewarm a truncated "
                        "model while debugging a serving config")
    args = p.parse_args(argv)

    import jax

    if args.cache_dir:
        jax.config.update("jax_compilation_cache_dir", args.cache_dir)
        # persist EVERYTHING: skipping fast-compiling programs would defeat
        # the tool (the server would still pay those compiles)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:  # an already-initialized cache instance ignores config updates
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import TransformerModel

    overrides = {}
    for item in args.override:
        key, sep, val = item.partition("=")
        assert sep and val, f"--override needs KEY=VALUE, got {item!r}"
        overrides[key] = _parse_value(val)
    model = TransformerModel.from_preset(args.preset, dtype=args.dtype, **overrides)
    cfg = {"dtype": args.dtype}
    if args.kv_int8:
        cfg["kv_cache_dtype"] = "int8"
    if args.no_tight_read:
        cfg["kv_tight_read"] = False
    if args.kv_floor:
        cfg["kv_read_floor"] = args.kv_floor
    rs = np.random.RandomState(0)

    # --audit: collect every program family the warm builds and contract-
    # check the artifacts at the end (exit 1 on findings) — this audits
    # the REAL serving configuration on the REAL mesh widths, where the
    # standalone tools/ds_audit.py audits a tiny calibration table
    collector = None
    if args.audit:
        from deepspeed_tpu.analysis.program.capture import (
            ArtifactCollector,
            set_hook,
        )

        collector = ArtifactCollector()
        set_hook(collector)

    def tick(name, fn):
        t0 = time.time()
        # drain the dispatch: without this the "ready in" time would report
        # enqueue latency while the compile/run still executes
        jax.block_until_ready(fn())
        print(f"prewarm: {name} ready in {time.time() - t0:.1f}s", flush=True)

    toks = rs.randint(0, model.cfg.vocab_size,
                      (args.batch, args.prompt)).astype(np.int32)
    # one param init shared by every engine: a second engine would
    # re-initialize AND hold another full on-device copy (3x HBM at 7B)
    params = model.init(jax.random.PRNGKey(0))

    # each requested serving mesh compiles its OWN program family (a
    # sharded executable is a different program — warming 1:1 does nothing
    # for a 1:2 serve); None = the engine's default mesh
    meshes = [None]
    if args.mesh:
        from deepspeed_tpu.parallel.partition import parse_mesh_arg

        meshes = [parse_mesh_arg(s) for s in args.mesh.split(",")]

    # the hook must not outlive the warm even when a build raises —
    # a leaked hook would capture (and re-lower) every later program
    # in the process (test engines included)
    try:
        for shape in meshes:
            mcfg = dict(cfg)
            label = ""
            if shape is not None:
                mcfg["mesh"] = {"shape": shape}
                label = (f", mesh={shape.get('data', 1)}:"
                         f"{shape.get('tensor', 1)}")
            eng = deepspeed_tpu.init_inference(model, params=params, config=dict(mcfg))
            tick(f"fused generate (B={args.batch}, S={args.prompt}, "
                 f"new={args.new}{label})",
                 lambda: np.asarray(eng.generate(toks, max_new_tokens=args.new)))

            if args.chunk:
                eng_c = deepspeed_tpu.init_inference(
                    model, params=params,
                    config=dict(mcfg, prefill_chunk_size=args.chunk))
                tick(f"chunked prefill (chunk={args.chunk}) + per-token decode"
                     f"{label}",
                     lambda: np.asarray(eng_c.generate(toks, max_new_tokens=2)))
                del eng_c

            if args.continuous:
                from deepspeed_tpu.inference import ContinuousBatchingEngine

                scfg, spec_kw, burst = dict(mcfg), {}, args.burst
                if args.speculative:
                    g, _, m = args.speculative.partition(":")
                    mode = m or "ngram"
                    scfg["speculative"] = {
                        "enabled": True, "pool": True, "mode": mode,
                        "num_draft_tokens": int(g)}
                    burst = 1  # the gamma-wide verify round IS the burst
                    if mode == "draft":
                        if not args.draft_preset:
                            p.error("--speculative GAMMA:draft needs "
                                    "--draft-preset")
                        dmodel = TransformerModel.from_preset(
                            args.draft_preset, dtype=args.dtype)
                        spec_kw = dict(
                            draft_model=dmodel,
                            draft_params=dmodel.init(jax.random.PRNGKey(1)))
                serve = ContinuousBatchingEngine(
                    model, params=params, config=scfg, max_slots=args.slots,
                    cache_len=args.cache_len, tokens_per_tick=burst,
                    pipeline_depth=args.pipeline_depth,
                    fused_prefill=not args.no_fused_prefill, **spec_kw)

                def run_pool():
                    # drive a real request through: warms the admission programs
                    # (prefill/splice or the first chunk width) plus the tick
                    # read-buckets this prompt actually crosses
                    pool_new = min(args.new, 8)
                    plen = min(args.prompt, args.cache_len - pool_new)
                    assert plen >= 1, (
                        f"--cache-len {args.cache_len} leaves no room for a prompt "
                        f"(warming {pool_new} tokens)")
                    serve.submit(toks[0, :plen], max_new_tokens=pool_new)
                    while serve.has_work():
                        serve.step()
                    serve.finished()

                spec_label = (f", speculative={args.speculative}"
                              if args.speculative else "")
                tick(f"continuous pool (slots={args.slots}, cache={args.cache_len}, "
                     f"burst={burst}{spec_label}{label})", run_pool)
                # then the FULL tick-program family (bucket x read_len x {plain,
                # burst, fused-prefill}) under THIS mesh: a live serve dispatches
                # whichever variant its mix demands — every one missing
                # cold-costs a remote compile
                n_fns = serve.precompile_tick_programs(
                    progress=lambda msg: print(f"prewarm: {msg}", flush=True))
                print(f"prewarm: tick-program family complete "
                      f"({n_fns} variants resident{label})", flush=True)
                del serve
            # drop this width's engines (and their on-device param placements
            # + KV pools) before the next width builds its own — two resident
            # placements is exactly the 3x-HBM-at-7B hazard the shared param
            # init above exists to avoid
            del eng
    finally:
        if collector is not None:
            from deepspeed_tpu.analysis.program.capture import clear_hook

            clear_hook()
    if collector is not None:
        from deepspeed_tpu.analysis.program import audit_artifacts
        from deepspeed_tpu.analysis.program.auditor import (
            build_report,
            print_text,
        )

        result = audit_artifacts(collector.artifacts)
        report = build_report(result, result.findings, [],
                              collector.artifacts)
        print(f"prewarm: ds-audit over {len(collector.artifacts)} captured "
              f"program(s)", flush=True)
        print_text(report)
        if result.findings:
            return 1
    print("prewarm: done — executables persisted to the XLA compile cache",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
