"""Inference engine.

Reference: ``deepspeed/inference/engine.py`` (InferenceEngine :89 — dtype
conversion, TP group creation :261, kernel injection :384, CUDA-graph
capture :500, generate wrapper :588). TPU redesign:

  - "kernel injection" is the compiler: the decode path is two jitted
    programs (prefill + single-token decode) over the cache-aware model
    forward; fused attention/norm come from XLA/Pallas, not swapped modules.
  - CUDA-graph capture has no analogue to build — jit IS whole-program
    capture (SURVEY.md "deliberately not ported").
  - TP: weights carry logical axes; placing them over the ``tensor`` mesh
    axis shards qkv/mlp exactly like the reference's AutoTP column/row split,
    with the per-layer allreduce inserted by GSPMD.
  - int8: weight-only groupwise quantization at load (ZeroQuant-style W8),
    dequantized in-register by XLA at matmul sites.

Decode loop: ``generate`` defaults to a FUSED whole-generation program —
prefill + ``lax.scan`` over decode steps in one jit, one dispatch per call
(``fused_generate`` in InferenceConfig; the pre-r5 per-token dispatch loop
remains as the opt-out). Greedy or temperature/top-k/top-p sampling; KV
cache donated into the program.
"""

import time
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu import comm
from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.models import transformer as tf
from deepspeed_tpu.runtime.zero.sharding import ShardingPolicy
from deepspeed_tpu.utils.logging import log_dist, logger


class InferenceEngine:
    def __init__(self, model, config=None, params=None, mesh=None, seed: int = 0):
        self.config = InferenceConfig.parse(config)
        # auto-dispatch (reference: _apply_injection_policy at
        # inference/engine.py:384 + sharded loading at :338): a checkpoint
        # path converts shard-by-shard; an HF torch module converts in place
        if isinstance(model, str):
            from deepspeed_tpu.module_inject.load_checkpoint import convert_hf_checkpoint

            model, np_params = convert_hf_checkpoint(model)
            if params is None:
                params = np_params
        elif model is not None and hasattr(model, "state_dict") and hasattr(model, "config") \
                and not isinstance(model, (tf.TransformerModel, tf.TransformerConfig)):
            from deepspeed_tpu.module_inject.policies import convert_hf_model

            model, np_params = convert_hf_model(model)
            if params is None:
                params = np_params
        builtin = isinstance(model, (tf.TransformerModel, tf.TransformerConfig))
        if isinstance(model, tf.TransformerConfig):
            model = tf.TransformerModel(model)
        self.model = model  # builtin or any object with cfg/init/apply protocol
        cfg = self.model.cfg

        dtype_name = self.config.dtype
        self._weight_quant = dtype_name == "int8" or self.config.quant.enabled
        want_dtype = None
        if dtype_name in ("float32", "float16", "bfloat16") and dtype_name != cfg.dtype:
            want_dtype = dtype_name
        elif self._weight_quant and cfg.dtype == "float32":
            want_dtype = "bfloat16"
        if self.config.kv_cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'model' or 'int8', got {self.config.kv_cache_dtype!r}"
            )
        floor = self.config.kv_read_floor
        if not (isinstance(floor, int) and floor >= 1 and (floor & (floor - 1)) == 0):
            raise ValueError(
                f"kv_read_floor must be a positive power of 2, got {floor!r}"
            )
        overrides = {}
        if self.config.kv_cache_dtype != cfg.kv_cache_dtype:
            overrides["kv_cache_dtype"] = self.config.kv_cache_dtype
        if want_dtype is not None:
            overrides["dtype"] = want_dtype
        if self.config.attn_impl is not None and self.config.attn_impl != cfg.attn_impl:
            assert self.config.attn_impl in ("xla", "pallas", "block_sparse"), \
                self.config.attn_impl
            overrides["attn_impl"] = self.config.attn_impl
        # rolling KV cache: exact for uniform-window models when prefill
        # rides the flash band kernel (segment attention never reads the
        # ring) and positions are relative (rope) or absent. Speculative
        # decoding writes per-row segments at varying depths — its paths
        # compile ring-off (full-length caches), so leave it off entirely.
        if (self.config.rolling_kv_cache
                and cfg.uniform_window is not None
                and cfg.pos_embedding in ("rope", "none")
                and overrides.get("attn_impl", cfg.attn_impl) == "pallas"
                and cfg.causal
                and not self.config.speculative.enabled):
            overrides["rolling_kv_cache"] = True
        if overrides:
            import dataclasses

            cfg = dataclasses.replace(cfg, **overrides)
            if builtin:
                self.model = tf.TransformerModel(cfg)
        if want_dtype is not None:
            if not builtin:
                # custom model object: keep it (its apply defines the network);
                # cfg carries the override so caches/compute use the new dtype
                logger.warning(
                    f"config dtype {want_dtype} != model cfg dtype {self.model.cfg.dtype}; "
                    "casting params, keeping the custom model's forward"
                )
        self.cfg = cfg

        # mesh: inference default is tensor-parallel (+ expert-parallel for
        # MoE models, reference moe_inference ep groups) over available chips.
        # An EXPLICIT config.mesh.shape with no -1 wildcard builds a subset
        # mesh over the first prod(shape) devices WITHOUT touching the global
        # comm state — several serving widths coexist in one process (the
        # sharded-vs-replicated loadgen A/B, the bench width sweep).
        mesh_cfg = self.config.mesh
        if mesh is None:
            shape = mesh_cfg.shape
            if shape is not None:
                # ALWAYS a LOCAL mesh for an explicit config shape — a
                # wildcard absorbs the whole host, a no-wildcard shape
                # takes the first prod(shape) devices — so a serving
                # engine never overwrites the process-global comm mesh a
                # training engine may be using
                devs = jax.devices()
                if -1 not in shape.values():
                    need = int(np.prod(list(shape.values()) or [1]))
                    if need > len(devs):
                        raise ValueError(
                            f"mesh shape {shape} needs {need} devices, "
                            f"only {len(devs)} available")
                    devs = devs[:need]
                mesh = comm.build_mesh(shape, devices=devs)
            elif comm.is_initialized():
                mesh = comm.get_mesh()
            else:
                shape = {"data": -1, "tensor": self.config.tensor_parallel.tp_size}
                ep = self.config.moe.ep_size
                if (self.config.moe.enabled or cfg.moe_num_experts > 0) and ep > 1:
                    shape["expert"] = ep
                mesh = comm.init_distributed(mesh_shape=shape, verbose=False)
        self.mesh = mesh

        self.policy = ShardingPolicy(mesh, stage=0, logical_specs=None)
        abstract = jax.eval_shape(self.model.init, jax.random.PRNGKey(seed))
        logical = self.model.logical_specs(abstract) if hasattr(self.model, "logical_specs") else None
        self.policy.logical_specs = logical
        if mesh_cfg.use_rules or logical is None:
            # whole-tree regex partition table (parallel/partition.py —
            # the module_inject layer for a mesh backend): user overrides
            # first, then the model-family defaults; serves models
            # WITHOUT logical_specs annotations, or any config forcing
            # the regex path with use_rules
            from deepspeed_tpu.parallel.partition import partition_params

            self.param_shardings = partition_params(mesh, abstract,
                                                    rules=mesh_cfg.rules)
        elif mesh_cfg.rules:
            # annotations win, user rules override PER-LEAF: only params
            # a rule matches change placement — one attention override
            # must not strip the expert/vocab intent annotations carry
            from deepspeed_tpu.parallel.partition import apply_rule_overrides

            self.param_shardings = apply_rule_overrides(
                mesh, abstract, self.policy.param_shardings(abstract),
                mesh_cfg.rules)
        else:
            self.param_shardings = self.policy.param_shardings(abstract)
        self.replicated = NamedSharding(mesh, PartitionSpec())
        self.batch_sharding = NamedSharding(mesh, PartitionSpec(("data", "fsdp")))

        if params is None:
            params = jax.jit(self.model.init, out_shardings=self.param_shardings)(jax.random.PRNGKey(seed))
        else:
            params = jax.device_put(params, self.param_shardings)
        # cast to model dtype (fp32 master irrelevant at inference), THEN
        # quantize — scales stay fp32 rather than riding the cast
        dt = cfg.jnp_dtype
        params = jax.tree.map(
            lambda p: p.astype(dt) if jnp.issubdtype(p.dtype, jnp.floating) else p, params
        )
        if self._weight_quant:
            params, self.param_shardings = self._quantize_weights(params)
        self.params = params

        self._prefill_fn = None
        self._decode_fn = None
        self._forward_fn = None
        self._model_times = []
        # --- telemetry hub (telemetry/: JSONL request traces, TTFT/decode
        # latency, compile-cache counters; inert when the block is disabled)
        from deepspeed_tpu.telemetry import Telemetry

        self.telemetry = Telemetry(self.config.telemetry, role="inference")
        self._request_id = 0
        self._compile_hits = 0
        self._compile_misses = 0
        # (B, max_len, alloc-bucket) shapes the migrating decode loop has
        # already traced — compile_cache_hit accounting (see generate())
        self._traced_geoms = set()
        if self.telemetry.enabled:
            # HBM baseline for the live ops plane: params are the only
            # resident allocation at build time (decode caches are
            # per-request; bucket migrations emit their own snapshots)
            from deepspeed_tpu.telemetry import memory as hbm

            hbm.emit_snapshot(self.telemetry,
                              {"params": hbm.tree_device_bytes(self.params)},
                              "build")
        log_dist(
            f"InferenceEngine ready: dtype={cfg.dtype} quant={self._weight_quant} "
            f"mesh={dict(mesh.shape)}",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    # matmul weight leaves that switch to int8 storage ("w" = untied lm head;
    # biases / norms / the MoE router gate stay float)
    _QUANT_KEYS = ("wq", "wk", "wv", "wo", "wi", "wg", "w")

    def _is_quant_target(self, path, ndim: int) -> bool:
        names = [getattr(x, "key", "") for x in path]
        return (ndim >= 2 and names[-1] in self._QUANT_KEYS
                and any(n in ("attn", "mlp", "lm_head") for n in names))

    def _quantize_weights(self, params):
        """REAL weight-only int8 storage (num_bits=8): each matmul weight
        becomes {"q8": int8, "s": fp32 per-output-channel scales} and the
        model's matmul sites (models/transformer._linear) run W8A8 on the
        MXU int8 path — HBM truly holds int8, halving the decode bandwidth
        bound, unlike fake-quant which only reproduces the numerics.
        (Reference: module_inject weight_quantizer.py + the int8 GEMM /
        dequant kernel family, csrc/transformer/inference pt_binding.cpp.)
        num_bits != 8 falls back to fake-quant storage. Returns
        (params, shardings) transformed in lockstep so every jit
        in_shardings pytree keeps matching."""
        nbits = self.config.quant.num_bits
        if nbits != 8:
            from deepspeed_tpu.ops.quantizer import fake_quantize

            def fq(path, p):
                if p.ndim >= 2 and any(
                    getattr(x, "key", "") in ("attn", "mlp", "lm_head") for x in path
                ):
                    groups = max(1, p.shape[-1] // 128) if p.size % max(1, p.shape[-1] // 128) == 0 else 1
                    return fake_quantize(p, num_bits=nbits, num_groups=groups)
                return p

            return jax.tree_util.tree_map_with_path(fq, params), self.param_shardings

        def quant_leaf(path, p):
            if not self._is_quant_target(path, p.ndim):
                return p
            w32 = jnp.asarray(p, jnp.float32)
            absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)  # over contraction dim
            s = jnp.maximum(absmax / 127.0, 1e-12)
            q8 = jnp.clip(jnp.round(w32 / s), -128, 127).astype(jnp.int8)
            return {"q8": q8, "s": s}

        def shard_leaf(path, p, sh):
            if not self._is_quant_target(path, p.ndim):
                return sh
            spec = list(sh.spec) + [None] * (p.ndim - len(sh.spec))
            s_spec = list(spec)
            s_spec[-2] = None  # scales have extent 1 on the contraction dim
            return {"q8": sh, "s": NamedSharding(self.mesh, PartitionSpec(*s_spec))}

        new_params = jax.tree_util.tree_map_with_path(quant_leaf, params)
        new_shardings = jax.tree_util.tree_map_with_path(shard_leaf, params, self.param_shardings)
        return new_params, new_shardings

    # ------------------------------------------------------------------
    def _compile(self, batch_size: int, max_len: int):
        from deepspeed_tpu.inference.decoding import compile_decode_fns

        self._prefill_fn, self._decode_fn, self._cache_sharding, self.batch_sharding = (
            compile_decode_fns(self.mesh, self.cfg, self.param_shardings, batch_size, max_len)
        )
        self._compiled_shape = (batch_size, max_len)
        if self.telemetry.enabled:
            rec = self.telemetry.compile_recorder()
            self._prefill_fn = rec.wrap(self._prefill_fn, "decode_prefill",
                                        self._compiled_shape)
            self._decode_fn = rec.wrap(self._decode_fn, "decode_step",
                                       self._compiled_shape)
        # ds-audit capture (zero cost without a hook): the decode pair is
        # the engine's hot program family — contract-checked as built
        from deepspeed_tpu.analysis.program import capture

        if capture.active():
            def sds(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

            params_s = jax.tree.map(sds, self.params)
            cache_s = jax.tree.map(sds, jax.eval_shape(
                lambda: tf.init_cache(self.cfg, batch_size, max_len)))
            capture.notify_program(
                "decode_prefill", "", self._prefill_fn,
                lambda: (params_s,
                         jax.ShapeDtypeStruct((batch_size, 8), jnp.int32),
                         cache_s),
                meta=self._audit_meta)
            capture.notify_program(
                "decode_step", "", self._decode_fn,
                lambda: (params_s,
                         jax.ShapeDtypeStruct((batch_size, 1), jnp.int32),
                         cache_s, jax.ShapeDtypeStruct((), jnp.int32)),
                meta=self._audit_meta)
        # fresh jit objects hold no traces — geoms recorded against the
        # discarded pair must not claim their shapes are still compiled
        self._traced_geoms = set()

    def _ensure_compiled(self, batch_size: int, max_len: int):
        miss = self._prefill_fn is None or self._compiled_shape != (batch_size, max_len)
        if miss:
            self._compile(batch_size, max_len)
            self._compile_misses += 1
        else:
            self._compile_hits += 1
        if self.telemetry.enabled:
            self.telemetry.registry.counter(
                "compile_cache", {"kind": "decode", "outcome": "miss" if miss else "hit"}
            ).inc()

    def _audit_meta(self) -> dict:
        """ProgramArtifact meta for ds-audit captures from this engine
        (analysis/program/capture.py) — built only while a hook is
        installed. The decode pair always donates its cache
        (compile_decode_fns donate_argnums=(2,))."""
        from deepspeed_tpu.analysis.program.capture import param_leaf_shapes
        from deepspeed_tpu.parallel.partition import mesh_tensor_width

        accum = {"float32": ("f32",), "bfloat16": ("bf16", "f32"),
                 "float16": ("f16", "f32")}.get(self.cfg.dtype, ())
        tp = mesh_tensor_width(self.mesh)
        return {
            "tp": tp,
            # dp/fsdp/... width: >1 means the calibrated tensor-only
            # collective tables don't apply (the inventory rule skips)
            "other_axes": int(self.mesh.devices.size) // max(tp, 1),
            "donate": True,
            "param_shapes": param_leaf_shapes(self.params),
            "accum_dtypes": accum,
            "int8_kv": self.cfg.kv_cache_dtype == "int8",
            "hbm_limit_bytes": getattr(self.telemetry.cfg,
                                       "hbm_limit_bytes", 0),
        }

    # ------------------------------------------------------------------
    def forward(self, input_ids, **kwargs):
        """Full-sequence logits (HF-pipeline parity surface)."""
        t0 = time.time()
        tokens = jnp.asarray(np.asarray(input_ids), jnp.int32)
        if self._forward_fn is None:
            cfg = self.cfg
            self._forward_fn = jax.jit(lambda p, t: tf.apply(p, cfg, t))
        logits = self._forward_fn(self.params, tokens)
        return self._finish_request(
            "forward", t0, logits,
            prompt_tokens=tokens.shape[1], new_tokens=0, batch=tokens.shape[0],
        )

    __call__ = forward

    def model_times(self):
        times = self._model_times
        self._model_times = []
        return times

    def _kv_fields(self, prompt_len: int, new_tokens: int, cache_len: int,
                   floor: Optional[int], batch: int,
                   alloc: Optional[int] = None) -> Optional[dict]:
        """Deterministic KV-read accounting for a generate call (None when
        telemetry is off): total cache bytes the decode steps streamed
        (``kv_bytes_read``), the per-decoded-token rate, the cache dtype,
        and how much of the allocation the request actually used. Pure host
        math mirroring the compiled read geometry (decoding.read_stages),
        so tests assert it exactly and the CPU mesh can measure the
        tight-read win with the TPU relay down. On a tensor-parallel mesh
        the bytes are PER-CHIP — each chip streams only its head shard, so
        kv_shard_width divides them out (that per-chip rate is what bounds
        a bandwidth-limited decode step)."""
        if not self.telemetry.enabled:
            return None
        from deepspeed_tpu.inference.decoding import decode_kv_bytes
        from deepspeed_tpu.parallel.partition import kv_shard_width

        per_row = decode_kv_bytes(self.cfg, prompt_len, new_tokens, cache_len,
                                  floor, tp=kv_shard_width(self.mesh, self.cfg))
        decoded = max(new_tokens - 1, 0)
        alloc = alloc if alloc is not None else cache_len
        fields = {
            "kv_dtype": "int8" if self.cfg.kv_cache_dtype == "int8" else self.cfg.dtype,
            "kv_bytes_read": int(batch) * per_row,
            "cache_utilization": round(min((prompt_len + new_tokens) / alloc, 1.0), 4),
        }
        if decoded:
            fields["kv_bytes_per_token"] = round(per_row / decoded, 1)
        return fields

    def _finish_request(self, path: str, t0: float, result, prompt_tokens: int,
                        new_tokens: int, batch: int, cache_len: Optional[int] = None,
                        timings: Optional[dict] = None,
                        misses_before: Optional[int] = None,
                        kv: Optional[dict] = None):
        """Single exit point for every forward/generate path. Preserves the
        reference's ``profile_model_time`` wall-clock list (``model_times()``
        drain semantics unchanged) and emits one structured
        "inference_request" telemetry event: TTFT when the path exposes a
        first-token boundary (the host-driven loops; the fused program is
        one dispatch, so TTFT degenerates to total), batch-aggregate decode
        tokens/sec, the chosen KV-cache length, and whether the request hit
        the compiled-fn cache or paid a compile."""
        want_time = self.config.profile_model_time or self.telemetry.enabled
        if not want_time:
            return result
        jax.block_until_ready(result)
        now = time.time()
        total_s = now - t0
        if self.config.profile_model_time:
            self._model_times.append(total_s)
        if self.telemetry.enabled:
            self._request_id += 1
            event = {
                "request": self._request_id,
                "path": path,
                "batch": int(batch),
                "prompt_tokens": int(prompt_tokens),
                "new_tokens": int(new_tokens),
                "total_ms": total_s * 1000.0,
            }
            if cache_len is not None:
                event["cache_len"] = int(cache_len)
            if kv is not None:
                event.update(kv)
            if misses_before is not None:
                event["compile_cache_hit"] = self._compile_misses == misses_before
            ttft_s = (timings or {}).get("first_token_s")
            if ttft_s is not None:
                event["ttft_ms"] = (ttft_s - t0) * 1000.0
            if new_tokens > 0 and total_s > 0:
                event["tokens_per_sec"] = int(batch) * (prompt_tokens + new_tokens) / total_s
                if ttft_s is None:
                    event["decode_tokens_per_sec"] = int(batch) * new_tokens / total_s
                elif new_tokens > 1:
                    # the first token lands at TTFT; rate the remaining
                    # tokens over the decode span (a 1-token request has no
                    # decode span — omit rather than divide by ~0)
                    event["decode_tokens_per_sec"] = (
                        int(batch) * (new_tokens - 1) / max(now - ttft_s, 1e-9)
                    )
            self.telemetry.emit("inference_request", event)
        return result

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        rng: Optional[jax.Array] = None,
        eos_token_id: Optional[int] = None,
        draft: Optional["InferenceEngine"] = None,
        num_draft_tokens: Optional[int] = None,
        attention_mask=None,
    ):
        """Greedy / temperature sampling with a compiled decode loop.

        ``attention_mask`` ((B, S) of 0/1, HF semantics) enables ragged
        prompts — left or right padding; pad slots never enter the KV
        cache, and each row decodes from its own length.

        Passing ``draft`` (a second, smaller InferenceEngine on the same
        tokenizer/vocab) switches to lossless speculative decoding: the
        draft proposes ``num_draft_tokens`` tokens per round and this
        engine verifies them in one segment forward (config block
        ``speculative.num_draft_tokens`` sets the default)."""
        tokens = jnp.asarray(np.asarray(input_ids), jnp.int32)
        B, S = tokens.shape
        if max_new_tokens <= 0:
            return tokens
        # with a mask, capacity is governed by the longest REAL prompt, not
        # the padded width (padding='max_length' batches are legal even at
        # S == max_seq_len)
        longest = int(np.asarray(attention_mask).sum(axis=1).max()) if attention_mask is not None else S
        total = longest + max_new_tokens
        assert total <= self.cfg.max_seq_len, (
            f"prompt {longest} + {max_new_tokens} new > max_seq_len {self.cfg.max_seq_len}"
        )
        # KV-cache allocation bounded by max_out_tokens (reference
        # inference/config.py max_out_tokens), grown only if the request needs it
        from deepspeed_tpu.inference.decoding import bounded_cache_len, decode_loop

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # telemetry: compile-cache snapshot (events tag compile-paying
        # requests) and the TTFT stamp dict for the host-driven loops
        misses0 = self._compile_misses
        timings = {} if self.telemetry.enabled else None
        if self.config.prefill_chunk_size and draft is None \
                and not self.config.speculative.enabled:
            # fixed-shape (B, chunk) prefill program for EVERY prompt
            # length and padded width — including attention_mask batches,
            # the varied-width serving workload that motivates chunking.
            # Rides the ragged/segment families (ring-off, full cache).
            from deepspeed_tpu.inference.decoding import chunked_generate

            max_len = bounded_cache_len(total, self.cfg.max_seq_len,
                                        self.config.max_out_tokens)
            prefill_fn, segment_fn, cache_sh = self._ragged_fns_for(B, max_len)
            cache = jax.device_put(tf.init_cache(self.cfg, B, max_len), cache_sh)
            t0 = time.time()
            result = chunked_generate(
                prefill_fn, segment_fn, self.params, tokens, cache, max_len,
                self.config.prefill_chunk_size, max_new_tokens, temperature,
                top_k, rng, top_p, attention_mask=attention_mask,
                timings=timings, tight_read=self.config.kv_tight_read)
            result = self._finish_request(
                "chunked_prefill", t0, result, prompt_tokens=S,
                new_tokens=max_new_tokens, batch=B, cache_len=max_len,
                timings=timings, misses_before=misses0,
                kv=self._kv_fields(longest, max_new_tokens, max_len,
                                   self._tight_floor(), B))
            if eos_token_id is not None:
                result = self._truncate_eos(result, S, eos_token_id)
            return result
        if attention_mask is not None:
            if draft is not None or self.config.speculative.enabled:
                raise NotImplementedError(
                    "speculative decoding does not take attention_mask yet"
                )
            from deepspeed_tpu.inference.decoding import ragged_decode_loop

            max_len = bounded_cache_len(total, self.cfg.max_seq_len, self.config.max_out_tokens)
            prefill_fn, segment_fn, cache_sh = self._ragged_fns_for(B, max_len)
            cache = jax.device_put(tf.init_cache(self.cfg, B, max_len), cache_sh)
            t0 = time.time()
            result = ragged_decode_loop(
                prefill_fn, segment_fn, self.params, tokens, attention_mask,
                cache, max_len, max_new_tokens, temperature, top_k, rng, top_p,
                timings=timings, tight_read=self.config.kv_tight_read,
            )
            result = self._finish_request(
                "ragged", t0, result, prompt_tokens=S,
                new_tokens=max_new_tokens, batch=B, cache_len=max_len,
                timings=timings, misses_before=misses0,
                kv=self._kv_fields(longest, max_new_tokens, max_len,
                                   self._tight_floor(), B))
            if eos_token_id is not None:
                result = self._truncate_eos(result, S, eos_token_id)
            return result
        if draft is None and self.config.speculative.enabled:
            draft = getattr(self, "_draft_engine", None)
            if draft is None:
                raise ValueError(
                    "speculative.enabled but no draft model: pass draft= to "
                    "generate() or draft_model= to init_inference(), or set "
                    "speculative.mode='ngram' for draft-free self-drafting "
                    "(pooled serving, ContinuousBatcher)"
                )
        if draft is not None:
            gamma = (num_draft_tokens if num_draft_tokens is not None
                     else self.config.speculative.num_draft_tokens)
            if gamma < 1:
                raise ValueError(
                    f"speculative.num_draft_tokens must be >= 1, got {gamma}")
            result = self._generate_speculative(
                draft, tokens, max_new_tokens, temperature, top_k, top_p, rng,
                gamma, eos_token_id,
            )
            if eos_token_id is not None:
                result = self._truncate_eos(result, S, eos_token_id)
            return result

        max_len = bounded_cache_len(total, self.cfg.max_seq_len, self.config.max_out_tokens)
        max_len = self._ring_cache_len(max_len, S)
        # tight reads never apply to the ring geometry (already O(window))
        floor = None if self.cfg.rolling_kv_cache else self._tight_floor()
        if self.config.fused_generate:
            # one dispatch for the whole generation (prefill + scan over
            # decode steps) — identical token stream to decode_loop; tight
            # reads ride as bucket-staged scans inside the same program
            fused_fn, cache_sh = self._fused_generate_fn(
                B, max_len, max_new_tokens, temperature, top_k, top_p,
                read_floor=floor)
            cache = jax.device_put(tf.init_cache(self.cfg, B, max_len), cache_sh)
            t0 = time.time()
            result = fused_fn(self.params, tokens, cache, rng)
            result = self._finish_request(
                "fused", t0, result, prompt_tokens=S,
                new_tokens=max_new_tokens, batch=B, cache_len=max_len,
                misses_before=misses0,
                kv=self._kv_fields(S, max_new_tokens, max_len, floor, B))
            if eos_token_id is not None:
                result = self._truncate_eos(result, S, eos_token_id)
            return result
        self._ensure_compiled(B, max_len)

        from deepspeed_tpu.inference.decoding import read_bucket

        # bucket-migrated allocation: the per-token loop starts its cache at
        # the prompt's bucket and grows by migration (decode reads therefore
        # stream the bucketed active length); tight-read off or a ring-sized
        # cache keeps the full allocation. The final allocation stops at
        # bucket(total-1): the LAST write lands at total-2 (the closing
        # sampled token is never cached) — bucket(total) would overstate
        # alloc 2x at exact boundaries and halve the reported utilization.
        alloc = max_len if floor is None else min(read_bucket(S + 1, max_len, floor), max_len)
        final_alloc = (max_len if floor is None else
                       min(read_bucket(max(S + 1, total - 1), max_len, floor),
                           max_len))
        fresh_allocs: set = set()
        if floor is not None:
            # honest compile accounting: the prefill/decode jit OBJECTS are
            # keyed (B, max_len), but migration retraces them per allocation
            # bucket — a request whose bucket walk meets an untraced shape
            # pays real XLA compiles and must not be tagged a cache hit
            geoms, b = {(B, max_len, alloc)}, alloc
            while b < final_alloc:
                b = min(b * 2, max_len)
                geoms.add((B, max_len, b))
            fresh = geoms - self._traced_geoms
            if fresh:
                self._compile_misses += 1
                self._traced_geoms |= fresh
                # allocation buckets whose migration dispatch will pay a
                # real re-trace this request — the flight recorder only
                # journals those (an already-traced bucket re-migrated by
                # a later request dispatches from the jit cache)
                fresh_allocs = {g[2] for g in fresh}
        decode_fn = (self._decode_fn if floor is None
                     else self._migrating_decode_fn(max_len, floor,
                                                    fresh_allocs))
        cache = jax.device_put(tf.init_cache(self.cfg, B, alloc), self._cache_sharding)
        t0 = time.time()
        result = decode_loop(
            self._prefill_fn, decode_fn, self.params, tokens, cache,
            max_new_tokens, temperature, top_k, rng, top_p=top_p,
            timings=timings,
        )
        result = self._finish_request(
            "decode_loop", t0, result, prompt_tokens=S,
            new_tokens=max_new_tokens, batch=B, cache_len=max_len,
            timings=timings, misses_before=misses0,
            kv=self._kv_fields(S, max_new_tokens, max_len, floor, B,
                               alloc=final_alloc))
        if eos_token_id is not None:
            result = self._truncate_eos(result, S, eos_token_id)
        return result

    def _tight_floor(self) -> Optional[int]:
        """The tight-read bucket floor, or None when the knob is off."""
        return self.config.kv_read_floor if self.config.kv_tight_read else None

    def _migrating_decode_fn(self, max_len: int, floor: int,
                             fresh_allocs: Optional[set] = None):
        """Wrap the compiled decode step with bucket-migrated cache growth:
        when the write position reaches the current allocation, one jitted
        pad (memoized per target length) migrates the cache to the next
        power-of-2 bucket. Every step's read then streams the bucketed
        active length — the tight-read geometry — without any per-step
        slicing in the compiled program."""
        from deepspeed_tpu.inference.decoding import read_bucket
        from deepspeed_tpu.models.transformer import cache_alloc_len

        fresh = set() if fresh_allocs is None else fresh_allocs
        first = True

        def dispatch(params, tok, cache, pos):
            nonlocal first
            if pos + 1 > cache_alloc_len(cache):
                new_len = min(read_bucket(pos + 1, max_len, floor), max_len)
                cache = self._grow_cache(cache, new_len)
                if self.telemetry.enabled:
                    # every migration snapshots the grown allocation; the
                    # decode jit RE-TRACES only at an untraced bucket —
                    # that runtime recompile is what the flight recorder
                    # journals (each fresh bucket compiles exactly once)
                    retrace = new_len in fresh
                    fresh.discard(new_len)
                    return self._migrated_decode(params, tok, cache, pos,
                                                 new_len, retrace)
            if first:
                # a request can also pay a re-trace at its STARTING bucket
                # (a longer prompt opening an untraced allocation, no
                # migration involved) — journal that compile too, unless
                # the decode fn's own first-call timer is still armed (the
                # genuine first compile, which records itself)
                first = False
                start_alloc = cache_alloc_len(cache)
                if (start_alloc in fresh and self.telemetry.enabled
                        and getattr(self._decode_fn, "_done", True)):
                    fresh.discard(start_alloc)
                    return self._timed_decode_retrace(params, tok, cache,
                                                      pos, start_alloc)
                fresh.discard(start_alloc)
            return self._decode_fn(params, tok, cache, pos)

        return dispatch

    def _migrated_decode(self, params, tok, cache, pos, new_len: int,
                         retrace: bool):
        """First decode dispatch after a bucket migration: emit the
        ``memory_snapshot`` (reason ``migration``) for the grown
        allocation and — when this bucket is genuinely untraced — journal
        the decode re-trace as a compile_event under the same family+key
        as the original ``decode_step`` compile, so the event is
        recompile-flagged (the visible counter behind runtime recompile
        storms)."""
        from deepspeed_tpu.telemetry import memory as hbm

        hbm.emit_snapshot(self.telemetry, {
            "params": hbm.tree_device_bytes(self.params),
            "kv_cache": hbm.tree_device_bytes(cache),
        }, "migration")
        if not retrace:
            return self._decode_fn(params, tok, cache, pos)
        return self._timed_decode_retrace(params, tok, cache, pos, new_len)

    def _timed_decode_retrace(self, params, tok, cache, pos, alloc: int):
        """Dispatch one decode step that is known to pay a runtime
        re-trace (an untraced allocation bucket) and journal it as a
        compile_event under the same family+key as the original
        ``decode_step`` compile — recompile-flagged, ``cache_alloc``
        attached (the visible counter behind runtime recompile storms)."""
        rec = self.telemetry.compile_recorder()
        t0 = time.perf_counter()
        out = self._decode_fn(params, tok, cache, pos)
        # dispatch blocks through the re-trace + XLA compile and returns
        # futures — the span is compile cost, not execution, by design
        rec.record("decode_step", self._compiled_shape,
                   # ds-lint: disable=unsynced-timing
                   (time.perf_counter() - t0) * 1000.0, cache_alloc=alloc)
        return out

    def _grow_cache(self, cache, new_len: int):
        """Migrate a KV cache to a longer time axis (zero-padded tail; the
        position mask keeps the tail inert until real writes reach it).
        No donation — the output shape differs from the input's, so XLA
        could not alias the buffers anyway; the old cache frees when its
        last reference (the caller's local) drops after the dispatch."""
        sharding = self._cache_sharding  # snapshot: the closure must match
        # the cache THIS call grows, and _cache_sharding flips between
        # batch-sharded and replicated with the request's batch size — so
        # the memo key carries the batch dim alongside the target length
        batch = jax.tree.leaves(cache)[0].shape[1]

        def build():
            def grow(c):
                return jax.tree.map(
                    lambda leaf: jnp.pad(
                        leaf, [(0, 0), (0, 0), (0, new_len - leaf.shape[2]),
                               (0, 0), (0, 0)]), c)

            return jax.jit(grow, in_shardings=(sharding,),
                           out_shardings=sharding)

        # every bucket from floor to max_len is a distinct target length —
        # keep them all resident, not the default-4 LRU window
        return self._cached_fn("grow_cache", (batch, new_len), build,
                               slots=16)(cache)

    def _ring_cache_len(self, max_len: int, prompt_len: int) -> int:
        """Rolling-cache sizing: shrink the cache to the sliding window when
        prefill will ride the flash band path (segment attention never reads
        the ring) — or the prompt is a single token. Otherwise keep the full
        length: the ring math degenerates to a plain cache when nothing
        wraps, so correctness never depends on this choice."""
        if not self.cfg.rolling_kv_cache:
            return max_len
        from deepspeed_tpu.ops.pallas.flash_attention import supports_seq_len

        if prompt_len > 1 and not supports_seq_len(prompt_len):
            return max_len  # einsum prefill must see an unwrapped cache
        return min(max_len, self.cfg.uniform_window)

    @property
    def _ring_off_cfg(self):
        """cfg clone for the per-row-depth compiled families (speculative /
        ragged / continuous segments): they write rows at varying offsets,
        which the ring's aligned-path math does not cover — they run with
        full-length caches instead."""
        if not self.cfg.rolling_kv_cache:
            return self.cfg
        import dataclasses

        return dataclasses.replace(self.cfg, rolling_kv_cache=False)

    def _cached_fn(self, kind: str, key, builder, slots: int = 4):
        """Bounded memoization for every compiled-fn family on the engine
        (plain decode, speculative, ragged) — decoding.cached_fn, shared
        with the hybrid engine. Multiple slots matter: the speculative and
        ragged paths share the "segment" family but legitimately use
        different cache lengths (the spec path adds gamma+1 slack), and
        tight-read families multiply keys by the bucket count."""
        from deepspeed_tpu.inference.decoding import cached_fn

        return cached_fn(self, kind, key, builder, slots=slots)

    def _segment_fn(self, batch_size: int, max_len: int):
        """Per-row-position segment forward, shared by the speculative and
        ragged paths (any segment width retraces under the same wrapper).
        Returns a DISPATCHER ``fn(params, toks, cache, pos, active=None)``:
        callers that know the live rows' max cached extent (the ragged /
        chunked decode tails) pass ``active`` and get a tight-read variant
        compiled per bucket; 4-arg callers (speculative verify) read the
        full cache as before."""
        from deepspeed_tpu.inference.decoding import compile_segment_fn, read_bucket

        floor = self._tight_floor()

        def fn_for(read_len):
            # one long generation walks every bucket up to max_len (~6 keys
            # at 4096/128) — the default 4 slots would evict and recompile
            # the early buckets on EVERY subsequent request
            return self._cached_fn(
                "segment", (batch_size, max_len, read_len),
                lambda: compile_segment_fn(self.mesh, self._ring_off_cfg,
                                           self.param_shardings, batch_size,
                                           max_len, read_len=read_len)[0],
                slots=16,
            )

        local = {}  # dispatcher-local memo: the per-token decode tail must
        # not touch the LRU (dict pop/reinsert + a telemetry counter inc)
        # on EVERY step — one cached_fn hit per bucket per request, like
        # the one-fetch-per-generate accounting before tight reads

        def dispatch(params, toks, cache, pos, active=None):
            read_len = None
            if floor is not None and active is not None:
                r = read_bucket(active, max_len, floor)
                read_len = None if r >= max_len else r
            if read_len not in local:
                local[read_len] = fn_for(read_len)
            return local[read_len](params, toks, cache, pos)

        return dispatch

    def _fused_generate_fn(self, batch_size: int, max_len: int,
                           max_new_tokens: int, temperature: float,
                           top_k: int, top_p: float,
                           read_floor: Optional[int] = None):
        """(generate_fn, cache_sharding) for the fused whole-generation
        program — shared wiring in decoding.fused_generate_fn."""
        from deepspeed_tpu.inference.decoding import fused_generate_fn

        return fused_generate_fn(self, self.mesh, self.cfg, self.param_shardings,
                                 batch_size, max_len, max_new_tokens,
                                 temperature, top_k, top_p,
                                 read_floor=read_floor)

    def _ragged_fns_for(self, batch_size: int, max_len: int):
        """(ragged_prefill_fn, segment_fn, cache_sharding) for attention_mask
        generation."""
        from deepspeed_tpu.inference.decoding import compile_ragged_prefill_fn

        prefill_fn, cache_sh = self._cached_fn(
            "ragged_prefill", (batch_size, max_len),
            lambda: compile_ragged_prefill_fn(self.mesh, self._ring_off_cfg, self.param_shardings,
                                              batch_size, max_len)[:2],
        )
        return prefill_fn, self._segment_fn(batch_size, max_len), cache_sh

    def _spec_fns(self, batch_size: int, max_len: int):
        """(prefill_fn, segment_fn, cache_sharding) for speculative decoding.
        Keyed by (B, cache_len) only, so target (gamma+1-wide) and draft
        (1-wide) roles share one compiled-fn cache even when one engine
        plays both (self-draft)."""
        from deepspeed_tpu.inference.decoding import compile_decode_fns

        prefill_fn, cache_sh = self._cached_fn(
            "spec_prefill", (batch_size, max_len),
            lambda: (lambda r: (r[0], r[2]))(compile_decode_fns(
                self.mesh, self._ring_off_cfg, self.param_shardings, batch_size, max_len)),
        )
        return prefill_fn, self._segment_fn(batch_size, max_len), cache_sh

    def _generate_speculative(self, draft, tokens, max_new_tokens, temperature,
                              top_k, top_p, rng, gamma: int,
                              eos_token_id: Optional[int] = None):
        from deepspeed_tpu.inference.decoding import speculative_generate

        misses0 = self._compile_misses
        t0 = time.time()
        result = speculative_generate(
            self._ring_off_cfg, self.params, draft, tokens, max_new_tokens, temperature,
            top_k, top_p, rng, gamma, self.config.max_out_tokens,
            get_fns=self._spec_fns, eos_token_id=eos_token_id,
        )
        return self._finish_request(
            "speculative", t0, result, prompt_tokens=tokens.shape[1],
            new_tokens=max_new_tokens, batch=tokens.shape[0],
            misses_before=misses0)

    @staticmethod
    def _select(logits, temperature, top_k, rng, top_p=1.0):
        from deepspeed_tpu.inference.decoding import select_token

        return select_token(logits, temperature, top_k, rng, top_p)

    @staticmethod
    def _truncate_eos(tokens, prompt_len, eos_id):
        """Pad everything after each row's first generated EOS with EOS.

        One host transfer (read-only ``np.asarray`` view), and the writable
        copy + device re-dispatch happen ONLY for rows that actually need
        rewriting — the common no-EOS case (and the speculative path, which
        already EOS-pads) used to pay a full host copy AND a full re-upload
        of the token buffer on every call."""
        arr = np.asarray(tokens)
        gen = arr[:, prompt_len:]
        need = []
        for b in np.nonzero((gen == eos_id).any(axis=1))[0]:
            first = int(np.argmax(gen[b] == eos_id))
            if not (gen[b, first + 1:] == eos_id).all():
                need.append((b, first))
        if not need:
            return tokens
        arr = arr.copy()
        for b, first in need:
            arr[b, prompt_len + first + 1:] = eos_id
        return jnp.asarray(arr)


def init_inference(model, config=None, params=None, mesh=None, draft_model=None,
                   draft_params=None, seed: int = 0, **kwargs) -> InferenceEngine:
    """Reference: deepspeed.init_inference (deepspeed/__init__.py:251).

    ``draft_model`` (plus ``config.speculative.enabled``) attaches a smaller
    same-vocabulary model whose engine drives speculative decoding on every
    generate() call."""
    if kwargs and config is None:
        config = kwargs
    engine = InferenceEngine(model, config=config, params=params, mesh=mesh, seed=seed)
    if draft_model is not None:
        engine._draft_engine = InferenceEngine(
            draft_model,
            # the draft shares the cache format: int8 KV's memory halving
            # must cover both engines or long-context speculative serving
            # silently loses it
            config={"dtype": engine.config.dtype,
                    "kv_cache_dtype": engine.config.kv_cache_dtype,
                    "kv_tight_read": engine.config.kv_tight_read,
                    "kv_read_floor": engine.config.kv_read_floor},
            params=draft_params, mesh=mesh, seed=seed,
        )
    return engine
