"""HF-model injection policies.

Reference: ``deepspeed/module_inject/`` (replace_policy.py:20 — per-model
policies describing where qkv/mlp/ln weights live; replace_module.py —
swap-in of fused modules; auto_tp.py — shard inference TP). TPU redesign:
instead of swapping torch submodules, a policy maps an HF architecture onto
the flagship TPU transformer (models/transformer.py) — config translation +
weight-tensor relayout into the stacked-layer param tree. TP sharding then
falls out of the logical-axis annotations (the AutoTP equivalent), and the
"fused kernels" are the XLA/Pallas compiled forward.

Policies operate on numpy state dicts so torch is only touched to read
tensors.
"""

from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _np(t):
    if hasattr(t, "detach"):
        return t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


class HFPolicy:
    """Base: subclass per architecture (reference policy ABC, policy.py)."""

    ARCHITECTURES: Tuple[str, ...] = ()
    # family-specific regex partition-rule overrides, prepended to
    # parallel/partition.DEFAULT_RULES by ``partition_rules`` (the AutoTP
    # analogue: most families need nothing — conversion lands in the
    # builtin naming the default table covers; divisibility fallbacks,
    # e.g. multi-query kv heads on a wide tensor axis, are clipped
    # per-weight at placement, not here)
    tp_rules: Tuple = ()

    @classmethod
    def matches(cls, hf_config) -> bool:
        archs = getattr(hf_config, "architectures", None) or []
        mt = getattr(hf_config, "model_type", "")
        return any(a in cls.ARCHITECTURES for a in archs) or mt in cls.ARCHITECTURES

    def config(self, hf_config) -> TransformerConfig:
        raise NotImplementedError

    def params(self, state: Dict[str, Any], cfg: TransformerConfig) -> Dict:
        raise NotImplementedError


class GPT2Policy(HFPolicy):
    """reference: HFGPT2LayerPolicy (module_inject/containers/gpt2.py)."""

    ARCHITECTURES = ("GPT2LMHeadModel", "gpt2")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",
            tie_embeddings=True,
            use_bias=True,
            norm_eps=hf_config.layer_norm_epsilon,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def stack(fmt, slicer=None):
            mats = [g(fmt.format(i)) for i in range(L)]
            if slicer is not None:
                mats = [slicer(m) for m in mats]
            return np.stack(mats)

        # Conv1D stores (in, out): y = x @ W + b — already our orientation
        params = {
            "embed": {"tok": g("wte.weight"), "pos": g("wpe.weight")},
            "layers": {
                "attn": {
                    "wq": stack("h.{}.attn.c_attn.weight", lambda m: m[:, :D]),
                    "wk": stack("h.{}.attn.c_attn.weight", lambda m: m[:, D:2 * D]),
                    "wv": stack("h.{}.attn.c_attn.weight", lambda m: m[:, 2 * D:]),
                    "wo": stack("h.{}.attn.c_proj.weight"),
                    "bq": stack("h.{}.attn.c_attn.bias", lambda b: b[:D]),
                    "bk": stack("h.{}.attn.c_attn.bias", lambda b: b[D:2 * D]),
                    "bv": stack("h.{}.attn.c_attn.bias", lambda b: b[2 * D:]),
                    "bo": stack("h.{}.attn.c_proj.bias"),
                },
                "mlp": {
                    "wi": stack("h.{}.mlp.c_fc.weight"),
                    "wo": stack("h.{}.mlp.c_proj.weight"),
                    "bi": stack("h.{}.mlp.c_fc.bias"),
                    "bo": stack("h.{}.mlp.c_proj.bias"),
                },
                "ln1": {"scale": stack("h.{}.ln_1.weight"), "bias": stack("h.{}.ln_1.bias")},
                "ln2": {"scale": stack("h.{}.ln_2.weight"), "bias": stack("h.{}.ln_2.bias")},
            },
            "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        }
        return params


class GPTNeoPolicy(HFPolicy):
    """reference: HFGPTNEOLayerPolicy (module_inject/containers/gptneo.py)
    — GPT-2-shaped stack with separate (bias-free) q/k/v Linears, UNSCALED
    attention logits, and global/local attention alternation (local layers
    attend only the last ``window_size`` positions; the per-layer window
    rides the model's ``local_attn_windows``)."""

    ARCHITECTURES = ("GPTNeoForCausalLM", "GPTNeoModel", "gpt_neo")

    def config(self, hf_config) -> TransformerConfig:
        window = getattr(hf_config, "window_size", 256)
        layers = getattr(hf_config, "attention_layers", None)
        if layers is None:
            layers = ["global"] * hf_config.num_layers
        windows = tuple(window if kind == "local" else 0 for kind in layers)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_layers,
            num_heads=hf_config.num_heads,
            ffn_hidden_size=getattr(hf_config, "intermediate_size", None) or 4 * hf_config.hidden_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",  # gelu_new == tanh approximation (our default)
            tie_embeddings=True,
            use_bias=True,
            norm_eps=hf_config.layer_norm_epsilon,
            attn_scale=1.0,  # GPT-Neo does not scale q@k^T
            local_attn_windows=windows if any(windows) else None,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        zeros_b = np.zeros((L, D), np.float32)  # q/k/v Linears carry no bias
        params = {
            "embed": {"tok": g("wte.weight"), "pos": g("wpe.weight")},
            "layers": {
                "attn": {
                    "wq": stackT("h.{}.attn.attention.q_proj.weight"),
                    "wk": stackT("h.{}.attn.attention.k_proj.weight"),
                    "wv": stackT("h.{}.attn.attention.v_proj.weight"),
                    "wo": stackT("h.{}.attn.attention.out_proj.weight"),
                    "bq": zeros_b, "bk": zeros_b.copy(), "bv": zeros_b.copy(),
                    "bo": stackB("h.{}.attn.attention.out_proj.bias"),
                },
                "mlp": {
                    "wi": stackT("h.{}.mlp.c_fc.weight"),
                    "wo": stackT("h.{}.mlp.c_proj.weight"),
                    "bi": stackB("h.{}.mlp.c_fc.bias"),
                    "bo": stackB("h.{}.mlp.c_proj.bias"),
                },
                "ln1": {"scale": stackB("h.{}.ln_1.weight"), "bias": stackB("h.{}.ln_1.bias")},
                "ln2": {"scale": stackB("h.{}.ln_2.weight"), "bias": stackB("h.{}.ln_2.bias")},
            },
            "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        }
        return params


class LlamaPolicy(HFPolicy):
    """reference: the Megatron/LLaMA-family container lineage (v0.9.1
    predates llama support; mapping follows the same policy pattern)."""

    ARCHITECTURES = ("LlamaForCausalLM", "llama", "MistralForCausalLM", "mistral")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            ffn_hidden_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="rope",
            norm_type="rmsnorm",
            activation="silu_glu",
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
            use_bias=False,
            norm_eps=hf_config.rms_norm_eps,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            # the flash kernel is the TPU-preferred exact attention (bench
            # self-tune winner) and the gate for the tile-pruned window
            # band + rolling KV cache below
            attn_impl="pallas",
            # Mistral: uniform sliding window (HF `sliding_window`) — a
            # static uniform window rides the tile-pruned flash band
            # kernel during training/prefill
            local_attn_windows=(
                (int(hf_config.sliding_window),) * hf_config.num_hidden_layers
                if getattr(hf_config, "sliding_window", None) else None),
        )

    def params(self, state, cfg) -> Dict:
        L = cfg.num_layers
        pre = "model." if any(k.startswith("model.") for k in state) else ""

        def g(name):
            return _np(state[pre + name] if pre + name in state else state[name])

        def stackT(fmt):
            # torch Linear stores (out, in); ours is (in, out)
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        params = {
            "embed": {"tok": g("embed_tokens.weight")},
            "layers": {
                "attn": {
                    "wq": stackT("layers.{}.self_attn.q_proj.weight"),
                    "wk": stackT("layers.{}.self_attn.k_proj.weight"),
                    "wv": stackT("layers.{}.self_attn.v_proj.weight"),
                    "wo": stackT("layers.{}.self_attn.o_proj.weight"),
                },
                "mlp": {
                    "wg": stackT("layers.{}.mlp.gate_proj.weight"),
                    "wi": stackT("layers.{}.mlp.up_proj.weight"),
                    "wo": stackT("layers.{}.mlp.down_proj.weight"),
                },
                "ln1": {"scale": np.stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)])},
                "ln2": {"scale": np.stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)])},
            },
            "final_norm": {"scale": g("norm.weight")},
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = {"w": _np(state["lm_head.weight"]).T}
        return params


class OPTPolicy(HFPolicy):
    """reference: HFOPTLayerPolicy (module_inject/containers/opt.py)."""

    ARCHITECTURES = ("OPTForCausalLM", "opt")

    def config(self, hf_config) -> TransformerConfig:
        if getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size) != hf_config.hidden_size:
            raise NotImplementedError("OPT word_embed_proj_dim != hidden_size (project_in/out) unsupported")
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            ffn_hidden_size=hf_config.ffn_dim,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            # facebook/opt-* use relu; galactica ships OPT arch with gelu
            activation=getattr(hf_config, "activation_function", "relu"),
            # OPT-350m ships do_layer_norm_before=False (post-LN)
            norm_position="pre" if getattr(hf_config, "do_layer_norm_before", True) else "post",
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            use_bias=True,
        )

    def params(self, state, cfg) -> Dict:
        L = cfg.num_layers
        pre = "model.decoder." if any(k.startswith("model.decoder.") for k in state) else "decoder."

        def g(name):
            return _np(state[pre + name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        params = {
            "embed": {
                "tok": g("embed_tokens.weight"),
                # OPT's learned positions are queried at position+2
                # (modeling_opt OPTLearnedPositionalEmbedding offset); baking
                # the offset into the table keeps the model's 0-based lookup
                "pos": g("embed_positions.weight")[2:],
            },
            "layers": {
                "attn": {
                    "wq": stackT("layers.{}.self_attn.q_proj.weight"),
                    "wk": stackT("layers.{}.self_attn.k_proj.weight"),
                    "wv": stackT("layers.{}.self_attn.v_proj.weight"),
                    "wo": stackT("layers.{}.self_attn.out_proj.weight"),
                    "bq": stackB("layers.{}.self_attn.q_proj.bias"),
                    "bk": stackB("layers.{}.self_attn.k_proj.bias"),
                    "bv": stackB("layers.{}.self_attn.v_proj.bias"),
                    "bo": stackB("layers.{}.self_attn.out_proj.bias"),
                },
                "mlp": {
                    "wi": stackT("layers.{}.fc1.weight"),
                    "wo": stackT("layers.{}.fc2.weight"),
                    "bi": stackB("layers.{}.fc1.bias"),
                    "bo": stackB("layers.{}.fc2.bias"),
                },
                "ln1": {
                    "scale": stackB("layers.{}.self_attn_layer_norm.weight"),
                    "bias": stackB("layers.{}.self_attn_layer_norm.bias"),
                },
                "ln2": {
                    "scale": stackB("layers.{}.final_layer_norm.weight"),
                    "bias": stackB("layers.{}.final_layer_norm.bias"),
                },
            },
        }
        if cfg.norm_position == "pre":
            params["final_norm"] = {"scale": g("final_layer_norm.weight"), "bias": g("final_layer_norm.bias")}
        else:
            D = cfg.hidden_size
            params["final_norm"] = {"scale": np.ones(D, np.float32), "bias": np.zeros(D, np.float32)}
        return params


class BloomPolicy(HFPolicy):
    """reference: BLOOMLayerPolicy (module_inject/containers/bloom.py) —
    ALiBi positions, embedding LayerNorm, per-head-interleaved fused qkv."""

    ARCHITECTURES = ("BloomForCausalLM", "BloomModel", "bloom")

    def config(self, hf_config) -> TransformerConfig:
        D = hf_config.hidden_size
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=D,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=getattr(hf_config, "seq_length", 2048),
            pos_embedding="alibi",
            norm_type="layernorm",
            activation="gelu",
            tie_embeddings=True,
            use_bias=True,
            embed_norm=True,
            norm_eps=hf_config.layer_norm_epsilon,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        nh, hd = cfg.num_heads, cfg.head_dim
        pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def qkv_w(i, which):
            # fused (3D, D) laid out per head: [h, (q|k|v), hd, D]
            w = g(f"h.{i}.self_attention.query_key_value.weight").reshape(nh, 3, hd, D)
            return w[:, which].reshape(nh * hd, D).T  # -> (D, nh*hd)

        def qkv_b(i, which):
            b = g(f"h.{i}.self_attention.query_key_value.bias").reshape(nh, 3, hd)
            return b[:, which].reshape(nh * hd)

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        params = {
            "embed": {"tok": g("word_embeddings.weight")},
            "embed_norm": {
                "scale": g("word_embeddings_layernorm.weight"),
                "bias": g("word_embeddings_layernorm.bias"),
            },
            "layers": {
                "attn": {
                    "wq": np.stack([qkv_w(i, 0) for i in range(L)]),
                    "wk": np.stack([qkv_w(i, 1) for i in range(L)]),
                    "wv": np.stack([qkv_w(i, 2) for i in range(L)]),
                    "wo": stackT("h.{}.self_attention.dense.weight"),
                    "bq": np.stack([qkv_b(i, 0) for i in range(L)]),
                    "bk": np.stack([qkv_b(i, 1) for i in range(L)]),
                    "bv": np.stack([qkv_b(i, 2) for i in range(L)]),
                    "bo": stackB("h.{}.self_attention.dense.bias"),
                },
                "mlp": {
                    "wi": stackT("h.{}.mlp.dense_h_to_4h.weight"),
                    "wo": stackT("h.{}.mlp.dense_4h_to_h.weight"),
                    "bi": stackB("h.{}.mlp.dense_h_to_4h.bias"),
                    "bo": stackB("h.{}.mlp.dense_4h_to_h.bias"),
                },
                "ln1": {
                    "scale": stackB("h.{}.input_layernorm.weight"),
                    "bias": stackB("h.{}.input_layernorm.bias"),
                },
                "ln2": {
                    "scale": stackB("h.{}.post_attention_layernorm.weight"),
                    "bias": stackB("h.{}.post_attention_layernorm.bias"),
                },
            },
            "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        }
        return params


class GPTNeoXPolicy(HFPolicy):
    """reference: GPTNEOXLayerPolicy (module_inject/containers/gptneox.py) —
    parallel residual, partial rotary (rotary_pct), fused qkv per head."""

    ARCHITECTURES = ("GPTNeoXForCausalLM", "gpt_neox")

    def config(self, hf_config) -> TransformerConfig:
        hd = hf_config.hidden_size // hf_config.num_attention_heads
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            ffn_hidden_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="rope",
            rope_dim=int(hd * getattr(hf_config, "rotary_pct", 1.0)),
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            norm_type="layernorm",
            activation="gelu",
            parallel_residual=getattr(hf_config, "use_parallel_residual", True),
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
            use_bias=True,
            norm_eps=hf_config.layer_norm_eps,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        nh, hd = cfg.num_heads, cfg.head_dim
        pre = "gpt_neox." if any(k.startswith("gpt_neox.") for k in state) else ""

        def g(name):
            return _np(state[pre + name] if pre + name in state else state[name])

        def qkv_w(i, which):
            # fused (3D, D) laid out per head: [h, (q|k|v), hd, D]
            w = g(f"layers.{i}.attention.query_key_value.weight").reshape(nh, 3, hd, D)
            return w[:, which].reshape(nh * hd, D).T

        def qkv_b(i, which):
            b = g(f"layers.{i}.attention.query_key_value.bias").reshape(nh, 3, hd)
            return b[:, which].reshape(nh * hd)

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        params = {
            "embed": {"tok": g("embed_in.weight")},
            "layers": {
                "attn": {
                    "wq": np.stack([qkv_w(i, 0) for i in range(L)]),
                    "wk": np.stack([qkv_w(i, 1) for i in range(L)]),
                    "wv": np.stack([qkv_w(i, 2) for i in range(L)]),
                    "wo": stackT("layers.{}.attention.dense.weight"),
                    "bq": np.stack([qkv_b(i, 0) for i in range(L)]),
                    "bk": np.stack([qkv_b(i, 1) for i in range(L)]),
                    "bv": np.stack([qkv_b(i, 2) for i in range(L)]),
                    "bo": stackB("layers.{}.attention.dense.bias"),
                },
                "mlp": {
                    "wi": stackT("layers.{}.mlp.dense_h_to_4h.weight"),
                    "wo": stackT("layers.{}.mlp.dense_4h_to_h.weight"),
                    "bi": stackB("layers.{}.mlp.dense_h_to_4h.bias"),
                    "bo": stackB("layers.{}.mlp.dense_4h_to_h.bias"),
                },
                "ln1": {
                    "scale": stackB("layers.{}.input_layernorm.weight"),
                    "bias": stackB("layers.{}.input_layernorm.bias"),
                },
                "ln2": {
                    "scale": stackB("layers.{}.post_attention_layernorm.weight"),
                    "bias": stackB("layers.{}.post_attention_layernorm.bias"),
                },
            },
            "final_norm": {"scale": g("final_layer_norm.weight"), "bias": g("final_layer_norm.bias")},
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = {"w": _np(state["embed_out.weight"]).T}
        return params


class GPTJPolicy(HFPolicy):
    """reference: HFGPTJLayerPolicy (module_inject/containers/gptj.py) —
    parallel residual with a single shared LN, interleaved partial rotary,
    bias-free attention projections, biased lm_head."""

    ARCHITECTURES = ("GPTJForCausalLM", "gptj")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions,
            pos_embedding="rope",
            rope_dim=getattr(hf_config, "rotary_dim", None),
            rope_interleaved=True,
            norm_type="layernorm",
            activation="gelu",
            parallel_residual=True,
            shared_ln=True,
            tie_embeddings=False,
            lm_head_bias=True,
            use_bias=True,  # mlp/ln have biases; attn biases are zero-filled
            norm_eps=hf_config.layer_norm_epsilon,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""

        def g(name):
            return _np(state[pre + name] if pre + name in state else state[name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        zeros_b = np.zeros((L, D), np.float32)
        params = {
            "embed": {"tok": g("wte.weight")},
            "layers": {
                "attn": {
                    "wq": stackT("h.{}.attn.q_proj.weight"),
                    "wk": stackT("h.{}.attn.k_proj.weight"),
                    "wv": stackT("h.{}.attn.v_proj.weight"),
                    "wo": stackT("h.{}.attn.out_proj.weight"),
                    "bq": zeros_b,
                    "bk": zeros_b,
                    "bv": zeros_b,
                    "bo": zeros_b,
                },
                "mlp": {
                    "wi": stackT("h.{}.mlp.fc_in.weight"),
                    "wo": stackT("h.{}.mlp.fc_out.weight"),
                    "bi": stackB("h.{}.mlp.fc_in.bias"),
                    "bo": stackB("h.{}.mlp.fc_out.bias"),
                },
                "ln1": {"scale": stackB("h.{}.ln_1.weight"), "bias": stackB("h.{}.ln_1.bias")},
                # shared_ln: ln2 unused; identity keeps the param tree uniform
                "ln2": {"scale": np.ones((L, D), np.float32), "bias": zeros_b},
            },
            "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
            "lm_head": {"w": _np(state["lm_head.weight"]).T, "b": _np(state["lm_head.bias"])},
        }
        return params


class BertPolicy(HFPolicy):
    """reference: HFBertLayerPolicy (module_inject/containers/bert.py) —
    post-LN encoder with token-type embeddings + embedding LayerNorm.
    Produces the encoder stack; use models.transformer.encode() for
    last-hidden-state outputs (the reference injects encoder layers only)."""

    ARCHITECTURES = ("BertModel", "BertForMaskedLM", "BertForSequenceClassification", "bert")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            ffn_hidden_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",
            norm_position="post",
            causal=False,
            type_vocab_size=getattr(hf_config, "type_vocab_size", 2),
            embed_norm=True,
            tie_embeddings=True,
            use_bias=True,
            norm_eps=hf_config.layer_norm_eps,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "bert." if any(k.startswith("bert.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        params = {
            "embed": {
                "tok": g("embeddings.word_embeddings.weight"),
                "pos": g("embeddings.position_embeddings.weight"),
                "type": g("embeddings.token_type_embeddings.weight"),
            },
            "embed_norm": {
                "scale": g("embeddings.LayerNorm.weight"),
                "bias": g("embeddings.LayerNorm.bias"),
            },
            "layers": {
                "attn": {
                    "wq": stackT("encoder.layer.{}.attention.self.query.weight"),
                    "wk": stackT("encoder.layer.{}.attention.self.key.weight"),
                    "wv": stackT("encoder.layer.{}.attention.self.value.weight"),
                    "wo": stackT("encoder.layer.{}.attention.output.dense.weight"),
                    "bq": stackB("encoder.layer.{}.attention.self.query.bias"),
                    "bk": stackB("encoder.layer.{}.attention.self.key.bias"),
                    "bv": stackB("encoder.layer.{}.attention.self.value.bias"),
                    "bo": stackB("encoder.layer.{}.attention.output.dense.bias"),
                },
                "mlp": {
                    "wi": stackT("encoder.layer.{}.intermediate.dense.weight"),
                    "wo": stackT("encoder.layer.{}.output.dense.weight"),
                    "bi": stackB("encoder.layer.{}.intermediate.dense.bias"),
                    "bo": stackB("encoder.layer.{}.output.dense.bias"),
                },
                # post-LN: ln1 = attention.output.LayerNorm, ln2 = output.LayerNorm
                "ln1": {
                    "scale": stackB("encoder.layer.{}.attention.output.LayerNorm.weight"),
                    "bias": stackB("encoder.layer.{}.attention.output.LayerNorm.bias"),
                },
                "ln2": {
                    "scale": stackB("encoder.layer.{}.output.LayerNorm.weight"),
                    "bias": stackB("encoder.layer.{}.output.LayerNorm.bias"),
                },
            },
            # unused at post-LN (forward skips final norm); identity for shape
            "final_norm": {"scale": np.ones(D, np.float32), "bias": np.zeros(D, np.float32)},
        }
        # BertForMaskedLM head: cls.predictions.transform (dense+gelu+LN)
        # + the decoder bias, applied by models/transformer._vocab_head —
        # without it MLM logits deviate from the HF checkpoint
        if "cls.predictions.transform.dense.weight" in state:
            params["mlm_head"] = {
                "w": _np(state["cls.predictions.transform.dense.weight"]).T,
                "b": _np(state["cls.predictions.transform.dense.bias"]),
                "ln_scale": _np(state["cls.predictions.transform.LayerNorm.weight"]),
                "ln_bias": _np(state["cls.predictions.transform.LayerNorm.bias"]),
                "proj_bias": _np(state["cls.predictions.bias"]),
            }
        return params


class DistilBertPolicy(HFPolicy):
    """reference: HFDistilBertLayerPolicy (module_inject/containers/
    distil_bert.py) — BERT-family post-LN encoder without token types;
    torch Linear weights are (out, in) so every matmul transposes."""

    ARCHITECTURES = ("DistilBertModel", "DistilBertForMaskedLM",
                     "DistilBertForSequenceClassification", "distilbert")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.dim,
            num_layers=hf_config.n_layers,
            num_heads=hf_config.n_heads,
            ffn_hidden_size=hf_config.hidden_dim,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",
            norm_position="post",
            causal=False,
            type_vocab_size=0,
            embed_norm=True,
            tie_embeddings=True,
            use_bias=True,
            norm_eps=1e-12,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "distilbert." if any(k.startswith("distilbert.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        params = {
            "embed": {
                "tok": g("embeddings.word_embeddings.weight"),
                "pos": g("embeddings.position_embeddings.weight"),
            },
            "embed_norm": {
                "scale": g("embeddings.LayerNorm.weight"),
                "bias": g("embeddings.LayerNorm.bias"),
            },
            "layers": {
                "attn": {
                    "wq": stackT("transformer.layer.{}.attention.q_lin.weight"),
                    "wk": stackT("transformer.layer.{}.attention.k_lin.weight"),
                    "wv": stackT("transformer.layer.{}.attention.v_lin.weight"),
                    "wo": stackT("transformer.layer.{}.attention.out_lin.weight"),
                    "bq": stackB("transformer.layer.{}.attention.q_lin.bias"),
                    "bk": stackB("transformer.layer.{}.attention.k_lin.bias"),
                    "bv": stackB("transformer.layer.{}.attention.v_lin.bias"),
                    "bo": stackB("transformer.layer.{}.attention.out_lin.bias"),
                },
                "mlp": {
                    "wi": stackT("transformer.layer.{}.ffn.lin1.weight"),
                    "wo": stackT("transformer.layer.{}.ffn.lin2.weight"),
                    "bi": stackB("transformer.layer.{}.ffn.lin1.bias"),
                    "bo": stackB("transformer.layer.{}.ffn.lin2.bias"),
                },
                # post-LN: ln1 after attention residual, ln2 after ffn residual
                "ln1": {
                    "scale": stackB("transformer.layer.{}.sa_layer_norm.weight"),
                    "bias": stackB("transformer.layer.{}.sa_layer_norm.bias"),
                },
                "ln2": {
                    "scale": stackB("transformer.layer.{}.output_layer_norm.weight"),
                    "bias": stackB("transformer.layer.{}.output_layer_norm.bias"),
                },
            },
            "final_norm": {"scale": np.ones(D, np.float32), "bias": np.zeros(D, np.float32)},
        }
        # DistilBertForMaskedLM head: vocab_transform (dense+gelu) +
        # vocab_layer_norm + the vocab_projector bias (the projector weight
        # is tied to the embedding); see models/transformer._vocab_head
        if "vocab_transform.weight" in state:
            params["mlm_head"] = {
                "w": _np(state["vocab_transform.weight"]).T,
                "b": _np(state["vocab_transform.bias"]),
                "ln_scale": _np(state["vocab_layer_norm.weight"]),
                "ln_bias": _np(state["vocab_layer_norm.bias"]),
                "proj_bias": _np(state["vocab_projector.bias"]),
            }
        return params


class MegatronGPTPolicy(HFPolicy):
    """reference: MegatronLayerPolicy (module_inject/containers/megatron_gpt.py)
    — Megatron-LM GPT checkpoints with FUSED query_key_value projections.
    Both row layouts are handled: checkpoint_version >= 2 stores per-head
    [q;k;v] blocks, version 0 stores [all-q; all-k; all-v] (the reference
    splits via megatron's fix_query_key_value_ordering)."""

    ARCHITECTURES = ("MegatronGPT2LMHeadModel", "megatron-gpt2", "megatron_gpt2")

    def __init__(self, checkpoint_version: int = 2):
        self.checkpoint_version = checkpoint_version

    def config(self, hf_config) -> TransformerConfig:
        # the dispatch path (policy_for) constructs with no arguments, so a
        # checkpoint that carries its version must win over the default —
        # version 0 split with the v2 layout scrambles heads silently
        # (both layouts have identical shapes, so no error would surface)
        if hasattr(hf_config, "checkpoint_version"):
            self.checkpoint_version = int(hf_config.checkpoint_version)
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=getattr(hf_config, "hidden_size", getattr(hf_config, "n_embd", None)),
            num_layers=getattr(hf_config, "num_layers", getattr(hf_config, "n_layer", None)),
            num_heads=getattr(hf_config, "num_attention_heads", getattr(hf_config, "n_head", None)),
            max_seq_len=getattr(hf_config, "max_position_embeddings", 1024),
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",
            tie_embeddings=True,
            use_bias=True,
        )

    def _split_qkv(self, w, nh, hd):
        """(D, 3*D) fused matrix -> three (D, D) matrices, by row layout."""
        if self.checkpoint_version >= 2:
            # columns grouped per head: [h0q h0k h0v h1q ...]
            cols = w.reshape(w.shape[0], nh, 3, hd)
            return (cols[:, :, 0].reshape(w.shape[0], nh * hd),
                    cols[:, :, 1].reshape(w.shape[0], nh * hd),
                    cols[:, :, 2].reshape(w.shape[0], nh * hd))
        D = nh * hd
        return w[:, :D], w[:, D:2 * D], w[:, 2 * D:]

    def _split_qkv_bias(self, b, nh, hd):
        if self.checkpoint_version >= 2:
            cols = b.reshape(nh, 3, hd)
            return cols[:, 0].ravel(), cols[:, 1].ravel(), cols[:, 2].ravel()
        D = nh * hd
        return b[:D], b[D:2 * D], b[2 * D:]

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        nh, hd = cfg.num_heads, cfg.head_dim
        pre = ""
        for cand in ("model.language_model.", "language_model.", ""):
            if any(k.startswith(cand + "embedding") for k in state):
                pre = cand
                break

        def g(name):
            return _np(state[pre + name])

        qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
        for i in range(L):
            # megatron Linear stores (out, in): transpose to (in, out) first
            w = g(f"transformer.layers.{i}.attention.query_key_value.weight").T
            b = g(f"transformer.layers.{i}.attention.query_key_value.bias")
            wq, wk, wv = self._split_qkv(w, nh, hd)
            bq, bk, bv = self._split_qkv_bias(b, nh, hd)
            qs.append(wq), ks.append(wk), vs.append(wv)
            bqs.append(bq), bks.append(bk), bvs.append(bv)

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        return {
            "embed": {
                "tok": g("embedding.word_embeddings.weight"),
                "pos": g("embedding.position_embeddings.weight"),
            },
            "layers": {
                "attn": {
                    "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
                    "wo": stackT("transformer.layers.{}.attention.dense.weight"),
                    "bq": np.stack(bqs), "bk": np.stack(bks), "bv": np.stack(bvs),
                    "bo": stackB("transformer.layers.{}.attention.dense.bias"),
                },
                "mlp": {
                    "wi": stackT("transformer.layers.{}.mlp.dense_h_to_4h.weight"),
                    "wo": stackT("transformer.layers.{}.mlp.dense_4h_to_h.weight"),
                    "bi": stackB("transformer.layers.{}.mlp.dense_h_to_4h.bias"),
                    "bo": stackB("transformer.layers.{}.mlp.dense_4h_to_h.bias"),
                },
                "ln1": {
                    "scale": stackB("transformer.layers.{}.input_layernorm.weight"),
                    "bias": stackB("transformer.layers.{}.input_layernorm.bias"),
                },
                "ln2": {
                    "scale": stackB("transformer.layers.{}.post_attention_layernorm.weight"),
                    "bias": stackB("transformer.layers.{}.post_attention_layernorm.bias"),
                },
            },
            "final_norm": {
                "scale": g("transformer.final_layernorm.weight"),
                "bias": g("transformer.final_layernorm.bias"),
            },
        }


class CLIPTextPolicy(HFPolicy):
    """reference: HFCLIPLayerPolicy (module_inject/containers/clip.py) —
    the CLIP TEXT encoder (pre-LN, causal attention, quick_gelu). The
    vision tower's conv patch-embedding is outside the injected layer set
    in the reference too; its transformer layers share this shape."""

    ARCHITECTURES = ("CLIPTextModel", "CLIPModel", "clip", "clip_text_model")

    def config(self, hf_config) -> TransformerConfig:
        # CLIPModel configs nest the text tower under .text_config
        tc = getattr(hf_config, "text_config", hf_config)
        return TransformerConfig(
            vocab_size=tc.vocab_size,
            hidden_size=tc.hidden_size,
            num_layers=tc.num_hidden_layers,
            num_heads=tc.num_attention_heads,
            ffn_hidden_size=tc.intermediate_size,
            max_seq_len=tc.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="quick_gelu" if getattr(tc, "hidden_act", "quick_gelu") == "quick_gelu" else "gelu",
            norm_position="pre",
            causal=True,  # CLIP text attention is causal
            tie_embeddings=True,
            use_bias=True,
            norm_eps=tc.layer_norm_eps,
        )

    def params(self, state, cfg) -> Dict:
        L = cfg.num_layers
        pre = ""
        for cand in ("text_model.", "model.text_model.", ""):
            if any(k.startswith(cand + "embeddings") for k in state):
                pre = cand
                break

        def g(name):
            return _np(state[pre + name])

        def stackT(fmt):
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        def stackB(fmt):
            return np.stack([g(fmt.format(i)) for i in range(L)])

        return {
            "embed": {
                "tok": g("embeddings.token_embedding.weight"),
                "pos": g("embeddings.position_embedding.weight"),
            },
            "layers": {
                "attn": {
                    "wq": stackT("encoder.layers.{}.self_attn.q_proj.weight"),
                    "wk": stackT("encoder.layers.{}.self_attn.k_proj.weight"),
                    "wv": stackT("encoder.layers.{}.self_attn.v_proj.weight"),
                    "wo": stackT("encoder.layers.{}.self_attn.out_proj.weight"),
                    "bq": stackB("encoder.layers.{}.self_attn.q_proj.bias"),
                    "bk": stackB("encoder.layers.{}.self_attn.k_proj.bias"),
                    "bv": stackB("encoder.layers.{}.self_attn.v_proj.bias"),
                    "bo": stackB("encoder.layers.{}.self_attn.out_proj.bias"),
                },
                "mlp": {
                    "wi": stackT("encoder.layers.{}.mlp.fc1.weight"),
                    "wo": stackT("encoder.layers.{}.mlp.fc2.weight"),
                    "bi": stackB("encoder.layers.{}.mlp.fc1.bias"),
                    "bo": stackB("encoder.layers.{}.mlp.fc2.bias"),
                },
                "ln1": {
                    "scale": stackB("encoder.layers.{}.layer_norm1.weight"),
                    "bias": stackB("encoder.layers.{}.layer_norm1.bias"),
                },
                "ln2": {
                    "scale": stackB("encoder.layers.{}.layer_norm2.weight"),
                    "bias": stackB("encoder.layers.{}.layer_norm2.bias"),
                },
            },
            "final_norm": {
                "scale": g("final_layer_norm.weight"),
                "bias": g("final_layer_norm.bias"),
            },
        }


POLICIES = [GPT2Policy, LlamaPolicy, OPTPolicy, BloomPolicy, GPTNeoXPolicy, GPTJPolicy,
            GPTNeoPolicy, BertPolicy, DistilBertPolicy, MegatronGPTPolicy, CLIPTextPolicy]


def policy_for(hf_config) -> HFPolicy:
    for p in POLICIES:
        if p.matches(hf_config):
            return p()
    raise ValueError(
        f"no injection policy for architecture {getattr(hf_config, 'architectures', None)} "
        f"(model_type={getattr(hf_config, 'model_type', '?')}); available: "
        f"{[p.__name__ for p in POLICIES]}"
    )


def config_from_hf(hf_config) -> TransformerConfig:
    return policy_for(hf_config).config(hf_config)


def partition_rules(hf_config=None):
    """Regex partition-rule table for a converted model's param tree —
    the inference-TP half of module_inject on a mesh backend (reference:
    auto_tp.py's column/row split decisions). Every policy relayouts into
    the builtin transformer naming, so the model-family defaults
    (parallel/partition.DEFAULT_RULES: heads/mlp/vocab on ``tensor``)
    serve all architectures; a policy with family-specific needs prepends
    its ``tp_rules`` (first match wins). Pass the result — or your own
    overrides — as ``InferenceConfig.mesh.rules``."""
    from deepspeed_tpu.parallel.partition import DEFAULT_RULES

    rules = ()
    if hf_config is not None:
        rules = tuple(policy_for(hf_config).tp_rules)
    return rules + tuple(DEFAULT_RULES)


def convert_hf_model(hf_model) -> Tuple[TransformerConfig, Dict]:
    """(reference: replace_transformer_layer) HF torch model -> (cfg, params).

    Architectures without an explicit policy fall back to the AutoTP
    name/shape-heuristic policy (reference module_inject/auto_tp.py)."""
    state = dict(hf_model.state_dict())
    try:
        policy = policy_for(hf_model.config)
    except ValueError:
        from deepspeed_tpu.module_inject.auto_tp import auto_policy

        policy = auto_policy(state)
        logger.info(
            f"no explicit policy for {getattr(hf_model.config, 'model_type', '?')}; "
            "using the AutoTP fallback"
        )
    cfg = policy.config(hf_model.config)
    params = policy.params(state, cfg)
    logger.info(f"converted HF {hf_model.config.model_type} -> TransformerConfig({cfg.num_params():,} params)")
    return cfg, params
