"""HF-model injection policies.

Reference: ``deepspeed/module_inject/`` (replace_policy.py:20 — per-model
policies describing where qkv/mlp/ln weights live; replace_module.py —
swap-in of fused modules; auto_tp.py — shard inference TP). TPU redesign:
instead of swapping torch submodules, a policy maps an HF architecture onto
the flagship TPU transformer (models/transformer.py) — config translation +
weight-tensor relayout into the stacked-layer param tree. TP sharding then
falls out of the logical-axis annotations (the AutoTP equivalent), and the
"fused kernels" are the XLA/Pallas compiled forward.

Policies operate on numpy state dicts so torch is only touched to read
tensors.
"""

from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _np(t):
    if hasattr(t, "detach"):
        return t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


class HFPolicy:
    """Base: subclass per architecture (reference policy ABC, policy.py)."""

    ARCHITECTURES: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, hf_config) -> bool:
        archs = getattr(hf_config, "architectures", None) or []
        mt = getattr(hf_config, "model_type", "")
        return any(a in cls.ARCHITECTURES for a in archs) or mt in cls.ARCHITECTURES

    def config(self, hf_config) -> TransformerConfig:
        raise NotImplementedError

    def params(self, state: Dict[str, Any], cfg: TransformerConfig) -> Dict:
        raise NotImplementedError


class GPT2Policy(HFPolicy):
    """reference: HFGPT2LayerPolicy (module_inject/containers/gpt2.py)."""

    ARCHITECTURES = ("GPT2LMHeadModel", "gpt2")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            max_seq_len=hf_config.n_positions,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",
            tie_embeddings=True,
            use_bias=True,
            norm_eps=hf_config.layer_norm_epsilon,
        )

    def params(self, state, cfg) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""

        def g(name):
            return _np(state[pre + name])

        def stack(fmt, slicer=None):
            mats = [g(fmt.format(i)) for i in range(L)]
            if slicer is not None:
                mats = [slicer(m) for m in mats]
            return np.stack(mats)

        # Conv1D stores (in, out): y = x @ W + b — already our orientation
        params = {
            "embed": {"tok": g("wte.weight"), "pos": g("wpe.weight")},
            "layers": {
                "attn": {
                    "wq": stack("h.{}.attn.c_attn.weight", lambda m: m[:, :D]),
                    "wk": stack("h.{}.attn.c_attn.weight", lambda m: m[:, D:2 * D]),
                    "wv": stack("h.{}.attn.c_attn.weight", lambda m: m[:, 2 * D:]),
                    "wo": stack("h.{}.attn.c_proj.weight"),
                    "bq": stack("h.{}.attn.c_attn.bias", lambda b: b[:D]),
                    "bk": stack("h.{}.attn.c_attn.bias", lambda b: b[D:2 * D]),
                    "bv": stack("h.{}.attn.c_attn.bias", lambda b: b[2 * D:]),
                    "bo": stack("h.{}.attn.c_proj.bias"),
                },
                "mlp": {
                    "wi": stack("h.{}.mlp.c_fc.weight"),
                    "wo": stack("h.{}.mlp.c_proj.weight"),
                    "bi": stack("h.{}.mlp.c_fc.bias"),
                    "bo": stack("h.{}.mlp.c_proj.bias"),
                },
                "ln1": {"scale": stack("h.{}.ln_1.weight"), "bias": stack("h.{}.ln_1.bias")},
                "ln2": {"scale": stack("h.{}.ln_2.weight"), "bias": stack("h.{}.ln_2.bias")},
            },
            "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        }
        return params


class LlamaPolicy(HFPolicy):
    """reference: the Megatron/LLaMA-family container lineage (v0.9.1
    predates llama support; mapping follows the same policy pattern)."""

    ARCHITECTURES = ("LlamaForCausalLM", "llama", "MistralForCausalLM", "mistral")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            ffn_hidden_size=hf_config.intermediate_size,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="rope",
            norm_type="rmsnorm",
            activation="silu_glu",
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
            use_bias=False,
            norm_eps=hf_config.rms_norm_eps,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        )

    def params(self, state, cfg) -> Dict:
        L = cfg.num_layers
        pre = "model." if any(k.startswith("model.") for k in state) else ""

        def g(name):
            return _np(state[pre + name] if pre + name in state else state[name])

        def stackT(fmt):
            # torch Linear stores (out, in); ours is (in, out)
            return np.stack([g(fmt.format(i)).T for i in range(L)])

        params = {
            "embed": {"tok": g("embed_tokens.weight")},
            "layers": {
                "attn": {
                    "wq": stackT("layers.{}.self_attn.q_proj.weight"),
                    "wk": stackT("layers.{}.self_attn.k_proj.weight"),
                    "wv": stackT("layers.{}.self_attn.v_proj.weight"),
                    "wo": stackT("layers.{}.self_attn.o_proj.weight"),
                },
                "mlp": {
                    "wg": stackT("layers.{}.mlp.gate_proj.weight"),
                    "wi": stackT("layers.{}.mlp.up_proj.weight"),
                    "wo": stackT("layers.{}.mlp.down_proj.weight"),
                },
                "ln1": {"scale": np.stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)])},
                "ln2": {"scale": np.stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)])},
            },
            "final_norm": {"scale": g("norm.weight")},
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = {"w": _np(state["lm_head.weight"]).T}
        return params


class OPTPolicy(HFPolicy):
    """reference: HFOPTLayerPolicy (module_inject/containers/opt.py)."""

    ARCHITECTURES = ("OPTForCausalLM", "opt")

    def config(self, hf_config) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            ffn_hidden_size=hf_config.ffn_dim,
            max_seq_len=hf_config.max_position_embeddings,
            pos_embedding="learned",
            norm_type="layernorm",
            activation="gelu",  # OPT uses relu; gelu kept for shared kernel — see note
            tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
            use_bias=True,
        )

    def params(self, state, cfg) -> Dict:
        raise NotImplementedError(
            "OPT weight relayout requires relu activation + offset position "
            "embeddings; config translation is provided, weights land with "
            "the activation-registry extension."
        )


POLICIES = [GPT2Policy, LlamaPolicy, OPTPolicy]


def policy_for(hf_config) -> HFPolicy:
    for p in POLICIES:
        if p.matches(hf_config):
            return p()
    raise ValueError(
        f"no injection policy for architecture {getattr(hf_config, 'architectures', None)} "
        f"(model_type={getattr(hf_config, 'model_type', '?')}); available: "
        f"{[p.__name__ for p in POLICIES]}"
    )


def config_from_hf(hf_config) -> TransformerConfig:
    return policy_for(hf_config).config(hf_config)


def convert_hf_model(hf_model) -> Tuple[TransformerConfig, Dict]:
    """(reference: replace_transformer_layer) HF torch model -> (cfg, params)."""
    policy = policy_for(hf_model.config)
    cfg = policy.config(hf_model.config)
    state = dict(hf_model.state_dict())
    params = policy.params(state, cfg)
    logger.info(f"converted HF {hf_model.config.model_type} -> TransformerConfig({cfg.num_params():,} params)")
    return cfg, params
