"""Export trained parameters BACK to HF state-dict format — the inverse of
the injection policies' HF->ours mapping (policies.py), so a model trained
on TPU can be published/served as a standard HF checkpoint.

Reference parity note: v0.9.1 converts HF checkpoints IN (module_inject/
load_checkpoint.py) and exports its own ZeRO formats; the HF round trip is
the TPU-stack equivalent of handing a trained model to the torch ecosystem.

Supported families mirror the flagship import policies: GPT-2 and
Llama/Mistral. Round-trip tested (convert -> export -> strict
load_state_dict -> logits parity)."""

from typing import Dict

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig


def _np(x) -> np.ndarray:
    return np.asarray(x)


def export_hf_state_dict(params: Dict, cfg: TransformerConfig,
                         architecture: str) -> Dict[str, np.ndarray]:
    """params: the model tree (engine.params / InferenceEngine.params —
    layers stacked (L, ...)); returns {hf_param_name: np.ndarray} matching
    the given architecture family ("gpt2" | "llama" | "mistral")."""
    arch = architecture.lower()
    if arch in ("gpt2", "gpt2lmheadmodel"):
        return _export_gpt2(params, cfg)
    if arch in ("llama", "llamaforcausallm", "mistral", "mistralforcausallm"):
        return _export_llama(params, cfg)
    raise NotImplementedError(
        f"HF export supports gpt2 and llama/mistral; got {architecture!r}")


def _export_gpt2(params: Dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    L = cfg.num_layers
    layers = params["layers"]
    out = {
        "transformer.wte.weight": _np(params["embed"]["tok"]),
        "transformer.wpe.weight": _np(params["embed"]["pos"]),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"]),
        # tied head: HF GPT2LMHeadModel's state_dict carries the shared
        # tensor under both names
        "lm_head.weight": _np(params["embed"]["tok"]),
    }
    attn, mlp = layers["attn"], layers["mlp"]
    for i in range(L):
        p = f"transformer.h.{i}."
        # Conv1D stores (in, out) = our orientation; qkv re-concatenate
        out[p + "attn.c_attn.weight"] = np.concatenate(
            [_np(attn["wq"][i]), _np(attn["wk"][i]), _np(attn["wv"][i])], axis=1)
        out[p + "attn.c_attn.bias"] = np.concatenate(
            [_np(attn["bq"][i]), _np(attn["bk"][i]), _np(attn["bv"][i])])
        out[p + "attn.c_proj.weight"] = _np(attn["wo"][i])
        out[p + "attn.c_proj.bias"] = _np(attn["bo"][i])
        out[p + "mlp.c_fc.weight"] = _np(mlp["wi"][i])
        out[p + "mlp.c_fc.bias"] = _np(mlp["bi"][i])
        out[p + "mlp.c_proj.weight"] = _np(mlp["wo"][i])
        out[p + "mlp.c_proj.bias"] = _np(mlp["bo"][i])
        out[p + "ln_1.weight"] = _np(layers["ln1"]["scale"][i])
        out[p + "ln_1.bias"] = _np(layers["ln1"]["bias"][i])
        out[p + "ln_2.weight"] = _np(layers["ln2"]["scale"][i])
        out[p + "ln_2.bias"] = _np(layers["ln2"]["bias"][i])
    return out


def _export_llama(params: Dict, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    L = cfg.num_layers
    layers = params["layers"]
    out = {
        "model.embed_tokens.weight": _np(params["embed"]["tok"]),
        "model.norm.weight": _np(params["final_norm"]["scale"]),
    }
    if cfg.tie_embeddings:
        out["lm_head.weight"] = _np(params["embed"]["tok"])
    else:
        out["lm_head.weight"] = _np(params["lm_head"]["w"]).T
    attn, mlp = layers["attn"], layers["mlp"]
    for i in range(L):
        p = f"model.layers.{i}."
        # torch Linear stores (out, in); ours is (in, out)
        out[p + "self_attn.q_proj.weight"] = _np(attn["wq"][i]).T
        out[p + "self_attn.k_proj.weight"] = _np(attn["wk"][i]).T
        out[p + "self_attn.v_proj.weight"] = _np(attn["wv"][i]).T
        out[p + "self_attn.o_proj.weight"] = _np(attn["wo"][i]).T
        out[p + "mlp.gate_proj.weight"] = _np(mlp["wg"][i]).T
        out[p + "mlp.up_proj.weight"] = _np(mlp["wi"][i]).T
        out[p + "mlp.down_proj.weight"] = _np(mlp["wo"][i]).T
        out[p + "input_layernorm.weight"] = _np(layers["ln1"]["scale"][i])
        out[p + "post_attention_layernorm.weight"] = _np(layers["ln2"]["scale"][i])
    return out


def save_hf_checkpoint(save_dir: str, params: Dict, cfg: TransformerConfig,
                       architecture: str, hf_config=None) -> str:
    """Write an HF-loadable checkpoint directory: pytorch_model.bin (torch
    state dict, float32) plus config.json when an HF config object is
    given. Returns the state-dict path."""
    import os

    import torch

    os.makedirs(save_dir, exist_ok=True)
    state = {k: torch.from_numpy(np.ascontiguousarray(v.astype(np.float32)))
             for k, v in export_hf_state_dict(params, cfg, architecture).items()}
    path = os.path.join(save_dir, "pytorch_model.bin")
    torch.save(state, path)
    if hf_config is not None:
        hf_config.save_pretrained(save_dir)
    return path
