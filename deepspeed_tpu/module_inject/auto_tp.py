"""AutoTP fallback policy: convert an HF architecture with NO explicit
injection policy by inferring the layer structure from state-dict key
names and shapes.

Reference: ``deepspeed/module_inject/auto_tp.py`` — AutoTP walks an
unknown HF model, finds the linear layers, and shards them without a
hand-written policy. The TPU form goes one step further: it maps the
unknown checkpoint onto the unified ``models/transformer.py`` parameter
tree, after which ALL engine features (TP via logical-axis rules, int8,
KV-cache decode, flash prefill) apply exactly as for known policies.

Heuristics (decoder-only, pre-LN, the HF mainstream):

  - the per-layer key template is the ``(prefix, suffix)`` pair around an
    integer path segment with the most distinct indices;
  - attention projections by name (``q_proj``/``query``/…, fused
    ``query_key_value``/``c_attn`` split by (D, kvD, kvD));
  - MLP matrices by name (``gate/up/down``, ``fc1/fc2``,
    ``dense_h_to_4h``…) with shape confirmation (D->F vs F->D);
  - norms: ``input_layernorm``/``ln_1`` -> ln1,
    ``post_attention…``/``ln_2`` -> ln2; falls back to key order;
  - torch Linear stores (out, in) -> transposed; shape-checked where the
    dims disambiguate;
  - missing biases are synthesized as zeros when the config says
    ``use_bias`` (e.g. Qwen2: qkv biased, o/mlp not).

Not covered (each needs a real policy): encoder/post-LN stacks, ALiBi
(no config signal), per-head-interleaved fused qkv (GPT-NeoX — has a
policy), Conv1D fused qkv (GPT-2 — has a policy).
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger

_LAYER_RE = re.compile(r"^(.*?\.)(\d+)(\..+)$")

_Q_RE = re.compile(r"\b(q_proj|q_lin|query)\b|\.q\.", re.I)
_K_RE = re.compile(r"\b(k_proj|k_lin|key)\b|\.k\.", re.I)
_V_RE = re.compile(r"\b(v_proj|v_lin|value)\b|\.v\.", re.I)
_O_RE = re.compile(r"\b(o_proj|out_proj|out_lin|wo)\b", re.I)
_QKV_RE = re.compile(r"\b(query_key_value|qkv_proj|qkv|c_attn|Wqkv)\b", re.I)
_ATTN_SCOPE_RE = re.compile(r"\b(attn|attention|self_attn|self_attention)\b", re.I)
_MLP_SCOPE_RE = re.compile(r"\b(mlp|ffn|feed_forward|fc|dense_h_to_4h|dense_4h_to_h)\b", re.I)
_GATE_RE = re.compile(r"\b(gate_proj|w1|wg)\b", re.I)
_UP_RE = re.compile(r"\b(up_proj|fc1|fc_in|c_fc|wi|w3|dense_h_to_4h|lin1)\b", re.I)
_DOWN_RE = re.compile(r"\b(down_proj|fc2|fc_out|c_proj|w2|dense_4h_to_h|lin2)\b", re.I)
_LN1_RE = re.compile(r"\b(input_layernorm|ln_1|ln1|attention_norm|self_attn_layer_norm|"
                     r"pre_attention_layernorm|sa_layer_norm)\b", re.I)
_LN2_RE = re.compile(r"\b(post_attention_layernorm|ln_2|ln2|ffn_norm|final_layer_norm|"
                     r"post_layernorm|output_layer_norm)\b", re.I)
_TOK_RE = re.compile(r"\b(embed_tokens|wte|word_embeddings|tok_embeddings|embeddings\.word)\b", re.I)
_POS_RE = re.compile(r"\b(wpe|embed_positions|position_embeddings)\b", re.I)
_HEAD_RE = re.compile(r"\b(lm_head|embed_out|output_layer)\b", re.I)


def _np(t):
    if hasattr(t, "detach"):
        return t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _attr(cfg, names, default=None):
    for n in names:
        v = getattr(cfg, n, None)
        if v is not None:
            return v
    return default


def _layer_template(keys: List[str]) -> Tuple[str, int]:
    """Find (layer key prefix, num_layers) by majority vote over integer
    path segments."""
    counts: Dict[str, set] = {}
    for k in keys:
        m = _LAYER_RE.match(k)
        if m:
            counts.setdefault(m.group(1), set()).add(int(m.group(2)))
    if not counts:
        raise ValueError("AutoTP: no '<prefix>.<i>.<suffix>' layer keys found")
    prefix = max(counts, key=lambda p: len(counts[p]))
    idxs = counts[prefix]
    assert idxs == set(range(len(idxs))), f"non-contiguous layer indices under {prefix}"
    return prefix, max(idxs) + 1


class AutoTPPolicy:
    """Fallback policy instance bound to a probed state dict.

    Produced by :func:`auto_policy` (which needs the state dict to detect
    bias/GLU/norm structure); exposes the same ``config`` / ``params``
    surface as the explicit HFPolicy classes."""

    def __init__(self, state: Dict[str, Any]):
        self._keys = [k for k in state.keys() if k.endswith(("weight", "bias"))]
        self._layer_prefix, self._num_layers = _layer_template(self._keys)
        l0 = [k for k in self._keys
              if k.startswith(f"{self._layer_prefix}0.")]
        self._l0 = l0
        self._has_gate = any(_GATE_RE.search(k) for k in l0)
        self._qkv_bias = any(
            _ATTN_SCOPE_RE.search(k) and k.endswith(".bias")
            and (_Q_RE.search(k) or _QKV_RE.search(k)) for k in l0
        )
        self._any_bias = any(k.endswith(".bias") and "norm" not in k.lower()
                             and "ln" not in k.lower() for k in l0)

    def config(self, hf_config) -> TransformerConfig:
        D = _attr(hf_config, ("hidden_size", "n_embd", "d_model"))
        L = _attr(hf_config, ("num_hidden_layers", "n_layer", "num_layers"))
        nh = _attr(hf_config, ("num_attention_heads", "n_head", "num_heads"))
        if D is None or L is None or nh is None:
            raise ValueError("AutoTP: config lacks hidden/layers/heads attributes")
        if int(L) != self._num_layers:
            raise ValueError(
                f"AutoTP: config says {L} layers, state dict has {self._num_layers}"
            )
        rms_eps = _attr(hf_config, ("rms_norm_eps",))
        ropeish = _attr(hf_config, ("rope_theta", "rotary_emb_base")) is not None or \
            _attr(hf_config, ("rotary_pct", "partial_rotary_factor")) is not None
        has_pos_embed = any(_POS_RE.search(k) for k in self._keys)
        act = str(_attr(hf_config, ("hidden_act", "activation_function"), "gelu")).lower()
        if act in ("silu", "swish") and self._has_gate:
            act = "silu_glu"
        elif act.startswith("gelu"):
            act = "gelu"
        elif act not in ("relu", "quick_gelu"):
            act = "gelu"
        tie = bool(_attr(hf_config, ("tie_word_embeddings",), False)) or \
            not any(_HEAD_RE.search(k) for k in self._keys)
        hd = D // nh
        rot_frac = _attr(hf_config, ("partial_rotary_factor", "rotary_pct"))
        rope_dim = int(rot_frac * hd) if rot_frac is not None else None
        parallel = bool(_attr(hf_config, ("use_parallel_residual", "parallel_attn"), False))
        return TransformerConfig(
            rope_dim=rope_dim,
            parallel_residual=parallel,
            vocab_size=_attr(hf_config, ("vocab_size",)),
            hidden_size=D,
            num_layers=int(L),
            num_heads=nh,
            num_kv_heads=_attr(hf_config, ("num_key_value_heads", "num_kv_heads")),
            ffn_hidden_size=_attr(hf_config, ("intermediate_size", "ffn_dim", "n_inner")),
            max_seq_len=_attr(hf_config, ("max_position_embeddings", "n_positions"), 2048),
            pos_embedding="rope" if (ropeish or not has_pos_embed) else "learned",
            norm_type="rmsnorm" if rms_eps is not None else "layernorm",
            activation=act,
            tie_embeddings=tie,
            use_bias=self._any_bias or self._qkv_bias,
            norm_eps=rms_eps if rms_eps is not None
            else _attr(hf_config, ("layer_norm_epsilon", "layer_norm_eps"), 1e-5),
            rope_theta=_attr(hf_config, ("rope_theta", "rotary_emb_base"), 10000.0),
        )

    # -- params mapping ----------------------------------------------------

    def _classify_layer_keys(self) -> Dict[str, str]:
        """suffix (after '<prefix>0.') -> slot tag, from layer-0 keys."""
        tags: Dict[str, str] = {}
        for k in self._l0:
            suffix = k[len(self._layer_prefix) + 2:]
            is_w = k.endswith(".weight")
            attn = bool(_ATTN_SCOPE_RE.search(k))
            if attn and _QKV_RE.search(k):
                tags[suffix] = "qkv_w" if is_w else "qkv_b"
            elif attn and _Q_RE.search(k):
                tags[suffix] = "wq" if is_w else "bq"
            elif attn and _K_RE.search(k):
                tags[suffix] = "wk" if is_w else "bk"
            elif attn and _V_RE.search(k):
                tags[suffix] = "wv" if is_w else "bv"
            elif attn and (_O_RE.search(k) or re.search(r"\bdense\b", k)):
                tags[suffix] = "wo" if is_w else "bo"
            elif _LN1_RE.search(k):
                tags[suffix] = "ln1_scale" if is_w else "ln1_bias"
            elif _LN2_RE.search(k):
                tags[suffix] = "ln2_scale" if is_w else "ln2_bias"
            elif _GATE_RE.search(k):
                tags[suffix] = "m_wg" if is_w else "m_bg"
            elif _UP_RE.search(k):
                tags[suffix] = "m_wi" if is_w else "m_bi"
            elif _DOWN_RE.search(k):
                tags[suffix] = "m_wo" if is_w else "m_bo"
            elif _MLP_SCOPE_RE.search(k):
                # generic MLP leaf with no up/down name hint — resolved by
                # shape in params() (torch Linear: up is (F, D), down (D, F))
                tags[suffix] = "m_unresolved_w" if is_w else "m_unresolved_b"
        return tags

    def params(self, state: Dict[str, Any], cfg: TransformerConfig) -> Dict:
        D, L = cfg.hidden_size, cfg.num_layers
        nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        F = cfg.ffn_size
        tags = self._classify_layer_keys()
        # resolve name-hint-less MLP leaves by layer-0 shape
        # (torch Linear: up stores (F, D), down stores (D, F))
        for suffix, tag in list(tags.items()):
            arr_shape = tuple(np.shape(state[f"{self._layer_prefix}0.{suffix}"]))
            if tag == "m_unresolved_w" and D != F:
                if arr_shape == (F, D):
                    tags[suffix] = "m_wi"
                elif arr_shape == (D, F):
                    tags[suffix] = "m_wo"
            elif tag == "m_unresolved_b":
                tags[suffix] = "m_bi" if arr_shape == (F,) else "m_bo"
        need = {"wo", "m_wi", "m_wo", "ln1_scale", "ln2_scale"}
        have = set(tags.values())
        if not ({"wq", "wk", "wv"} <= have or "qkv_w" in have) or not (need <= have):
            raise ValueError(
                f"AutoTP could not identify the layer structure: found {sorted(have)}"
            )
        if "m_wg" in have and ("m_bg" in have or "m_bi" in have):
            # the unified model's GLU branch has no gate/up bias terms —
            # silently dropping them would diverge from HF, so fail loudly
            # (this module's contract: structural mismatch errors at
            # conversion, never silent wrongness)
            raise ValueError(
                "AutoTP: GLU MLP with gate/up-projection biases is not "
                "representable by the unified model; this architecture "
                "needs an explicit policy"
            )

        def lk(suffix, i):
            return f"{self._layer_prefix}{i}.{suffix}"

        by_tag = {t: s for s, t in tags.items()}

        def stackW(tag, expect_in):
            """Stack L weight mats, transposing torch (out,in) -> (in,out);
            shape-checked against the expected input dim where possible."""
            mats = []
            for i in range(L):
                m = _np(state[lk(by_tag[tag], i)])
                if m.ndim != 2:
                    raise ValueError(f"AutoTP: {tag} is not 2-D")
                if m.shape[0] != expect_in or m.shape[1] == expect_in:
                    m = m.T  # torch Linear convention
                mats.append(m)
            return np.stack(mats)

        def stackB(tag, size):
            if tag in by_tag:
                return np.stack([_np(state[lk(by_tag[tag], i)]) for i in range(L)])
            return np.zeros((L, size), np.float32)  # synthesized (e.g. Qwen2 o_proj)

        attn: Dict[str, Any] = {}
        if "qkv_w" in have:
            fused = np.stack([_np(state[lk(by_tag["qkv_w"], i)]) for i in range(L)])
            if fused.shape[1] != D:  # (L, out, in) -> (L, in, out)
                fused = np.transpose(fused, (0, 2, 1))
            qd, kvd = nh * hd, nkv * hd
            attn["wq"], attn["wk"], attn["wv"] = (
                fused[:, :, :qd], fused[:, :, qd:qd + kvd], fused[:, :, qd + kvd:])
            if cfg.use_bias:
                if "qkv_b" in have:
                    fb = np.stack([_np(state[lk(by_tag["qkv_b"], i)]) for i in range(L)])
                    attn["bq"], attn["bk"], attn["bv"] = (
                        fb[:, :qd], fb[:, qd:qd + kvd], fb[:, qd + kvd:])
                else:
                    attn["bq"] = np.zeros((L, qd), np.float32)
                    attn["bk"] = np.zeros((L, kvd), np.float32)
                    attn["bv"] = np.zeros((L, kvd), np.float32)
        else:
            attn["wq"] = stackW("wq", D)
            attn["wk"] = stackW("wk", D)
            attn["wv"] = stackW("wv", D)
            if cfg.use_bias:
                attn["bq"] = stackB("bq", nh * hd)
                attn["bk"] = stackB("bk", nkv * hd)
                attn["bv"] = stackB("bv", nkv * hd)
        attn["wo"] = stackW("wo", nh * hd)
        if cfg.use_bias:
            attn["bo"] = stackB("bo", D)

        mlp: Dict[str, Any] = {
            "wi": stackW("m_wi", D),
            "wo": stackW("m_wo", cfg.ffn_size),
        }
        if "m_wg" in have:
            mlp["wg"] = stackW("m_wg", D)
        if cfg.use_bias:
            mlp["bi"] = stackB("m_bi", cfg.ffn_size)
            mlp["bo"] = stackB("m_bo", D)

        def norm(tag_scale, tag_bias):
            out = {"scale": np.stack([_np(state[lk(by_tag[tag_scale], i)]) for i in range(L)])}
            if cfg.norm_type != "rmsnorm" and tag_bias in by_tag:
                out["bias"] = np.stack([_np(state[lk(by_tag[tag_bias], i)]) for i in range(L)])
            return out

        tok_key = next(k for k in self._keys if _TOK_RE.search(k) and k.endswith("weight"))
        embed: Dict[str, Any] = {"tok": _np(state[tok_key])}
        if cfg.pos_embedding == "learned":
            pos_key = next(k for k in self._keys if _POS_RE.search(k) and k.endswith("weight"))
            embed["pos"] = _np(state[pos_key])

        params = {
            "embed": embed,
            "layers": {"attn": attn, "mlp": mlp,
                       "ln1": norm("ln1_scale", "ln1_bias"),
                       "ln2": norm("ln2_scale", "ln2_bias")},
        }
        # final norm: a top-level (non-layer) norm weight
        fin = [k for k in self._keys
               if not k.startswith(self._layer_prefix[:-1] + ".")
               and re.search(r"\b(norm|ln_f|final_layer_norm|layernorm)\b", k, re.I)
               and k.endswith("weight") and not _LAYER_RE.match(k)]
        if fin:
            params["final_norm"] = {"scale": _np(state[fin[0]])}
            bias_key = fin[0][:-len("weight")] + "bias"
            if cfg.norm_type != "rmsnorm" and bias_key in state:
                params["final_norm"]["bias"] = _np(state[bias_key])
        if not cfg.tie_embeddings:
            head_key = next(k for k in self._keys if _HEAD_RE.search(k) and k.endswith("weight"))
            params["lm_head"] = {"w": _np(state[head_key]).T}
        params = _align_to_abstract(params, cfg)
        logger.info(
            f"AutoTP fallback mapped {self._num_layers} layers "
            f"(prefix='{self._layer_prefix}', slots={sorted(have)})"
        )
        return params


_BIAS_LEAVES = {"bias", "bq", "bk", "bv", "bo", "bi", "bg", "coef_b", "b"}


def _align_to_abstract(params: Dict, cfg: TransformerConfig) -> Dict:
    """Match the converted tree against the model's abstract init tree:
    zero-fill missing bias leaves (e.g. Qwen2's rms norms under a
    use_bias=True config), and hard-error on shape mismatches or missing
    non-bias leaves — the engine derives shardings from the init tree, so
    a structural mismatch would fail later with a much worse message."""
    import jax

    from deepspeed_tpu.models import transformer as _tm

    abstract = jax.eval_shape(lambda rng: _tm.init(rng, cfg), jax.random.PRNGKey(0))

    def walk(abs_node, got_node, path):
        if isinstance(abs_node, dict):
            got_node = dict(got_node) if isinstance(got_node, dict) else {}
            out = {}
            for k, sub in abs_node.items():
                out[k] = walk(sub, got_node.get(k), path + (k,))
            return out
        leaf_name = path[-1]
        if got_node is None:
            if leaf_name in _BIAS_LEAVES:
                return np.zeros(abs_node.shape, np.float32)
            raise ValueError(f"AutoTP: missing non-bias leaf {'.'.join(path)} "
                             f"(expected shape {abs_node.shape})")
        if tuple(got_node.shape) != tuple(abs_node.shape):
            raise ValueError(
                f"AutoTP: shape mismatch at {'.'.join(path)}: "
                f"mapped {got_node.shape}, model expects {abs_node.shape}"
            )
        return got_node

    return walk(abstract, params, ())


def auto_policy(state: Dict[str, Any]) -> AutoTPPolicy:
    """Build the fallback policy from a model's state dict."""
    return AutoTPPolicy(state)
