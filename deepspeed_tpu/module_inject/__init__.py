from deepspeed_tpu.module_inject.policies import (  # noqa: F401
    config_from_hf,
    convert_hf_model,
    partition_rules,
    policy_for,
)
